"""DNS frame parser + stitcher (the binary protocol).

Ref: protocols/dns/parse.cc (wire-format header + name decompression +
A/AAAA/CNAME record extraction), protocols/dns/stitcher.cc (header/query/
answers rendered to JSON; response-led txid matching bounded by
timestamps), dns_table.h kDNSElements (req_header/req_body/resp_header/
resp_body string columns).

DNS messages are datagram-framed: one UDP payload = one message, so
parse_frame consumes whole payloads (the reference parses per-event the
same way).
"""

from __future__ import annotations

import dataclasses
import json
import struct

from pixie_tpu.protocols import base
from pixie_tpu.protocols.base import MessageType, ParseState, Record

_HDR = struct.Struct(">HHHHHH")

TYPE_A = 1
TYPE_NS = 2
TYPE_CNAME = 5
TYPE_AAAA = 28
_TYPE_NAMES = {TYPE_A: "A", TYPE_AAAA: "AAAA", TYPE_CNAME: "CNAME", TYPE_NS: "NS"}


@dataclasses.dataclass
class ResourceRecord:
    name: str = ""
    rtype: int = 0
    cname: str = ""
    addr: str = ""


@dataclasses.dataclass
class Frame(base.Frame):
    """Ref: dns::Frame (types.h) — header fields + parsed records."""

    txid: int = 0
    flags: int = 0
    num_queries: int = 0
    num_answers: int = 0
    num_auth: int = 0
    num_addl: int = 0
    queries: list = dataclasses.field(default_factory=list)
    answers: list = dataclasses.field(default_factory=list)

    @property
    def qr(self) -> int:
        return (self.flags >> 15) & 1

    @property
    def rcode(self) -> int:
        return self.flags & 0xF

    def header_json(self) -> str:
        """Ref: HeaderToJSONString (stitcher.cc:37)."""
        f = self.flags
        return json.dumps(
            {
                "txid": self.txid,
                "qr": (f >> 15) & 1,
                "opcode": (f >> 11) & 0xF,
                "aa": (f >> 10) & 1,
                "tc": (f >> 9) & 1,
                "rd": (f >> 8) & 1,
                "ra": (f >> 7) & 1,
                "ad": (f >> 5) & 1,
                "cd": (f >> 4) & 1,
                "rcode": f & 0xF,
                "num_queries": self.num_queries,
                "num_answers": self.num_answers,
                "num_auth": self.num_auth,
                "num_addl": self.num_addl,
            }
        )


def _decode_name(buf: bytes, pos: int, depth: int = 0) -> tuple[str, int]:
    """DNS name with compression pointers. Returns (name, next position).
    Raises ValueError on malformed/looping names."""
    if depth > 16:
        raise ValueError("dns name compression loop")
    labels = []
    while True:
        if pos >= len(buf):
            raise ValueError("dns name past end")
        n = buf[pos]
        if n == 0:
            pos += 1
            break
        if n & 0xC0 == 0xC0:
            if pos + 2 > len(buf):
                raise ValueError("dns pointer past end")
            ptr = ((n & 0x3F) << 8) | buf[pos + 1]
            if ptr >= pos:
                raise ValueError("dns forward pointer")
            tail, _ = _decode_name(buf, ptr, depth + 1)
            labels.append(tail)
            pos += 2
            break
        pos += 1
        if pos + n > len(buf):
            raise ValueError("dns label past end")
        labels.append(buf[pos : pos + n].decode("latin-1"))
        pos += n
    return ".".join(l for l in labels if l), pos


def _addr_str(rtype: int, rdata: bytes) -> str:
    import ipaddress

    if rtype == TYPE_A and len(rdata) == 4:
        return str(ipaddress.IPv4Address(rdata))
    if rtype == TYPE_AAAA and len(rdata) == 16:
        return str(ipaddress.IPv6Address(rdata))
    return ""


class DnsParser(base.ProtocolParser):
    name = "dns"

    def find_frame_boundary(self, msg_type, buf: bytes, start: int) -> int:
        # Datagram framing: a failed parse drops the datagram; there is no
        # in-stream resync (matches the reference's per-event parsing).
        return -1

    def parse_frame(
        self,
        msg_type: MessageType,
        buf: bytes,
        conn_closed: bool = False,
        state=None,
    ):
        if len(buf) < _HDR.size:
            return ParseState.NEEDS_MORE_DATA, 0, None
        txid, fl, qd, an, ns, ar = _HDR.unpack_from(buf, 0)
        frame = Frame(
            txid=txid,
            flags=fl,
            num_queries=qd,
            num_answers=an,
            num_auth=ns,
            num_addl=ar,
        )
        is_resp = (fl >> 15) & 1
        if (msg_type == MessageType.RESPONSE) != bool(is_resp):
            return ParseState.INVALID, 0, None
        pos = _HDR.size
        try:
            for _ in range(qd):
                name, pos = _decode_name(buf, pos)
                if pos + 4 > len(buf):
                    raise ValueError("query past end")
                qtype = struct.unpack_from(">H", buf, pos)[0]
                pos += 4
                frame.queries.append(
                    ResourceRecord(name=name, rtype=qtype)
                )
            for _ in range(an):
                name, pos = _decode_name(buf, pos)
                if pos + 10 > len(buf):
                    raise ValueError("answer past end")
                rtype, _cls, _ttl, rdlen = struct.unpack_from(
                    ">HHIH", buf, pos
                )
                pos += 10
                rdata = buf[pos : pos + rdlen]
                if len(rdata) != rdlen:
                    raise ValueError("rdata past end")
                rec = ResourceRecord(name=name, rtype=rtype)
                if rtype == TYPE_CNAME:
                    rec.cname, _ = _decode_name(buf, pos)
                else:
                    rec.addr = _addr_str(rtype, rdata)
                frame.answers.append(rec)
                pos += rdlen
        except ValueError:
            return ParseState.INVALID, 0, None
        # auth/additional sections are skipped (not surfaced in the table)
        return ParseState.SUCCESS, len(buf), frame

    def stitch(self, requests: list, responses: list, state=None):
        """Response-led txid matching bounded by timestamps
        (ref: dns StitchFrames, stitcher.cc:175-219)."""
        records: list[Record] = []
        errors = 0
        consumed: set[int] = set()
        for resp in responses:
            found = False
            for i, req in enumerate(requests):
                if i in consumed:
                    continue
                if req.timestamp_ns > resp.timestamp_ns:
                    break
                if req.txid == resp.txid:
                    records.append(Record(req=req, resp=resp))
                    consumed.add(i)
                    found = True
                    break
            if not found:
                errors += 1
        keep_reqs = [
            r for i, r in enumerate(requests) if i not in consumed
        ]
        return records, errors, keep_reqs, []


def _queries_json(frame: Frame) -> str:
    return json.dumps(
        {
            "queries": [
                {"name": q.name, "type": _TYPE_NAMES.get(q.rtype, "")}
                for q in frame.queries
            ]
        }
    )


def _answers_json(frame: Frame) -> str:
    answers = []
    for a in frame.answers:
        if a.rtype == TYPE_CNAME:
            answers.append(
                {
                    "name": a.name,
                    "type": _TYPE_NAMES.get(a.rtype, ""),
                    "cname": a.cname,
                }
            )
        else:
            answers.append(
                {
                    "name": a.name,
                    "type": _TYPE_NAMES.get(a.rtype, ""),
                    "addr": a.addr,
                }
            )
    return json.dumps({"answers": answers})


def record_to_row(
    record: Record,
    upid: str,
    remote_addr: str,
    remote_port: int,
    trace_role: int,
) -> dict:
    """A dns_events row (ref: dns_table.h kDNSElements)."""
    req, resp = record.req, record.resp
    return {
        "time_": req.timestamp_ns,
        "upid": upid,
        "remote_addr": remote_addr,
        "remote_port": remote_port,
        "trace_role": int(trace_role),
        "req_header": req.header_json(),
        "req_body": _queries_json(req),
        "resp_header": resp.header_json(),
        "resp_body": _answers_json(resp),
        "latency": max(resp.timestamp_ns - req.timestamp_ns, 0),
    }
