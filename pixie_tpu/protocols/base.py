"""Protocol framework: stream reassembly, parser interface, conn tracking.

Ref mapping:
- ``DataStreamBuffer`` ≙ protocols/common/data_stream_buffer.{h,cc}
  (AlwaysContiguous impl): byte chunks arrive tagged with an absolute
  stream position + timestamp; the contiguous head is handed to the
  parser; a gap larger than the buffer allowance fast-forwards past the
  missing bytes (counted as a data-loss event).
- ``ProtocolParser`` ≙ the per-protocol template trio in
  protocols/common/interface.h — find_frame_boundary / parse_frame /
  stitch.
- ``ConnTracker`` ≙ conn_tracker.h:88's per-connection state machine:
  two DataStreams (send/recv), role-based request/response assignment,
  ProcessToRecords = parse both streams, stitch, emit records.
"""

from __future__ import annotations

import bisect
import dataclasses
import enum
import threading
from typing import Any, Optional

from pixie_tpu.utils import metrics_registry
from pixie_tpu.utils.config import define_flag, flags

define_flag(
    "protocol_stream_gap_limit",
    1 << 20,
    help_="Bytes a stream buffer may hold waiting for a gap to fill "
    "before fast-forwarding past the missing data "
    "(ref: datastream buffer size limits).",
)
define_flag(
    "ingest_robustness",
    True,
    help_="Master gate for the r24 overload-proof ingest plane: "
    "per-tracker byte budgets with oldest-chunk eviction, the global "
    "ingest budget, the shedding ladder, parser quarantine, and the "
    "exact per-reason drop ledger. Off restores the unbounded legacy "
    "path (the <1% disabled-overhead gate in "
    "tools/microbench_fault_overhead.py measures that path).",
)
define_flag(
    "ingest_stream_buffer_bytes",
    1 << 20,
    help_="Per-direction ConnTracker byte budget (contiguous head + "
    "pending out-of-order chunks). Exceeding it evicts oldest head "
    "bytes, attributed to the 'evict' ledger cause (ref: the "
    "reference's DataStreamBuffer size limit + eviction posture).",
)

_M = metrics_registry()
_GAP_SKIPS = _M.counter(
    "protocol_stream_gap_skips_total",
    "Stream gaps fast-forwarded (missing capture data).",
)
_PARSE_ERRORS = _M.counter(
    "protocol_parse_errors_total", "Frames that failed protocol parsing."
)


class ParseState(enum.Enum):
    # Ref: src/stirling/utils/parse_state.h
    SUCCESS = "success"
    NEEDS_MORE_DATA = "needs_more_data"
    INVALID = "invalid"
    IGNORED = "ignored"


class MessageType(enum.Enum):
    # Ref: message_type_t in bcc_bpf_intf/common.h
    REQUEST = "request"
    RESPONSE = "response"


class TraceRole(enum.IntEnum):
    # Ref: endpoint_role_t — numeric values surface in the trace_role column.
    UNKNOWN = 0
    CLIENT = 1
    SERVER = 2


@dataclasses.dataclass
class Frame:
    """Base parsed frame (ref: FrameBase in common/event_parser.h)."""

    timestamp_ns: int = 0


@dataclasses.dataclass
class Record:
    """A stitched request/response pair."""

    req: Any = None
    resp: Any = None


class DataStreamBuffer:
    """Reassembles a directional byte stream from positioned chunks.

    Chunks may arrive out of order (kernel perf buffers do); each carries
    (stream position, bytes, timestamp). ``head()`` exposes the contiguous
    prefix; ``consume(n)`` advances past parsed bytes; ``timestamp_at``
    answers "when did the byte at this position arrive" for frame
    timestamping (ref: data_stream_buffer.h position/timestamp API).
    """

    def __init__(
        self,
        gap_limit: Optional[int] = None,
        byte_budget: Optional[int] = None,
        ledger: Optional[dict] = None,
    ):
        self._chunks: dict[int, tuple[bytes, int]] = {}  # pos -> (data, ts)
        self._pos = 0  # stream position of buf start
        self._buf = bytearray()
        self._ts_marks: list[tuple[int, int]] = []  # (pos, ts), sorted
        self._gap_limit = (
            gap_limit
            if gap_limit is not None
            else flags.protocol_stream_gap_limit
        )
        # r24 bounded memory: head+pending may never exceed byte_budget
        # (oldest head bytes evict first, ledger cause 'evict'). The gap
        # allowance is clamped under the budget so pending out-of-order
        # chunks can't exceed it either (_assemble fast-forwards first).
        self._byte_budget = byte_budget
        if byte_budget is not None:
            self._gap_limit = min(self._gap_limit, byte_budget)
        # r24 event-disposition ledger: when given (a caller-owned dict,
        # shared by both directions of a tracker and guarded by the
        # tracker's lock), every add() is ONE capture event, attributed
        # to exactly one cause when its FINAL byte leaves the buffer:
        # parsed / parsed_meta / resync / gap_skip / evict / drain — or
        # stale_dup immediately if it duplicates consumed bytes. The
        # conservation law `events in == events attributed + events
        # pending` is exact; the soak gate builds on it.
        self._ledger = ledger
        self._event_ends: list[int] = []  # sorted event end positions
        self.gap_skips = 0
        self.evictions = 0

    def add(self, pos: int, data: bytes, timestamp_ns: int) -> None:
        led = self._ledger
        if pos + len(data) <= self._pos:
            if led is not None:
                led["stale_dup"] = led.get("stale_dup", 0) + 1
            return  # duplicate of already-consumed bytes
        if led is not None:
            bisect.insort(self._event_ends, pos + len(data))
        self._chunks[pos] = (bytes(data), timestamp_ns)
        self._assemble()
        if self._byte_budget is not None:
            self._enforce_budget()

    def _enforce_budget(self) -> None:
        """Evict oldest contiguous head bytes until head+pending fits the
        budget. _assemble already fast-forwarded any over-allowance gap
        (gap_limit <= byte_budget), so head eviction alone suffices."""
        pending = sum(len(d) for d, _ in self._chunks.values())
        over = len(self._buf) + pending - self._byte_budget
        if over > 0:
            k = min(over, len(self._buf))
            if k:
                self.evictions += 1
                self.consume(k, "evict")

    def _assemble(self) -> None:
        progressed = True
        while progressed:
            progressed = False
            end = self._pos + len(self._buf)
            for pos in sorted(self._chunks):
                data, ts = self._chunks[pos]
                if pos + len(data) <= end:
                    del self._chunks[pos]  # fully stale
                    progressed = True
                elif pos <= end:
                    take = data[end - pos :]
                    self._ts_marks.append((end, ts))
                    self._buf.extend(take)
                    del self._chunks[pos]
                    progressed = True
                    break
        # Gap handling: if pending out-of-order data exceeds the allowance,
        # fast-forward to the earliest pending chunk (data loss).
        pending = sum(len(d) for d, _ in self._chunks.values())
        if pending > self._gap_limit and self._chunks:
            nxt = min(self._chunks)
            if nxt > self._pos + len(self._buf):
                self.gap_skips += 1
                _GAP_SKIPS.inc()
                self._pos = nxt
                self._buf.clear()
                if self._ledger is not None:
                    self._attribute("gap_skip")
                self._assemble()

    def head(self) -> bytes:
        return bytes(self._buf)

    def byte_size(self) -> int:
        """Buffered bytes: contiguous head + pending out-of-order chunks
        (the quantity the r24 byte budgets bound)."""
        return len(self._buf) + sum(
            len(d) for d, _ in self._chunks.values()
        )

    def drain(self, cause: str = "drain") -> None:
        """Discard everything buffered (contiguous head AND pending
        out-of-order chunks) — used when the connection closed and the
        bytes can never complete a frame. ``cause`` names the ledger
        bucket the still-unattributed events land in (quarantine and
        idle disposal pass their own)."""
        end = self._pos + len(self._buf)
        for pos, (data, _) in self._chunks.items():
            end = max(end, pos + len(data))
        self._chunks.clear()
        self._buf.clear()
        self._pos = end
        if self._ledger is not None and self._event_ends:
            led = self._ledger
            led[cause] = led.get(cause, 0) + len(self._event_ends)
            self._event_ends.clear()

    def position(self) -> int:
        return self._pos

    def consume(self, n: int, cause: str = "parsed") -> None:
        assert 0 <= n <= len(self._buf)
        self._pos += n
        del self._buf[:n]
        self._ts_marks = [
            (p, t) for p, t in self._ts_marks if p >= self._pos
        ] or self._ts_marks[-1:]
        if self._ledger is not None:
            self._attribute(cause)

    def _attribute(self, cause: str) -> None:
        """Attribute every event whose final byte is now behind the
        stream position to ``cause`` — each event lands in exactly one
        bucket, which is what makes the soak's accounting invariant
        exact rather than approximate."""
        i = bisect.bisect_right(self._event_ends, self._pos)
        if i:
            led = self._ledger
            led[cause] = led.get(cause, 0) + i
            del self._event_ends[:i]

    def timestamp_at(self, pos: int) -> int:
        """Arrival timestamp of the chunk covering stream position pos."""
        best = 0
        for p, t in self._ts_marks:
            if p <= pos:
                best = t
            else:
                break
        return best


class ProtocolParser:
    """Per-protocol behavior (ref: common/interface.h template trio)."""

    name = "base"

    def find_frame_boundary(
        self, msg_type: MessageType, buf: bytes, start: int
    ) -> int:
        """Position of a plausible frame start > start, or -1."""
        raise NotImplementedError

    def new_state(self):
        """Fresh per-connection protocol state shared by both directions
        (ref: each protocol's StateWrapper in its types.h). None when the
        protocol needs none. Passed to parse_frame and stitch."""
        return None

    def on_resync(self, msg_type: MessageType, state) -> None:
        """Called when a direction hits INVALID and resyncs: a frame was
        lost, so any cross-direction bookkeeping (e.g. HTTP's request-
        method FIFO) may be desynchronized and should degrade safely."""

    def parse_frame(
        self,
        msg_type: MessageType,
        buf: bytes,
        conn_closed: bool = False,
        state=None,
    ):
        """(ParseState, bytes_consumed, frame_or_None). ``conn_closed``
        tells parsers the stream has ended: protocols with close-delimited
        payloads (HTTP responses lacking both Content-Length and
        Transfer-Encoding, ref http/parse.cc ParseResponseBody Case 4) may
        then emit the buffered remainder as the body instead of waiting.
        ``state`` is the connection's shared protocol state (new_state)."""
        raise NotImplementedError

    def stitch(self, requests: list, responses: list, state=None):
        """(records, error_count, requests_kept, responses_kept)."""
        raise NotImplementedError


class _DataStream:
    """One direction of a connection: buffer + parsed-frame deque
    (ref: data_stream.h:50)."""

    def __init__(
        self,
        parser: ProtocolParser,
        msg_type: MessageType,
        byte_budget: Optional[int] = None,
        ledger: Optional[dict] = None,
    ):
        self.buffer = DataStreamBuffer(
            byte_budget=byte_budget, ledger=ledger
        )
        self.frames: list = []
        self.frames_parsed = 0  # completed messages appended, ever
        self._parser = parser
        self._msg_type = msg_type
        self._last_ts = 0

    def parse_loop(self, conn_closed: bool = False, proto_state=None) -> None:
        """Parse as many frames as the contiguous head allows
        (ref: event_parser.h ParseFramesLoop)."""
        while True:
            buf = self.buffer.head()
            if not buf:
                return
            state, consumed, frame = self._parser.parse_frame(
                self._msg_type,
                buf,
                conn_closed=conn_closed,
                state=proto_state,
            )
            if state == ParseState.SUCCESS:
                if frame is None:
                    # Frame consumed but no message completed yet (e.g. an
                    # HTTP/2 SETTINGS frame, or a DATA frame mid-stream).
                    self.buffer.consume(consumed, "parsed_meta")
                    continue
                if frame.timestamp_ns == 0:
                    # Frames within one captured chunk share its arrival
                    # timestamp; nudge them monotonic so stitchers see the
                    # in-stream order (pipelined bursts stay ordered).
                    frame.timestamp_ns = max(
                        self.buffer.timestamp_at(self.buffer.position()),
                        self._last_ts + 1,
                    )
                self._last_ts = frame.timestamp_ns
                self.frames.append(frame)
                self.frames_parsed += 1
                self.buffer.consume(consumed, "parsed")
            elif state == ParseState.NEEDS_MORE_DATA:
                return
            else:  # INVALID: resync at the next plausible boundary
                _PARSE_ERRORS.inc(protocol=self._parser.name)
                self._parser.on_resync(self._msg_type, proto_state)
                nxt = self._parser.find_frame_boundary(
                    self._msg_type, buf, 1
                )
                self.buffer.consume(len(buf) if nxt < 0 else nxt, "resync")


class ConnTracker:
    """Per-connection protocol state machine (ref: conn_tracker.h:88).

    ``role`` decides which direction carries requests: a CLIENT conn
    sends requests; a SERVER conn receives them."""

    def __init__(
        self,
        parser: ProtocolParser,
        upid: str = "",
        remote_addr: str = "",
        remote_port: int = 0,
        role: TraceRole = TraceRole.CLIENT,
        byte_budget: Optional[int] = None,
        track_drops: bool = False,
    ):
        self.parser = parser
        self.upid = upid
        self.remote_addr = remote_addr
        self.remote_port = remote_port
        self.role = TraceRole(role)
        # r24: the event-disposition ledger shared by both direction
        # buffers. Guarded by self.lock — the feeder thread adds events
        # while the transfer thread parses/drains. The connector
        # delta-syncs it each transfer tick (copy + clear, identity kept).
        self.ledger: Optional[dict] = {} if track_drops else None
        self.lock = threading.Lock()
        self.last_activity_ns = 0  # stamped by the connector on events
        self.quarantined = False  # breaker-open: drop incoming events
        self.retired = False  # set (under lock) when the connector GCs
        # send stream carries requests for clients, responses for servers.
        if self.role == TraceRole.SERVER:
            self.send = _DataStream(
                parser, MessageType.RESPONSE, byte_budget, self.ledger
            )
            self.recv = _DataStream(
                parser, MessageType.REQUEST, byte_budget, self.ledger
            )
        else:
            self.send = _DataStream(
                parser, MessageType.REQUEST, byte_budget, self.ledger
            )
            self.recv = _DataStream(
                parser, MessageType.RESPONSE, byte_budget, self.ledger
            )
        self.protocol_state = parser.new_state()
        self.closed = False
        # Frame-conservation counters (law B of the soak invariant):
        # frames_parsed (per stream) == frames_stitched + frames_drained
        # + frames still pending in the stream deques.
        self.frames_stitched = 0
        self.frames_drained = 0
        self.records_stitched = 0
        # One full process cycle of grace after close before draining:
        # capture sources can deliver a conn's final data chunks after its
        # close event (ref: ConnTracker::MarkForDeath iteration countdown).
        self._close_grace = 1

    def add_send(self, pos: int, data: bytes, timestamp_ns: int) -> None:
        self.send.buffer.add(pos, data, timestamp_ns)

    def add_recv(self, pos: int, data: bytes, timestamp_ns: int) -> None:
        self.recv.buffer.add(pos, data, timestamp_ns)

    def process_to_records(self) -> list[Record]:
        """Parse pending bytes and stitch (ref: ConnTracker::
        ProcessToRecords)."""
        if self.role == TraceRole.SERVER:
            req_stream, resp_stream = self.recv, self.send
        else:
            req_stream, resp_stream = self.send, self.recv
        # Requests first: response parsing consults the request-method FIFO
        # in the protocol state (HEAD/CONNECT responses are bodiless).
        req_stream.parse_loop(
            conn_closed=self.closed, proto_state=self.protocol_state
        )
        resp_stream.parse_loop(
            conn_closed=self.closed, proto_state=self.protocol_state
        )
        before = len(req_stream.frames) + len(resp_stream.frames)
        records, errors, req_keep, resp_keep = self.parser.stitch(
            req_stream.frames, resp_stream.frames, self.protocol_state
        )
        req_stream.frames, resp_stream.frames = req_keep, resp_keep
        # Law B bookkeeping: every parsed frame either got consumed by
        # this stitch round, is still pending in a deque, or will be
        # drained at close — three exhaustive, exclusive fates.
        self.frames_stitched += before - (len(req_keep) + len(resp_keep))
        self.records_stitched += len(records)
        if errors:
            _PARSE_ERRORS.inc(errors, protocol=self.parser.name)
        if self.closed:
            if self._close_grace > 0:
                self._close_grace -= 1
            else:
                # The stream ended and the grace cycle for late-arriving
                # chunks has passed: bytes still unparseable (truncated
                # transfers) and unpaired frames can never complete —
                # drain both directions so the connector can GC this
                # tracker (ref: ConnTracker::MarkForDeath + countdown).
                self.drain_all()
        return records

    def drain_all(self, cause: str = "drain") -> None:
        """Discard both directions' buffered bytes and pending frames,
        attributing still-unattributed events to ``cause`` (close drain,
        quarantine, or idle disposal)."""
        for s in (self.send, self.recv):
            s.buffer.drain(cause)
            self.frames_drained += len(s.frames)
            s.frames.clear()

    def byte_size(self) -> int:
        """Total buffered bytes across both directions."""
        return self.send.buffer.byte_size() + self.recv.buffer.byte_size()

    def frames_pending(self) -> int:
        return len(self.send.frames) + len(self.recv.frames)

    def frames_parsed(self) -> int:
        return self.send.frames_parsed + self.recv.frames_parsed

    def events_pending(self) -> int:
        """Capture events not yet attributed to a ledger cause."""
        return len(self.send.buffer._event_ends) + len(
            self.recv.buffer._event_ends
        )


def stitch_by_timestamp(requests: list, responses: list):
    """The generic timestamp-order merge stitcher
    (ref: common/timestamp_stitcher.h:47 StitchMessagesWithTimestampOrder):
    each response pairs with the latest request older than it; responses
    with no older request are dropped (counted as errors); unconsumed
    requests are kept for the next round."""
    records: list[Record] = []
    errors = 0
    cur_req = None
    ri = 0
    for resp in responses:
        while ri < len(requests) and (
            requests[ri].timestamp_ns <= resp.timestamp_ns
        ):
            cur_req = requests[ri]  # newest older request wins
            ri += 1
        if cur_req is None:
            errors += 1
            continue
        records.append(Record(req=cur_req, resp=resp))
        cur_req = None
    return records, errors, requests[ri:], []
