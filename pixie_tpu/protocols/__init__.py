"""Protocol frame parsing + stitching — Pixie's actual product surface.

Ref: src/stirling/source_connectors/socket_tracer/protocols/ — the
userspace half of the socket tracer: per-connection byte-stream
reassembly (common/data_stream_buffer.*), per-protocol frame parsers
(http/parse.cc, dns/parse.cc, ...), and request/response stitchers
(http/stitcher.cc, common/timestamp_stitcher.h). eBPF is only the capture
mechanism; these transforms are pure userspace and run unchanged on TPU
hosts over replayed or synthetic socket events.
"""

from pixie_tpu.protocols.base import (
    ConnTracker,
    DataStreamBuffer,
    MessageType,
    ParseState,
    Record,
    TraceRole,
)
from pixie_tpu.protocols import dns, http, mysql

__all__ = [
    "ConnTracker",
    "DataStreamBuffer",
    "MessageType",
    "ParseState",
    "Record",
    "TraceRole",
    "dns",
    "http",
    "mysql",
]
