"""MySQL frame parser + stitcher.

Ref: protocols/mysql/{parse.cc,types.h,stitcher.cc,handler.cc} — packets
are 3-byte little-endian length + sequence id + payload; a request is a
sequence-0 packet whose first payload byte is a valid command; responses
are packet bundles interpreted per command (OK 0x00 / ERR 0xff / EOF 0xfe
/ resultsets with column definitions and row packets). Output rows match
mysql_table.h kMySQLElements (req_cmd, req_body, resp_status, resp_body,
latency).

The command set, OK/ERR/EOF/resultset framing, and prepared-statement
stitching are covered: STMT_PREPARE responses register (stmt_id -> query,
param count) in per-connection state, STMT_EXECUTE decodes the binary
parameter values and inflates them into the query's '?' placeholders
(stitcher.cc HandleStmtExecuteRequest), STMT_CLOSE evicts.
"""

from __future__ import annotations

import dataclasses
import struct

from pixie_tpu.protocols import base
from pixie_tpu.protocols.base import MessageType, ParseState, Record

HEADER_LEN = 4
MAX_PACKET = (1 << 24) - 1

# ref: types.h Command enum
COMMANDS = {
    0x01: "Quit",
    0x02: "InitDB",
    0x03: "Query",
    0x04: "FieldList",
    0x05: "CreateDB",
    0x06: "DropDB",
    0x07: "Refresh",
    0x08: "Shutdown",
    0x09: "Statistics",
    0x0A: "ProcessInfo",
    0x0C: "ProcessKill",
    0x0D: "Debug",
    0x0E: "Ping",
    0x11: "ChangeUser",
    0x16: "StmtPrepare",
    0x17: "StmtExecute",
    0x18: "StmtSendLongData",
    0x19: "StmtClose",
    0x1A: "StmtReset",
    0x1B: "SetOption",
    0x1C: "StmtFetch",
    0x1F: "ResetConnection",
}
# Commands whose body is a single string argument (ref: handler.cc).
_STRING_BODY = {0x02, 0x03, 0x05, 0x06, 0x16}
# Commands with no response at all (ref: handler.cc kNoResponse).
NO_RESPONSE_CMDS = {0x01, 0x18, 0x19}

RESP_UNKNOWN, RESP_NONE, RESP_OK, RESP_ERR = 0, 1, 2, 3  # ref: RespStatus

COM_STMT_PREPARE, COM_STMT_EXECUTE, COM_STMT_CLOSE = 0x16, 0x17, 0x19


class MysqlState:
    """Per-connection prepared-statement map (ref: mysql::State's
    prepared_statements, types.h — resolves COM_STMT_EXECUTE back to the
    prepared query text with arguments inflated)."""

    def __init__(self):
        # stmt_id -> {"query": str, "num_params": int, "types": list|None}
        self.prepared: dict[int, dict] = {}


@dataclasses.dataclass
class Packet(base.Frame):
    """One wire packet (ref: mysql::Packet, types.h:60)."""

    sequence_id: int = 0
    msg: bytes = b""

    @property
    def is_ok(self) -> bool:
        # ref: packet_utils.cc IsOKPacket (header 0x00, len >= 7... relaxed)
        return len(self.msg) >= 1 and self.msg[0] == 0x00 and len(self.msg) >= 7

    @property
    def is_err(self) -> bool:
        return len(self.msg) >= 3 and self.msg[0] == 0xFF

    @property
    def is_eof(self) -> bool:
        return len(self.msg) < 9 and len(self.msg) >= 1 and self.msg[0] == 0xFE


@dataclasses.dataclass
class MysqlRecord(Record):
    """Record with an optional resolved request text (prepared-statement
    EXECUTEs carry the query with params inflated, ref: stitcher.cc
    HandleStmtExecuteRequest)."""

    req_text: str = ""


class MysqlParser(base.ProtocolParser):
    name = "mysql"

    def new_state(self):
        return MysqlState()

    def find_frame_boundary(self, msg_type, buf: bytes, start: int) -> int:
        # ref: parse.cc FindFrameBoundary — scan for a plausible header:
        # requests restart at sequence id 0 with a valid command byte.
        for i in range(start, len(buf) - HEADER_LEN):
            length = int.from_bytes(buf[i : i + 3], "little")
            seq = buf[i + 3]
            if length == 0 or length > MAX_PACKET:
                continue
            if msg_type == MessageType.REQUEST:
                if seq == 0 and i + HEADER_LEN < len(buf) and (
                    buf[i + HEADER_LEN] in COMMANDS
                ):
                    return i
            else:
                if seq != 0:
                    return i
        return -1

    def parse_frame(
        self,
        msg_type: MessageType,
        buf: bytes,
        conn_closed: bool = False,
        state=None,
    ):
        if len(buf) < HEADER_LEN:
            return ParseState.NEEDS_MORE_DATA, 0, None
        length = int.from_bytes(buf[:3], "little")
        seq = buf[3]
        if length > MAX_PACKET:
            return ParseState.INVALID, 0, None
        if len(buf) < HEADER_LEN + length:
            return ParseState.NEEDS_MORE_DATA, 0, None
        msg = buf[HEADER_LEN : HEADER_LEN + length]
        if msg_type == MessageType.REQUEST:
            # Requests are command packets at sequence 0.
            if seq != 0 or not msg or msg[0] not in COMMANDS:
                return ParseState.INVALID, 0, None
        frame = Packet(sequence_id=seq, msg=bytes(msg))
        return ParseState.SUCCESS, HEADER_LEN + length, frame

    def stitch(self, requests: list, responses: list, state=None):
        """Bundle responses per request (ref: stitcher.cc StitchFrames:
        timestamp-bounded, sequence-contiguous response bundles handed to
        per-command handlers)."""
        records: list[Record] = []
        errors = 0
        ri = 0
        qi = 0
        while qi < len(requests):
            req = requests[qi]
            nxt_ts = (
                requests[qi + 1].timestamp_ns
                if qi + 1 < len(requests)
                else None
            )
            # Drop stale responses that pre-date this request (ref:
            # SyncRespQueue).
            while ri < len(responses) and (
                responses[ri].timestamp_ns < req.timestamp_ns
            ):
                ri += 1
                errors += 1
            cmd = req.msg[0]
            if cmd in NO_RESPONSE_CMDS:
                if cmd == COM_STMT_CLOSE and state is not None and (
                    len(req.msg) >= 5
                ):
                    state.prepared.pop(
                        int.from_bytes(req.msg[1:5], "little"), None
                    )
                records.append(
                    Record(req=req, resp=_Resp(req.timestamp_ns, RESP_NONE, b""))
                )
                qi += 1
                continue
            bundle = []
            j = ri
            while j < len(responses) and (
                nxt_ts is None or responses[j].timestamp_ns < nxt_ts
            ):
                bundle.append(responses[j])
                j += 1
            if not bundle:
                if nxt_ts is None:
                    break  # response may still be in flight: keep request
                errors += 1
                qi += 1
                ri = j
                continue
            if nxt_ts is None and not _bundle_complete(bundle):
                # Response still streaming across ingest ticks (a
                # resultset's rows/EOF may arrive next tick): keep both
                # the request and its partial bundle for the next round.
                break
            resp = _interpret(cmd, bundle)
            req_text = ""
            if state is not None:
                if cmd == COM_STMT_PREPARE and resp.status == RESP_OK:
                    _register_prepare(state, req, bundle)
                elif cmd == COM_STMT_EXECUTE:
                    req_text = _inflate_execute(state, req)
            records.append(
                MysqlRecord(req=req, resp=resp, req_text=req_text)
            )
            ri = j
            qi += 1
        return records, errors, requests[qi:], responses[ri:]


class _Resp(base.Frame):
    """Interpreted response (ref: mysql::Response)."""

    def __init__(self, timestamp_ns, status, msg):
        self.timestamp_ns = timestamp_ns
        self.status = status
        self.msg = msg


def _lenenc_int(buf: bytes, pos: int):
    """MySQL length-encoded integer (ref: parse_utils.cc)."""
    if pos >= len(buf):
        return None, pos
    b0 = buf[pos]
    if b0 < 0xFB:
        return b0, pos + 1
    if b0 == 0xFC:
        return int.from_bytes(buf[pos + 1 : pos + 3], "little"), pos + 3
    if b0 == 0xFD:
        return int.from_bytes(buf[pos + 1 : pos + 4], "little"), pos + 4
    if b0 == 0xFE:
        return int.from_bytes(buf[pos + 1 : pos + 9], "little"), pos + 9
    return None, pos


def _bundle_complete(bundle: list) -> bool:
    """Whether a response bundle has reached its terminator: OK/ERR/EOF
    head packets complete immediately; resultsets need the row-section
    terminator (EOF, or a trailing OK in CLIENT_DEPRECATE_EOF mode)."""
    first = bundle[0]
    if first.is_err or first.is_ok or first.is_eof:
        return True
    ncols, _ = _lenenc_int(first.msg, 0)
    if ncols is None:
        return True  # uninterpretable: don't hold the queue hostage
    eofs = sum(1 for p in bundle[1:] if p.is_eof)
    if eofs >= 2:
        return True  # column-section EOF + row-section EOF
    last = bundle[-1]
    # Deprecate-EOF mode terminates rows with an OK packet; a single EOF
    # plus trailing OK also closes the set.
    return len(bundle) > 1 and (last.is_err or (last.is_ok and eofs <= 1))


def _interpret(cmd: int, bundle: list) -> _Resp:
    """Interpret a response bundle (ref: handler.cc HandleOKMessage /
    HandleErrMessage / HandleResultsetResponse)."""
    first = bundle[0]
    ts = bundle[-1].timestamp_ns
    if first.is_err:
        code = int.from_bytes(first.msg[1:3], "little")
        text = first.msg[3:]
        if text[:1] == b"#":  # SQL-state marker: '#' + 5 chars
            text = text[6:]
        return _Resp(ts, RESP_ERR, f"{code}: ".encode() + text)
    if first.is_ok or first.is_eof:
        return _Resp(ts, RESP_OK, b"")
    # Resultset: first packet is the column count (length-encoded int).
    ncols, _ = _lenenc_int(first.msg, 0)
    nrows = 0
    if ncols is not None:
        seen_cols = 0
        phase = "cols"
        for p in bundle[1:]:
            if p.is_err:
                code = int.from_bytes(p.msg[1:3], "little")
                return _Resp(ts, RESP_ERR, f"{code}".encode())
            if phase == "cols":
                if p.is_eof:
                    phase = "rows"
                    continue
                seen_cols += 1
                if seen_cols >= ncols:
                    # Next packet is either the column-section EOF or (in
                    # CLIENT_DEPRECATE_EOF mode) already the first row.
                    phase = "cols_done"
                continue
            if phase == "cols_done":
                if p.is_eof:
                    phase = "rows"
                    continue
                phase = "rows"  # deprecate-EOF: fall through as a row
            # rows phase. A text-protocol row CAN start with 0x00 (empty
            # first column), so an OK header only terminates when it is
            # the bundle's final packet (deprecate-EOF terminator).
            if p.is_eof or (p.is_ok and p is bundle[-1]):
                break
            nrows += 1
        return _Resp(
            ts, RESP_OK, f"Resultset rows = {nrows}".encode()
        )
    return _Resp(ts, RESP_UNKNOWN, b"")


def _register_prepare(state: MysqlState, req, bundle) -> None:
    """COM_STMT_PREPARE response header: [0x00][stmt_id:4][num_cols:2]
    [num_params:2][filler:1][warnings:2] (ref: prepare handler)."""
    first = bundle[0]
    if len(first.msg) < 12 or first.msg[0] != 0:
        return
    stmt_id = int.from_bytes(first.msg[1:5], "little")
    num_params = int.from_bytes(first.msg[7:9], "little")
    state.prepared[stmt_id] = {
        "query": req.msg[1:].decode("latin-1", "replace"),
        "num_params": num_params,
        "types": None,
    }


# Binary-protocol value readers by MYSQL_TYPE code (ref: the reference's
# stmt-execute param parsing, protocols/mysql/parse.cc).
def _read_binary_value(msg: bytes, pos: int, mtype: int):
    need = {0x01: 1, 0x02: 2, 0x03: 4, 0x09: 4, 0x08: 8, 0x04: 4, 0x05: 8}
    if mtype in need and pos + need[mtype] > len(msg):
        raise ValueError("truncated binary value")  # -> raw-query fallback
    if mtype == 0x01:  # TINY
        return str(int.from_bytes(msg[pos:pos + 1], "little", signed=True)), pos + 1
    if mtype == 0x02:  # SHORT
        return str(int.from_bytes(msg[pos:pos + 2], "little", signed=True)), pos + 2
    if mtype in (0x03, 0x09):  # LONG / INT24
        return str(int.from_bytes(msg[pos:pos + 4], "little", signed=True)), pos + 4
    if mtype == 0x08:  # LONGLONG
        return str(int.from_bytes(msg[pos:pos + 8], "little", signed=True)), pos + 8
    if mtype == 0x04:  # FLOAT
        return repr(struct.unpack_from("<f", msg, pos)[0]), pos + 4
    if mtype == 0x05:  # DOUBLE
        return repr(struct.unpack_from("<d", msg, pos)[0]), pos + 8
    if mtype in (0x0F, 0xF6, 0xFC, 0xFD, 0xFE):  # VARCHAR/DECIMAL/BLOB/STRING
        n, pos2 = _lenenc_int(msg, pos)
        if n is None:
            raise ValueError("bad lenenc string")
        val = msg[pos2:pos2 + n].decode("latin-1", "replace")
        return "'" + val + "'", pos2 + n
    raise ValueError(f"unsupported binary type {mtype:#x}")


def _inflate_execute(state: MysqlState, req) -> str:
    """COM_STMT_EXECUTE → the prepared query with '?' placeholders
    substituted by the bound argument values (ref: stitcher.cc
    HandleStmtExecuteRequest + FillStmtExecute). Returns "" when the
    statement is unknown or the args cannot be decoded."""
    msg = req.msg
    if len(msg) < 10:
        return ""
    stmt_id = int.from_bytes(msg[1:5], "little")
    entry = state.prepared.get(stmt_id)
    if entry is None:
        return ""
    n = entry["num_params"]
    query = entry["query"]
    if n == 0:
        return query
    pos = 1 + 4 + 1 + 4  # cmd + stmt_id + flags + iteration_count
    nbytes = (n + 7) // 8
    if len(msg) < pos + nbytes + 1:
        return ""
    null_bitmap = msg[pos:pos + nbytes]
    pos += nbytes
    new_bound = msg[pos]
    pos += 1
    if new_bound:
        types = []
        for _ in range(n):
            if pos + 2 > len(msg):
                return ""
            types.append(msg[pos])  # second byte = unsigned flag
            pos += 2
        entry["types"] = types
    types = entry["types"]
    if types is None:
        return ""  # params bound before capture started
    vals = []
    try:
        for i in range(n):
            if null_bitmap[i // 8] & (1 << (i % 8)):
                vals.append("NULL")
                continue
            v, pos = _read_binary_value(msg, pos, types[i])
            vals.append(v)
    except (ValueError, IndexError, struct.error):
        return ""
    parts = query.split("?")
    if len(parts) != n + 1:
        return query  # placeholder/param mismatch: show the raw query
    out = [parts[0]]
    for v, tail in zip(vals, parts[1:]):
        out.append(v)
        out.append(tail)
    return "".join(out)


def request_body(req: Packet) -> str:
    cmd = req.msg[0]
    if cmd in _STRING_BODY:
        return req.msg[1:].decode("latin-1", errors="replace")
    return req.msg[1:].hex() if len(req.msg) > 1 else ""


def record_to_row(
    record: Record,
    upid: str,
    remote_addr: str,
    remote_port: int,
    trace_role: int,
) -> dict:
    """A mysql_events row (ref: mysql_table.h kMySQLElements)."""
    req, resp = record.req, record.resp
    return {
        "time_": req.timestamp_ns,
        "upid": upid,
        "remote_addr": remote_addr,
        "remote_port": remote_port,
        "trace_role": int(trace_role),
        "req_cmd": int(req.msg[0]),
        "req_body": getattr(record, "req_text", "") or request_body(req),
        "resp_status": int(resp.status),
        "resp_body": resp.msg.decode("latin-1", errors="replace"),
        "latency": max(resp.timestamp_ns - req.timestamp_ns, 0),
    }
