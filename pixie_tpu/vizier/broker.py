"""Query broker: compile → distributed plan → launch → forward results.

Ref: src/vizier/services/query_broker/ — Server.ExecuteScript
(controllers/server.go:308), QueryExecutorImpl.Run (query_executor.go:166),
LaunchQuery publishing per-agent plans on NATS Agent/<id> topics
(launch_query.go:36-82), QueryResultForwarder matching agent result streams
to the client with timeouts/cancellation (query_result_forwarder.go:395,
502,571), and the heartbeat-expiry agent tracker (tracker/agents.go +
agent_topic_listener.go:41,322 — 1-minute expiry, scaled down here).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from pixie_tpu.compiler import Compiler
from pixie_tpu.distributed import AgentInfo, DistributedPlanner, DistributedState
from pixie_tpu.engine import QueryResult
from pixie_tpu.exec import BridgeRouter
from pixie_tpu.plan.operators import BridgeSinkOp
from pixie_tpu.plan.plan import Plan
from pixie_tpu.types import Relation
from pixie_tpu.vizier.bus import (
    MessageBus,
    agent_topic,
)
from pixie_tpu.utils import flags
from pixie_tpu.vizier.agent import AGENT_STATUS_TOPIC, RESULTS_TOPIC_PREFIX


# ref: 1 minute (agent_topic_listener.go:41), scaled; env-overridable via
# PIXIE_TPU_AGENT_EXPIRY_S (read once at import).
AGENT_EXPIRY_S = flags.agent_expiry_s


class AgentTracker:
    """Liveness + table topology from register/heartbeat messages."""

    def __init__(self, bus: MessageBus):
        self._bus = bus
        self._sub = bus.subscribe(AGENT_STATUS_TOPIC)
        self._lock = threading.Lock()
        self._agents: dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            msg = self._sub.get(timeout=0.05)
            if msg is None:
                continue
            if msg.get("type") in ("register", "heartbeat"):
                with self._lock:
                    self._agents[msg["agent_id"]] = {
                        "is_kelvin": msg["is_kelvin"],
                        "tables": frozenset(msg.get("tables", ())),
                        "last_seen": time.monotonic(),
                    }

    def distributed_state(self) -> DistributedState:
        now = time.monotonic()
        with self._lock:
            # Expire silent agents (ref: agent_topic_listener expiry) so
            # plans skip them (prune_unavailable_sources_rule behavior).
            alive = {
                aid: a
                for aid, a in self._agents.items()
                if now - a["last_seen"] < AGENT_EXPIRY_S
            }
            self._agents = dict(alive)
        return DistributedState(
            agents=[
                AgentInfo(aid, a["tables"], a["is_kelvin"])
                for aid, a in sorted(alive.items())
            ]
        )

    def agents_snapshot(self) -> list[dict]:
        """Rows for the GetAgentStatus UDTF (ref: md_udtfs.h reads the
        agent manager's registry)."""
        now = time.monotonic()
        with self._lock:
            return [
                {
                    "agent_id": aid,
                    "asid": i + 1,
                    "hostname": aid,
                    "agent_state": (
                        "AGENT_STATE_HEALTHY"
                        if now - a["last_seen"] < AGENT_EXPIRY_S
                        else "AGENT_STATE_UNRESPONSIVE"
                    ),
                    # ns SINCE the last heartbeat (elapsed duration), matching
                    # the reference's ns_since_last_heartbeat column
                    # (src/vizier/funcs/md_udtfs/md_udtfs_impl.h) and the
                    # standalone fallback in md_udtfs.py (ADVICE r3).
                    "last_heartbeat_ns": int((now - a["last_seen"]) * 1e9),
                    "kelvin": a["is_kelvin"],
                }
                for i, (aid, a) in enumerate(sorted(self._agents.items()))
            ]

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self._sub.unsubscribe()


class TrackerVizierCtx:
    """FunctionContext.vizier_ctx backed by the broker's agent tracker."""

    def __init__(self, tracker: AgentTracker):
        self._tracker = tracker

    def agents(self) -> list[dict]:
        return self._tracker.agents_snapshot()


class QueryBroker:
    def __init__(
        self,
        bus: MessageBus,
        router: BridgeRouter,
        registry=None,
        table_relations: Optional[dict[str, Relation]] = None,
    ):
        if registry is None:
            from pixie_tpu.udf.registry import default_registry

            registry = default_registry()
        self.bus = bus
        self.router = router
        self.registry = registry
        self.compiler = Compiler(registry)
        self.tracker = AgentTracker(bus)
        self.vizier_ctx = TrackerVizierCtx(self.tracker)
        # Schema authority: in the reference the broker gets schemas from
        # the metadata service; here the caller provides them (or agents'
        # heartbeats name tables and the caller maps relations).
        self.table_relations = dict(table_relations or {})

    def execute_script(
        self,
        query: str,
        timeout_s: float = 30.0,
        now_ns: Optional[int] = None,
        script_args: Optional[dict] = None,
        analyze: bool = False,
        exec_funcs=None,
        on_batch=None,
    ) -> QueryResult:
        """The ExecuteScript path (server.go:308 → launch_query.go:36).

        Flow control (ref: query_result_forwarder.go:502,571): the result
        subscription is bounded (flags.broker_max_pending); agents
        publishing into a full queue block up to the publish timeout, so a
        slow consumer backpressures producers instead of growing broker
        memory. Pass ``on_batch(table_name, row_batch)`` to stream batches
        to the consumer as they arrive instead of accumulating them."""
        qid = str(uuid.uuid4())
        t0 = time.perf_counter_ns()
        logical = self.compiler.compile(
            query,
            self.table_relations,
            now_ns=now_ns,
            script_args=script_args,
            query_id=qid,
            exec_funcs=exec_funcs,
        )
        state = self.tracker.distributed_state()
        planner = DistributedPlanner(self.registry, self.table_relations)
        plan = planner.plan(logical, state)
        compile_ns = time.perf_counter_ns() - t0

        # Central bridge-producer registration over the shared router.
        for frag in plan.fragments:
            for nid in frag.nodes():
                if isinstance(frag.node(nid), BridgeSinkOp):
                    self.router.register_producer(
                        qid, frag.node(nid).bridge_id
                    )

        results_sub = self.bus.subscribe(
            RESULTS_TOPIC_PREFIX + qid, maxsize=flags.broker_max_pending
        )
        # Launch per-agent plans (launch_query.go:36-82).
        by_instance: dict[str, Plan] = {}
        for frag in plan.fragments:
            inst = plan.executing_instance[frag.fragment_id]
            sub = by_instance.setdefault(inst, Plan(qid))
            sub.fragments.append(frag)
            sub.executing_instance[frag.fragment_id] = inst
        t1 = time.perf_counter_ns()
        for inst, sub_plan in by_instance.items():
            self.bus.publish(
                agent_topic(inst),
                {
                    "type": "execute_fragment",
                    "query_id": qid,
                    "plan": sub_plan,
                    "analyze": analyze,
                },
            )

        # Forward results (query_result_forwarder.go:502,571).
        tables: dict[str, list] = {}
        exec_stats: dict[str, dict] = {}
        pending = len(by_instance)
        deadline = time.monotonic() + timeout_s
        errors: list[str] = []
        try:
            while pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"query {qid}: {pending} agents still running"
                    )
                msg = results_sub.get(timeout=min(remaining, 0.1))
                if msg is None:
                    continue
                if msg["type"] == "result_batch":
                    if on_batch is not None:
                        on_batch(msg["table"], msg["batch"])
                    else:
                        tables.setdefault(msg["table"], []).append(
                            msg["batch"]
                        )
                elif msg["type"] == "fragment_done":
                    for k, v in msg.get("exec_stats", {}).items():
                        exec_stats[f"{msg['agent_id']}/{k}"] = v
                    pending -= 1
                elif msg["type"] == "fragment_error":
                    errors.append(f"{msg['agent_id']}: {msg['error']}")
                    pending -= 1
        finally:
            results_sub.unsubscribe()
            self.router.cleanup_query(qid)
        if results_sub.dropped:
            # Result messages were dropped after the flow-control timeout:
            # the stream is incomplete — fail loudly rather than return
            # partial data as success (ref: the forwarder cancels the
            # query, query_result_forwarder.go:571).
            raise RuntimeError(
                f"query {qid}: consumer too slow — {results_sub.dropped} "
                "result messages dropped after "
                f"{flags.broker_publish_timeout_s}s of backpressure"
            )
        if errors:
            raise RuntimeError(
                f"query {qid} failed on agents:\n" + "\n".join(errors)
            )
        return QueryResult(
            query_id=qid,
            tables=tables,
            exec_stats=exec_stats,
            compile_time_ns=compile_ns,
            exec_time_ns=time.perf_counter_ns() - t1,
        )

    def stop(self) -> None:
        self.tracker.stop()
