"""Query broker: compile → distributed plan → launch → forward results.

Ref: src/vizier/services/query_broker/ — Server.ExecuteScript
(controllers/server.go:308), QueryExecutorImpl.Run (query_executor.go:166),
LaunchQuery publishing per-agent plans on NATS Agent/<id> topics
(launch_query.go:36-82), QueryResultForwarder matching agent result streams
to the client with timeouts/cancellation (query_result_forwarder.go:395,
502,571), and the heartbeat-expiry agent tracker (tracker/agents.go +
agent_topic_listener.go:41,322 — 1-minute expiry, scaled down here).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Callable, Optional

from pixie_tpu.compiler import Compiler
from pixie_tpu.distributed import AgentInfo, DistributedPlanner, DistributedState
from pixie_tpu.engine import QueryResult
from pixie_tpu.exec import BridgeRouter
from pixie_tpu.plan.operators import BridgeSinkOp, MemorySourceOp
from pixie_tpu.plan.plan import Plan
from pixie_tpu.plan.program_key import fragment_program_key
from pixie_tpu.types import Relation
from pixie_tpu.vizier.bus import (
    MessageBus,
    agent_topic,
)
from pixie_tpu.utils import faults, flags, metrics_registry, trace
from pixie_tpu.vizier.agent import AGENT_STATUS_TOPIC, RESULTS_TOPIC_PREFIX


# ref: 1 minute (agent_topic_listener.go:41), scaled; env-overridable via
# PIXIE_TPU_AGENT_EXPIRY_S (read once at import).
AGENT_EXPIRY_S = flags.agent_expiry_s

_log = logging.getLogger("pixie_tpu.broker")

# r22 learned cost model, resolved lazily (serving's package init
# transitively imports this module through controller -> vizier.slo).
_COST_MODEL = None


def _cost_model():
    global _COST_MODEL
    if _COST_MODEL is None:
        from pixie_tpu.serving import cost_model

        _COST_MODEL = cost_model
    return _COST_MODEL

# Broker-side query counters on the shared registry so /metrics reflects
# them (r11 satellite — ad-hoc totals were invisible to the endpoint).
_M = metrics_registry()
_QUERIES = _M.counter(
    "broker_queries_total", "Queries executed through the broker."
)
_DEGRADED = _M.counter(
    "broker_degraded_queries_total",
    "Queries that returned a partial result with a degraded annotation.",
)
_FORWARD_DROPPED = _M.counter(
    "broker_forward_dropped_total",
    "Result messages dropped in the broker's forwarder (fault site "
    "broker.forward).",
)
_QUERY_SECONDS = _M.histogram(
    "broker_query_seconds",
    "End-to-end broker query latency, by tenant (r15: per-tenant SLO "
    "rules get native series; aggregate views read the label-merged "
    "distribution via Histogram.agg_quantile).",
)
_ALERTS_EMITTED = _M.counter(
    "broker_alert_events_total",
    "SLO alert events fanned out through the broker's alert listeners, "
    "by rule and state.",
)
_REOFFERS = _M.counter(
    "broker_launch_reoffers_total",
    "execute_fragment launches re-offered to an agent that re-registered "
    "while a launch was still unacknowledged (reconnect-gap hole, r12), "
    "by reason: 'reconnect' (same process, new connection) vs 'restart' "
    "(new process with durable identity, r14).",
)
_RETRIES = _M.counter(
    "broker_fragment_retries_total",
    "Fragments re-launched onto a surviving agent after their executing "
    "agent was lost mid-query (r17, flag fragment_failover), by reason: "
    "agent_lost | agent_error | restart_lost | forward_dropped.",
)
_HEDGES = _M.counter(
    "broker_hedged_fragments_total",
    "Duplicate fragment attempts launched because the original was "
    "still pending past the hedge delay (r17, flag hedged_requests).",
)
_HEDGE_BOTH = _M.counter(
    "broker_hedge_both_complete_total",
    "Hedge/retry attempts whose results arrived AFTER another attempt "
    "already won the slot — dropped by the fragment-epoch dedup (the "
    "wasted-work count; fault site hedge.both_complete forces the "
    "race deterministically).",
)
_RECOVERED_Q = _M.counter(
    "broker_recovered_queries_total",
    "Queries that completed with FULL results only because fragment "
    "failover retried or hedged at least one fragment (the degraded "
    "annotation these queries would have carried pre-r17 is replaced "
    "by a recovered annotation).",
)
_RECOVERY_SECONDS_H = _M.histogram(
    "broker_fragment_recovery_seconds",
    "Wall seconds from a fragment attempt's detected loss to the "
    "replacement attempt completing its slot (r17: what failover adds "
    "to a faulted query's latency).",
)
_RESTARTS = _M.counter(
    "broker_agent_restarts_total",
    "Register messages from a RESTARTED agent incarnation (r14: durable "
    "identity restored from its WAL, epoch bumped past the dead "
    "process's persisted counter) — distinct from plain reconnect "
    "re-registers.",
)


class AgentTracker:
    """Liveness + table topology + device health from register/heartbeat
    messages, keyed on ``agent_id`` with ONLY the latest registration
    epoch retained (r10 satellite): a reconnecting agent re-registers
    with a bumped epoch, and any straggler message from its superseded
    incarnation (an old connection's buffered heartbeat arriving late)
    is dropped instead of resurrecting pre-reconnect table/health
    state."""

    def __init__(self, bus: MessageBus):
        self._bus = bus
        self._sub = bus.subscribe(AGENT_STATUS_TOPIC)
        self._lock = threading.Lock()
        self._agents: dict[str, dict] = {}
        self._stop = threading.Event()
        # fn(agent_id, epoch, restarted) fired on every "register"
        # message (r12): the broker re-offers unacknowledged fragment
        # launches to an agent that re-registered after a reconnect gap
        # (or, r14, after a full process restart — restarted=True).
        self._register_listeners: list = []
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def add_register_listener(self, fn) -> None:
        with self._lock:
            self._register_listeners.append(fn)

    def _loop(self) -> None:
        while not self._stop.is_set():
            msg = self._sub.get(timeout=0.05)
            if msg is None:
                continue
            if msg.get("type") in ("register", "heartbeat"):
                epoch = msg.get("epoch", 0)
                # r14: a register from a RESTARTED incarnation (durable
                # identity, epoch continued past the dead process's
                # counter) supersedes the zombie entry like any higher
                # epoch, but is counted and surfaced separately so
                # operators can tell crash recovery from network flaps.
                restarted = bool(
                    msg["type"] == "register" and msg.get("restarted")
                )
                with self._lock:
                    cur = self._agents.get(msg["agent_id"])
                    if cur is not None and epoch < cur["epoch"]:
                        continue  # stale straggler from an old incarnation
                    self._agents[msg["agent_id"]] = {
                        "is_kelvin": msg["is_kelvin"],
                        "tables": frozenset(msg.get("tables", ())),
                        # r17: tables this agent can serve WITHOUT
                        # owning (replica rings / shared store) — never
                        # planned over, but failover and the no-owner
                        # planning fallback route here.
                        "replica_tables": frozenset(
                            msg.get("replica_tables", ())
                        ),
                        "last_seen": time.monotonic(),
                        "epoch": epoch,
                        "health": msg.get("health"),
                        "restarts": (
                            (cur.get("restarts", 0) if cur else 0)
                            + (1 if restarted else 0)
                        ),
                    }
                    listeners = (
                        list(self._register_listeners)
                        if msg["type"] == "register"
                        else ()
                    )
                if restarted:
                    _RESTARTS.inc(agent=msg["agent_id"])
                for fn in listeners:
                    try:
                        fn(msg["agent_id"], epoch, restarted)
                    except Exception:
                        _log.exception(
                            "register listener failed (ignored)"
                        )

    def planning_view(self) -> tuple[DistributedState, list[str]]:
        """(alive agents for planning, skipped agent ids) — query planning
        only covers agents within the heartbeat-expiry window (ref:
        agent_topic_listener expiry + prune_unavailable_sources_rule); the
        skipped list rides the query's degraded annotation so callers can
        see whose data the plan never covered (r9)."""
        now = time.monotonic()
        with self._lock:
            alive, skipped = {}, []
            for aid, a in self._agents.items():
                silent = now - a["last_seen"]
                if silent < AGENT_EXPIRY_S:
                    alive[aid] = a
                elif silent < 10 * AGENT_EXPIRY_S:
                    # Recently expired: keep the record (UNRESPONSIVE in
                    # the status UDTF) and report it skipped.
                    skipped.append(aid)
                # Long-silent agents are forgotten entirely.
            self._agents = {
                aid: a
                for aid, a in self._agents.items()
                if now - a["last_seen"] < 10 * AGENT_EXPIRY_S
            }
        state = DistributedState(
            agents=[
                AgentInfo(aid, a["tables"], a["is_kelvin"])
                for aid, a in sorted(alive.items())
            ]
        )
        return state, sorted(skipped)

    def distributed_state(self) -> DistributedState:
        return self.planning_view()[0]

    def expired_among(self, agent_ids) -> list[str]:
        """Subset of ``agent_ids`` whose heartbeat has expired — the
        broker polls this mid-query to detect agents dying while their
        fragments run (ref: the forwarder cancelling dead-agent streams,
        query_result_forwarder.go:395)."""
        now = time.monotonic()
        with self._lock:
            return sorted(
                aid
                for aid in agent_ids
                if aid not in self._agents
                or now - self._agents[aid]["last_seen"] >= AGENT_EXPIRY_S
            )

    def failover_view(self) -> list[dict]:
        """Alive agents with everything failover candidate selection
        needs (r17): owned tables, replica tables, role, and the latest
        heartbeat health (replica ring coverage/lag rides in
        health['replicas'])."""
        now = time.monotonic()
        with self._lock:
            return [
                {
                    "agent_id": aid,
                    "tables": frozenset(a["tables"]),
                    "replica_tables": frozenset(
                        a.get("replica_tables") or ()
                    ),
                    "is_kelvin": a["is_kelvin"],
                    "health": a.get("health"),
                }
                for aid, a in sorted(self._agents.items())
                if now - a["last_seen"] < AGENT_EXPIRY_S
            ]

    def health_view(self) -> dict[str, dict]:
        """Aggregated broker-side cluster health (r10): agent_id ->
        liveness + registration epoch + the latest device-health payload
        from its heartbeat (breaker state per program key, staging depth,
        last fold latency). Consumed by execute_script's breaker-aware
        planning and the health HTTP endpoint."""
        now = time.monotonic()
        with self._lock:
            return {
                aid: {
                    "alive": now - a["last_seen"] < AGENT_EXPIRY_S,
                    "epoch": a["epoch"],
                    "is_kelvin": a["is_kelvin"],
                    "health": a.get("health"),
                    # r14: observed crash-restart registers; the agent's
                    # own recovery stats (wal_replayed_frames,
                    # ring_restaged_windows, recovery_seconds) ride in
                    # health["recovery"].
                    "restarts": a.get("restarts", 0),
                }
                for aid, a in sorted(self._agents.items())
            }

    def open_breaker_keys(self) -> dict[str, frozenset]:
        """agent_id -> program keys with an OPEN device breaker (from the
        latest heartbeat). Half-open keys are absent: a half-open breaker
        admits its trial, so the planner schedules normally."""
        out = {}
        with self._lock:
            for aid, a in self._agents.items():
                health = a.get("health") or {}
                keys = health.get("breaker_open") or ()
                if keys:
                    out[aid] = frozenset(keys)
        return out

    def fold_latency_view(self) -> dict[str, dict]:
        """program_key -> {agent_id: {p50_ms, p99_ms, n}} from the latest
        heartbeats (r11): the per-program-key fold-latency histograms the
        device executors publish, aggregated for /statusz so operators see
        live per-phase percentiles without running a query."""
        out: dict[str, dict] = {}
        with self._lock:
            for aid, a in sorted(self._agents.items()):
                fl = (a.get("health") or {}).get("fold_latency") or {}
                for key, st in fl.items():
                    out.setdefault(key, {})[aid] = st
        return out

    def mesh_view(self) -> dict[str, dict]:
        """agent_id -> the executor's mesh-recovery section from its
        latest heartbeat (r23): current vs full geometry, degradation
        ladder, per-geometry breaker state, degrade/checkpoint/resume
        counters. Operators read this off /statusz to see which agents
        are running on a degraded mesh rung (and whether the full
        geometry's breaker is open, half-open, or recovered) without
        touching the agents."""
        out = {}
        with self._lock:
            for aid, a in sorted(self._agents.items()):
                mesh = (a.get("health") or {}).get("mesh")
                if mesh:
                    out[aid] = mesh
        return out

    def ingest_view(self) -> dict[str, dict]:
        """agent_id -> the ingest-plane section from its latest
        heartbeat (r24): per-source events fed, rows emitted, total
        drops, live trackers, buffered bytes, current shedding-ladder
        level, and open quarantine breakers. /statusz surfaces it so an
        operator sees WHICH hosts are shedding (and why) during an
        overload without scraping per-host /metrics."""
        out = {}
        with self._lock:
            for aid, a in sorted(self._agents.items()):
                ingest = (a.get("health") or {}).get("ingest")
                if ingest:
                    out[aid] = ingest
        return out

    def agents_snapshot(self) -> list[dict]:
        """Rows for the GetAgentStatus UDTF (ref: md_udtfs.h reads the
        agent manager's registry), plus r10 health-plane columns."""
        now = time.monotonic()
        with self._lock:
            return [
                {
                    "agent_id": aid,
                    "asid": i + 1,
                    "hostname": aid,
                    "agent_state": (
                        "AGENT_STATE_HEALTHY"
                        if now - a["last_seen"] < AGENT_EXPIRY_S
                        else "AGENT_STATE_UNRESPONSIVE"
                    ),
                    # ns SINCE the last heartbeat (elapsed duration), matching
                    # the reference's ns_since_last_heartbeat column
                    # (src/vizier/funcs/md_udtfs/md_udtfs_impl.h) and the
                    # standalone fallback in md_udtfs.py (ADVICE r3).
                    "last_heartbeat_ns": int((now - a["last_seen"]) * 1e9),
                    "kelvin": a["is_kelvin"],
                    "epoch": a["epoch"],
                    "restarts": a.get("restarts", 0),
                    "breaker_open": len(
                        (a.get("health") or {}).get("breaker_open") or ()
                    ),
                }
                for i, (aid, a) in enumerate(sorted(self._agents.items()))
            ]

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self._sub.unsubscribe()


class TrackerVizierCtx:
    """FunctionContext.vizier_ctx backed by the broker's agent tracker."""

    def __init__(self, tracker: AgentTracker):
        self._tracker = tracker

    def agents(self) -> list[dict]:
        return self._tracker.agents_snapshot()


class QueryBroker:
    def __init__(
        self,
        bus: MessageBus,
        router: BridgeRouter,
        registry=None,
        table_relations: Optional[dict[str, Relation]] = None,
        residency=None,
        staging_estimator=None,
    ):
        if registry is None:
            from pixie_tpu.udf.registry import default_registry

            registry = default_registry()
        self.bus = bus
        self.router = router
        self.registry = registry
        self.compiler = Compiler(registry)
        self.tracker = AgentTracker(bus)
        self.vizier_ctx = TrackerVizierCtx(self.tracker)
        # Schema authority: in the reference the broker gets schemas from
        # the metadata service; here the caller provides them (or agents'
        # heartbeats name tables and the caller maps relations).
        self.table_relations = dict(table_relations or {})
        self._health_srv = None
        # Pluggable OTel exporter for finished query traces (flag
        # trace_otel_export); callers set it to an OTLP/HTTP callable.
        self.otel_exporter = None
        # Serving front door (r12, flag serving_enabled): admission
        # control with per-tenant weighted fair queueing and — when the
        # embedder wires ``residency`` (a serving.ResidencyPool, e.g. the
        # in-process agents' device executor pool) — an HBM byte-budget
        # check before admitting.
        from pixie_tpu.serving.admission import AdmissionController

        self.residency = residency
        self.admission = AdmissionController(
            budget_fn=(
                residency.snapshot if residency is not None else None
            )
        )
        # r16: the shared-scan batching window is demand-gated on live
        # admission queue depth — a solo query on an idle broker no
        # longer sleeps shared_scan_window_ms. Registered/unregistered
        # with THIS broker's bound fn so a stopped broker never yanks a
        # newer one's wiring.
        from pixie_tpu.serving import shared_scan as _shared_scan

        self._queue_depth_fn = self.admission.queue_depth
        _shared_scan.set_queue_depth_fn(self._queue_depth_fn)
        # r16: closed-loop admission control (flag admission_controller)
        # — an SLO-window adapter on the cron runner actuating the
        # serving knobs from the r15 telemetry planes, within guard
        # rails. Explicit start via start_admission_controller() for
        # embedders that want their own datastore.
        self.admission_controller = None
        if flags.admission_controller:
            self.start_admission_controller()
        # r18: admission-time placement plane (flag residency_placement)
        # — score live agents by heartbeat-advertised HBM residency /
        # fold latency / WFQ load and route each query's scan to the
        # winner. Shares its scorer with the r17 failover ranking. The
        # companion ring rebalancer (flag ring_rebalance) drains the
        # plane's per-table heat and adapts replica-follower
        # assignments over the ring_replica topic.
        self.placement = None
        if flags.residency_placement:
            from pixie_tpu.serving.placement import PlacementPlane

            self.placement = PlacementPlane()
        self.ring_rebalancer = None
        if flags.ring_rebalance:
            self.start_ring_rebalancer()
        # r13 satellite: table_name -> estimated staging bytes (e.g.
        # serving.admission.make_store_estimator over the agents' table
        # store). With it, admission rejects a query whose staging
        # could NEVER fit the HBM budget before the doomed cold stage
        # starts, not only once pinned bytes already exceed budget.
        self.staging_estimator = staging_estimator
        # Unacknowledged fragment launches per agent (r12 reconnect-gap
        # fix): a launch published into an agent's reconnect window is
        # silently lost by an at-most-once bus; when the agent
        # re-registers, every still-pending launch for it is re-offered
        # (agents dedup by query_id, so a double delivery is harmless).
        self._launch_lock = threading.Lock()
        self._inflight_launches: dict[str, dict[str, dict]] = {}
        self.tracker.add_register_listener(self._reoffer_launches)
        # SLO/alert plane (r15, vizier/slo.py): an attached SLOManager
        # (``broker.slo``) feeds /alertz; alert listeners receive every
        # rule transition as a structured event (same shape family as
        # the r10 on_event degradation events).
        self.slo = None
        self._alert_listeners: list = []
        # r20: materialized-view plane (flag materialized_views) —
        # registered aggregation scripts maintained as persisted
        # partial-agg state; matching queries are served from the
        # merged state BEFORE admission. Explicit start via
        # start_views() (needs a table store to fold against).
        self.views = None

    def start_admission_controller(self, datastore=None):
        """Attach the r16 closed-loop admission controller
        (serving/controller.py): persisted as a CronScript on its own
        runner (restart survival like SLO rules), reading the broker's
        admission/residency planes and actuating the serving flags
        within guard rails. Idempotent; returns the loop."""
        if self.admission_controller is not None:
            return self.admission_controller
        from pixie_tpu.serving.controller import AdmissionControlLoop

        self.admission_controller = AdmissionControlLoop(
            residency_fn=(
                self.residency.snapshot
                if self.residency is not None
                else None
            ),
            queue_depth_fn=self.admission.queue_depth,
        ).attach(self, datastore=datastore)
        return self.admission_controller

    def start_ring_rebalancer(self, interval_s=None):
        """Attach the r18 adaptive replica-ring rebalancer
        (serving/placement.py): drains the placement plane's per-table
        heat each interval and reassigns replica followers over the
        ring_replica topic, railed by heartbeat HBM budgets. Creates
        the placement plane if routing isn't already on (the heat
        window then only fills once placement routing runs, so ticks
        hold). Idempotent; returns the rebalancer."""
        if self.ring_rebalancer is not None:
            return self.ring_rebalancer
        from pixie_tpu.serving.placement import PlacementPlane, RingRebalancer
        from pixie_tpu.vizier.agent import RING_REPLICA_TOPIC

        if self.placement is None:
            self.placement = PlacementPlane()
        self.ring_rebalancer = RingRebalancer(
            publish=lambda msg: self.bus.publish(RING_REPLICA_TOPIC, msg),
            view_fn=self.tracker.failover_view,
            heat_fn=self.placement.drain_heat,
        )
        self.ring_rebalancer.start(interval_s)
        return self.ring_rebalancer

    def start_views(self, table_store, datastore=None):
        """Attach the r20 materialized-view plane (serving/views.py):
        view definitions persist as CronScripts in their own keyspace
        (``/view_scripts/``) on their own runner — restart-surviving
        like the r15 SLO rules and the r16 controller — and carried
        partial-agg state persists under ``/view_state/``, so a
        recovered broker's first read folds only the unflushed tail.
        Idempotent; returns the registry."""
        if self.views is not None:
            return self.views
        from pixie_tpu.serving.views import ViewRegistry

        self.views = ViewRegistry(
            self, table_store, datastore=datastore
        ).attach()
        return self.views

    # -- SLO alert fan-out (r15) --------------------------------------------
    def add_alert_listener(self, fn) -> None:
        """Register ``fn(event: dict)`` for SLO alert transitions
        ({"type": "slo_alert", "rule", "state", "severity", "value",
        "threshold", "tenant", ...}). Exceptions are logged and
        swallowed — alerting must never take the broker down."""
        self._alert_listeners.append(fn)

    def emit_alert(self, event: dict) -> None:
        """Fan a structured alert event out to every listener (called by
        the attached SLOManager on each rule transition)."""
        _ALERTS_EMITTED.inc(
            rule=event.get("rule", ""), state=event.get("state", "")
        )
        for fn in list(self._alert_listeners):
            try:
                fn(dict(event))
            except Exception:
                _log.exception("alert listener failed (ignored)")

    def start_health_server(self, host: str = "127.0.0.1", port: int = 0):
        """Expose the aggregated cluster health view over HTTP (r10):
        /statusz carries ``cluster_health`` (per-agent breaker state,
        staging depth, fold latency, liveness) and /agentz the
        GetAgentStatus-shaped snapshot. Returns the HealthServer (its
        ``.address`` is the bound (host, port))."""
        from pixie_tpu.vizier.health import serve_health

        self._health_srv = serve_health(
            "broker",
            status_fn=lambda: {
                "agents": self.tracker.agents_snapshot(),
                "cluster_health": self.tracker.health_view(),
                # Live per-program-key fold-latency percentiles from the
                # agents' heartbeat-carried histograms (r11).
                "fold_latency": self.tracker.fold_latency_view(),
                # Serving plane (r12): admission queue depth / active /
                # per-tenant virtual clocks, and (when wired) the HBM
                # residency pool's byte accounting.
                "admission": self.admission.snapshot(),
                # r16: the closed-loop controller's live knobs, rails,
                # and recent actuation trail.
                "admission_controller": (
                    self.admission_controller.status()
                    if self.admission_controller is not None
                    else None
                ),
                "residency": (
                    self.residency.snapshot()
                    if self.residency is not None
                    else None
                ),
                # r18: placement decisions/hit-rate/per-agent shares,
                # plus the ring rebalancer's assignments and actuation
                # trail.
                "placement": (
                    {
                        **self.placement.status(),
                        "rebalancer": (
                            self.ring_rebalancer.status()
                            if self.ring_rebalancer is not None
                            else None
                        ),
                    }
                    if self.placement is not None
                    else None
                ),
                # r20: materialized-view plane — per-view watermark,
                # staleness, hit counts, breaker state.
                "views": (
                    self.views.status()
                    if self.views is not None
                    else None
                ),
                # r23: per-agent mesh-recovery plane — degraded
                # geometry rungs, per-geometry breaker state, and
                # checkpoint/resume counters from executor heartbeats.
                "mesh": self.tracker.mesh_view(),
                # r24: per-agent ingest plane — events/rows/drops,
                # tracker and buffer gauges, shedding-ladder level, and
                # quarantine breakers from PEM heartbeats.
                "ingest": self.tracker.ingest_view(),
            },
            extra_routes={
                "/agentz": lambda: self.tracker.agents_snapshot(),
                # r20: the view plane's own route (empty shell when no
                # registry is attached, so the route always exists).
                "/viewz": lambda: (
                    self.views.status()
                    if self.views is not None
                    else {"enabled": False, "views": [],
                          "hits": 0, "misses": 0, "hit_rate": 0.0}
                ),
                # r15: live SLO rule + alert status (empty shell when no
                # SLOManager is attached, so the route always exists).
                "/alertz": lambda: (
                    self.slo.status()
                    if self.slo is not None
                    else {"rules": [], "active": [], "recent": []}
                ),
            },
            host=host,
            port=port,
        )
        return self._health_srv

    def _plan_around_open_breakers(
        self, planner, logical, plan, state
    ) -> tuple[Plan, list[str]]:
        """Health-plane planning step (r10): if any data-holding agent's
        heartbeat reports an OPEN device breaker for the exact program
        key of a fragment this plan assigns to it, replan without that
        agent — it would be discovered sick mid-query anyway (host
        fallback at best, breaker churn at worst). Returns the plan to
        run plus the proactively-skipped agent ids. Falls back to the
        original plan when every capable agent is sick (degraded data
        beats no data) or the replan is impossible."""
        open_keys = self.tracker.open_breaker_keys()
        if not open_keys:
            return plan, []
        kelvins = {a.agent_id for a in state.agents if a.is_kelvin}
        sick = set()
        for frag in plan.fragments:
            inst = plan.executing_instance[frag.fragment_id]
            if inst in open_keys and inst not in kelvins:
                if fragment_program_key(frag) in open_keys[inst]:
                    sick.add(inst)
        if not sick:
            return plan, []
        healthy = DistributedState(
            agents=[a for a in state.agents if a.agent_id not in sick]
        )
        try:
            replanned = planner.plan(logical, healthy)
        except ValueError:
            # No healthy agent holds the needed tables: run the original
            # plan rather than fail the query outright.
            _log.warning(
                "health plane: every capable agent has an open breaker "
                "for this query shape (%s); planning over them anyway",
                sorted(sick),
            )
            return plan, []
        return replanned, sorted(sick)

    # -- transparent fragment failover (r17) ---------------------------------
    @staticmethod
    def _plan_tables(frag_or_plan) -> frozenset:
        """Table names a fragment (or sub-plan) scans — what a failover
        replacement must be able to serve."""
        frags = getattr(frag_or_plan, "fragments", None) or [frag_or_plan]
        return frozenset(
            f.node(nid).table_name
            for f in frags
            for nid in f.nodes()
            if isinstance(f.node(nid), MemorySourceOp)
        )

    def _failover_candidate(
        self,
        needed: frozenset,
        tried: set,
        prefer_kelvin: bool,
        exclude: "tuple | set" = (),
    ) -> Optional[str]:
        """The best surviving agent to re-run a lost fragment on: it
        must cover every scanned table (owned or replica); among
        eligible agents, prefer the matching role, then owners, then
        the agent whose replica rings already hold the MOST windows of
        the needed tables with the least lag (wire ~ 0 on landing),
        then stable name order. When every capable agent has already
        been tried (retry budget permitting), a still-alive
        previously-tried agent is eligible again — transient faults
        (a dropped forwarder frame, one injected error) don't condemn
        an agent — except the one that just failed (``exclude``)."""
        pick = self._best_failover_candidate(
            needed, set(tried) | set(exclude), prefer_kelvin
        )
        if pick is None and tried:
            pick = self._best_failover_candidate(
                needed, set(exclude), prefer_kelvin
            )
        return pick

    def _best_failover_candidate(
        self, needed: frozenset, skip: set, prefer_kelvin: bool
    ) -> Optional[str]:
        # r18: failover and admission-time placement share one scorer
        # (serving/placement.py) — the rank tuple is the r17 one:
        # role match, ownership, replica warmth, lag, name.
        from pixie_tpu.serving.placement import best_failover_candidate

        return best_failover_candidate(
            self.tracker.failover_view(), needed, skip, prefer_kelvin
        )

    def _hedge_delay_s(self, sub_plan: Plan) -> Optional[float]:
        """How long a fragment may stay pending before a hedge launches:
        ``hedge_delay_ms`` when set, else the ``hedge_quantile`` of the
        per-program-key fold-latency view from agent heartbeats (r11).
        None = no data, no hedge (hedging on a guess just doubles
        load)."""
        ms = float(flags.hedge_delay_ms)
        if ms > 0:
            return ms / 1e3
        view = self.tracker.fold_latency_view()
        if not view:
            return None
        q = "p99_ms" if float(flags.hedge_quantile) >= 0.99 else "p50_ms"
        keys = [fragment_program_key(frag) for frag in sub_plan.fragments]
        vals = []
        for pk in keys:
            for st in view.get(pk, {}).values():
                v = st.get(q)
                if v:
                    vals.append(float(v))
        raw = max(vals) / 1e3 if vals else None
        # r22: the cost model ingests the instantaneous per-program-key
        # quantiles into decayed reservoirs and answers with a smoothed
        # estimate, clamped to [raw/rail, raw*rail] — one spiky
        # heartbeat no longer whipsaws the hedge timer. Cold, shadow,
        # or disabled: ``raw`` unchanged (the exact r17 value); no data
        # at all still means no hedge.
        cm = _cost_model()
        if cm.ACTIVE:
            pred = cm.hedge_delay_s(keys, view, q, raw)
            if pred is not None:
                return pred
        return raw

    def _plan_with_replica_fallback(self, planner, logical, state):
        """Distributed planning, with a failover-mode fallback: when NO
        alive agent owns the scanned tables (the owner died between
        queries), plan over ONE replica agent that covers them — its
        shared-store/replicated-ring data serves the scan, so the query
        runs instead of failing with 'no agent holds tables'. Exactly
        one replica is promoted (promoting several would double-count
        the un-sharded data)."""
        try:
            return planner.plan(logical, state), None
        except ValueError:
            if not flags.fragment_failover:
                raise
            needed = self._plan_tables(logical.fragments[0])
            pick = self._failover_candidate(needed, set(), False)
            if pick is None:
                raise
            promoted = DistributedState(
                agents=[
                    AgentInfo(
                        a.agent_id,
                        frozenset(a.tables) | needed
                        if a.agent_id == pick
                        else a.tables,
                        a.is_kelvin,
                    )
                    for a in state.agents
                ]
            )
            _log.info(
                "failover planning: no alive owner for %s; promoting "
                "replica agent %s", sorted(needed), pick,
            )
            return planner.plan(logical, promoted), pick

    def _reoffer_launches(
        self, agent_id: str, epoch: int, restarted: bool = False
    ) -> None:
        """Register-listener (r12): an agent re-registering while the
        broker still holds unacknowledged launches for it lost those
        publishes in its reconnect gap (the bus is at-most-once to
        CURRENT subscribers) — re-offer them. Agents dedup by query_id,
        so the common both-delivered case is harmless. A RESTARTED
        incarnation (r14) gets the same re-offer, but its durable query
        markers decide the outcome: ``done`` → drop (the WAL replay
        already completed the query), ``started`` → structured refusal
        (partial output may be applied), unseen → execute normally."""
        with self._launch_lock:
            msgs = list(self._inflight_launches.get(agent_id, {}).values())
        reason = "restart" if restarted else "reconnect"
        for msg in msgs:
            _REOFFERS.inc(reason=reason)
            _log.info(
                "re-offering query %s launch to re-registered agent %s "
                "(epoch %d, %s)",
                msg.get("query_id"), agent_id, epoch, reason,
            )
            self.bus.publish(agent_topic(agent_id), msg)

    def _launch_done(self, agent_id: str, query_id: str) -> None:
        with self._launch_lock:
            self._inflight_launches.get(agent_id, {}).pop(query_id, None)

    def _estimate_staging(self, query: str) -> int:
        """Sum the staging-bytes estimates of every table the script
        names (syntactic: px.DataFrame(table='...') references — the
        estimate gates admission, it does not need plan precision).
        Returns 0 without an estimator: the check disables cleanly."""
        if self.staging_estimator is None:
            return 0
        import re

        total = 0
        for name in set(
            re.findall(r"table\s*=\s*['\"]([^'\"]+)['\"]", query)
        ):
            try:
                total += int(self.staging_estimator(name) or 0)
            except Exception:
                pass  # advisory: estimation must never fail a query
        return total

    def _estimate_seconds(self, est_bytes: int) -> float:
        """r22 advisory next to the bytes estimate: predicted staging
        seconds for the estimated footprint plus the median whole-offload
        fold — the cost model's answer to "how long will this admission
        hold its slot". 0 cold/shadow/off (the signal vanishes; nothing
        downstream rejects on it)."""
        cm = _cost_model()
        if not cm.ACTIVE or cm.SHADOW:
            return 0.0
        try:
            total = cm.estimate_seconds_for_bytes(int(est_bytes)) or 0.0
            total += cm.fold_seconds_p50() or 0.0
            return float(total)
        except Exception:
            return 0.0

    def execute_script(
        self,
        query: str,
        timeout_s: float = 30.0,
        now_ns: Optional[int] = None,
        script_args: Optional[dict] = None,
        analyze: bool = False,
        exec_funcs=None,
        on_batch=None,
        on_event: Optional[Callable[[str, dict], None]] = None,
        tenant: str = "default",
    ) -> QueryResult:
        """ExecuteScript front door. With ``flags.serving_enabled`` the
        query first passes admission control (r12): a concurrency limit
        with per-tenant weighted fair queueing (``tenant`` is the WFQ
        key) and an HBM byte-budget check — on overload it raises a
        structured ``AdmissionRejected`` instead of queueing without
        bound. Flag off: straight through, the pre-r12 behavior.

        r20: with ``flags.materialized_views`` and an attached view
        plane, plain queries (no args/exec_funcs/analyze/streaming)
        probe the ViewRegistry FIRST — a fresh matching view answers
        from its merged partial-agg state before admission ever queues
        the query (``view_hit``, the top rung of the placement
        ladder)."""
        if (
            self.views is not None
            and flags.materialized_views
            and not script_args
            and not exec_funcs
            and not analyze
            and on_batch is None
        ):
            served = self.views.try_serve(query, tenant=tenant)
            if served is not None:
                if self.placement is not None:
                    self.placement.record_view_hit()
                return served
        if not flags.serving_enabled:
            # Tenant still threads through (r15): attribution and the
            # per-tenant serving metrics don't require admission control.
            return self._execute_script_inner(
                query, timeout_s, now_ns, script_args, analyze,
                exec_funcs, on_batch, on_event, tenant=tenant,
            )
        # may raise AdmissionRejected
        est_bytes = self._estimate_staging(query)
        ticket = self.admission.acquire(
            tenant,
            estimated_bytes=est_bytes,
            estimated_seconds=self._estimate_seconds(est_bytes),
        )
        try:
            return self._execute_script_inner(
                query, timeout_s, now_ns, script_args, analyze,
                exec_funcs, on_batch, on_event,
                tenant=tenant, admission_wait_s=ticket.waited_s,
            )
        finally:
            ticket.release()

    def _execute_script_inner(
        self,
        query: str,
        timeout_s: float = 30.0,
        now_ns: Optional[int] = None,
        script_args: Optional[dict] = None,
        analyze: bool = False,
        exec_funcs=None,
        on_batch=None,
        on_event: Optional[Callable[[str, dict], None]] = None,
        tenant: Optional[str] = None,
        admission_wait_s: float = 0.0,
    ) -> QueryResult:
        """The ExecuteScript path (server.go:308 → launch_query.go:36).

        Flow control (ref: query_result_forwarder.go:502,571): the result
        subscription is bounded (flags.broker_max_pending); agents
        publishing into a full queue block up to the publish timeout, so a
        slow consumer backpressures producers instead of growing broker
        memory. Pass ``on_batch(table_name, row_batch)`` to stream batches
        to the consumer as they arrive instead of accumulating them.

        Graceful degradation (r9; ref: query_result_forwarder.go:395's
        partial forwarding with per-agent annotations): with
        ``flags.partial_results`` on, an agent that errors, misses the
        deadline, or stops heartbeating mid-query no longer fails the
        whole query — the broker unregisters the dead agent's bridges (so
        merge fragments finalize with the input they have), keeps the rows
        it received, and returns them with a structured
        ``QueryResult.degraded`` annotation. Flag off restores the r8
        raise-on-failure behavior.

        Streaming degradation events (r10): pass ``on_event(query_id,
        event)`` to learn about mid-query degradation INLINE instead of
        only from the final annotation — it fires when an agent is
        skipped at planning ({"type": "agent_skipped", "agent_id",
        "reason"}), lost ({"type": "agent_lost", "agent_id", "error"}),
        timed out ({"type": "agent_timeout", "agent_id"}), or errors
        ({"type": "agent_error", "agent_id", "error", "error_kind"}) —
        the same entries the final annotation aggregates. Exceptions from
        the callback are logged and swallowed; the final annotation is
        unchanged.

        Health-plane planning (r10, flag ``health_plane``): agents whose
        heartbeats report an OPEN device breaker for this query's program
        shape are skipped proactively at planning time and recorded in
        ``degraded.skipped`` with reason ``breaker_open`` — instead of
        being discovered sick mid-query. Half-open breakers plan
        normally (they admit their trial)."""
        qid = str(uuid.uuid4())
        _QUERIES.inc()
        # The query_id is the trace_id (utils/trace.py): spans, inline
        # degradation events, and the degraded annotation join on it.
        root_attrs = {"query_bytes": len(query)}
        if tenant is not None:
            # Admission plane (r12): who the query ran as and how long
            # it queued, joinable with the admission_wait_seconds
            # histogram on /metrics.
            root_attrs["tenant"] = tenant
            root_attrs["admission_wait_s"] = round(admission_wait_s, 6)
        root = trace.begin(
            "query",
            trace_id=qid,
            parent_id="",
            instance="broker",
            attrs=root_attrs,
        )
        root_span_id = root.span_id if root is not None else ""

        def emit(event: dict) -> None:
            if on_event is None:
                return
            try:
                # trace_id-stamped (r11 satellite): inline events and the
                # query's spans are joinable on the same key.
                on_event(qid, {"trace_id": qid, **event})
            except Exception:
                _log.exception("on_event callback failed (ignored)")
        t0 = time.perf_counter_ns()
        # r15: broker-side CPU (compile + plan) is attributed to the
        # query/tenant so host-profiler samples of this thread label
        # themselves; the forwarding loop below mostly blocks and the
        # agents attribute their own execution.
        with trace.attribution(
            qid, tenant or "default", "broker"
        ), trace.span(
            "compile", trace_id=qid, parent_id=root_span_id,
            instance="broker",
        ):
            logical = self.compiler.compile(
                query,
                self.table_relations,
                now_ns=now_ns,
                script_args=script_args,
                query_id=qid,
                exec_funcs=exec_funcs,
            )
        # Plan only over agents inside the heartbeat-expiry window; the
        # skipped list rides the degraded annotation.
        with trace.attribution(
            qid, tenant or "default", "broker"
        ), trace.span(
            "plan", trace_id=qid, parent_id=root_span_id, instance="broker"
        ) as plan_span:
            state, expired_agents = self.tracker.planning_view()
            planner = DistributedPlanner(self.registry, self.table_relations)
            # r18: admission-time placement — route the scan to the
            # agent whose HBM already holds the span (or the warmest
            # fallback) by narrowing the planner's agent->table view to
            # the pick. decide() is pure; commit() only fires once the
            # placed plan actually succeeds, so a planner refusal falls
            # through to the normal path without polluting metrics.
            plan = None
            promoted_replica = None
            placed_agent = None
            placement_outcome = None
            if self.placement is not None:
                needed = self._plan_tables(logical.fragments[0])
                pick, outcome = self.placement.decide(
                    self.tracker.failover_view(),
                    needed,
                    fold_latency=self.tracker.fold_latency_view(),
                    estimated_bytes=self._estimate_staging(query),
                )
                if pick is None and outcome == "mesh_fold":
                    # r21: the span's estimated staging exceeds every
                    # agent's HBM budget — don't force a single-agent
                    # pick; plan over the UNMODIFIED state so fragments
                    # span the fleet and per-agent folds stay inside
                    # their budgets. Commit under the "__mesh__" pseudo
                    # agent (load/inflight accounting + the outcome
                    # counter; affinity on it never matches a real pick).
                    try:
                        plan = planner.plan(logical, state)
                    except ValueError:
                        plan = None
                    if plan is not None:
                        placed_agent = "__mesh__"
                        placement_outcome = "mesh_fold"
                        self.placement.commit(
                            "__mesh__",
                            "mesh_fold",
                            needed,
                            weight=self.admission._weight(tenant or "default"),
                        )
                elif pick is not None:
                    placed_state = DistributedState(
                        agents=[
                            AgentInfo(
                                a.agent_id,
                                frozenset(a.tables) | needed
                                if a.agent_id == pick
                                else (
                                    a.tables
                                    if a.is_kelvin
                                    else frozenset(a.tables) - needed
                                ),
                                a.is_kelvin,
                            )
                            for a in state.agents
                        ]
                    )
                    try:
                        plan = planner.plan(logical, placed_state)
                    except ValueError:
                        plan = None
                    if plan is not None:
                        placed_agent, placement_outcome = pick, outcome
                        self.placement.commit(
                            pick,
                            outcome,
                            needed,
                            weight=self.admission._weight(tenant or "default"),
                        )
            if plan is None:
                # r17: with failover on, a dead owner's tables can be
                # served by a promoted replica agent instead of failing
                # the plan.
                plan, promoted_replica = self._plan_with_replica_fallback(
                    planner, logical, state
                )
            # Health plane: route around agents whose device breaker is
            # open for this query's program shape.
            breaker_skipped: list[str] = []
            if flags.health_plane:
                plan, breaker_skipped = self._plan_around_open_breakers(
                    planner, logical, plan, state
                )
            plan_span.set(
                fragments=len(plan.fragments),
                agents=len({
                    plan.executing_instance[f.fragment_id]
                    for f in plan.fragments
                }),
                **(
                    {"placed": placed_agent, "placement": placement_outcome}
                    if placed_agent is not None
                    else {}
                ),
            )
        if promoted_replica:
            # r17: a promoted replica COVERS the data the dead owner(s)
            # held — the plan scans every table the query needs, from an
            # agent advertising full replica coverage, so the expired
            # owners' data is NOT missing from this result. Suppress
            # their skip entries: the query is complete and must carry a
            # recovered annotation, not a degraded one. (Tables an
            # expired agent owned that this query never scans are
            # irrelevant to this result's completeness.)
            expired_agents = []
        skipped = [
            {"agent_id": aid, "reason": "heartbeat_expired"}
            for aid in expired_agents
        ] + [
            {"agent_id": aid, "reason": "breaker_open"}
            for aid in breaker_skipped
        ]
        skipped_agents = sorted(expired_agents + breaker_skipped)
        for entry in skipped:
            emit({"type": "agent_skipped", **entry})
        if promoted_replica:
            # r17: no alive owner held the scanned tables — a replica
            # agent was promoted at planning time.
            emit({
                "type": "replica_promoted", "agent_id": promoted_replica,
            })
        if placed_agent is not None:
            emit({
                "type": "query_placed",
                "agent_id": placed_agent,
                "outcome": placement_outcome,
            })
        compile_ns = time.perf_counter_ns() - t0

        # The broker's deadline is also the propagated per-query deadline:
        # every fragment aborts at (about) the same wall-clock moment.
        if flags.query_deadline_s > 0:
            timeout_s = min(timeout_s, flags.query_deadline_s)

        # Central bridge-producer registration over the shared router,
        # remembering which instance feeds which bridges so a dead agent's
        # producers can be unregistered mid-query.
        bridges_by_instance: dict[str, list[str]] = {}
        for frag in plan.fragments:
            inst = plan.executing_instance[frag.fragment_id]
            for nid in frag.nodes():
                op = frag.node(nid)
                if isinstance(op, BridgeSinkOp):
                    self.router.register_producer(qid, op.bridge_id)
                    bridges_by_instance.setdefault(inst, []).append(
                        op.bridge_id
                    )

        results_sub = self.bus.subscribe(
            RESULTS_TOPIC_PREFIX + qid, maxsize=flags.broker_max_pending
        )
        # Launch per-agent plans (launch_query.go:36-82).
        by_instance: dict[str, Plan] = {}
        for frag in plan.fragments:
            inst = plan.executing_instance[frag.fragment_id]
            sub = by_instance.setdefault(inst, Plan(qid))
            sub.fragments.append(frag)
            sub.executing_instance[frag.fragment_id] = inst
        # r17 failover bookkeeping: each original instance is a SLOT
        # (stable across retries) whose live attempts carry result
        # epochs; exactly one attempt's output is ever applied.
        failover = flags.fragment_failover
        hedging = failover and flags.hedged_requests
        kelvin_ids = {a.agent_id for a in state.agents if a.is_kelvin}
        slots: dict[str, dict] = {}
        t1 = time.perf_counter_ns()
        for inst, sub_plan in by_instance.items():
            msg = {
                "type": "execute_fragment",
                "query_id": qid,
                "plan": sub_plan,
                "analyze": analyze,
                "deadline_s": timeout_s,
                # Trace-context propagation (Dapper): the agent's
                # execute span parents to the broker's root span.
                "trace": {"trace_id": qid, "span_id": root_span_id},
                # Attribution propagation (r15): the agent labels its
                # execution threads (and their workers) with the tenant.
                "tenant": tenant or "default",
            }
            if failover:
                msg["slot"] = inst
                msg["result_epoch"] = 1
                slots[inst] = {
                    "plan": sub_plan,
                    "analyze": analyze,
                    "bridges": list(bridges_by_instance.get(inst, ())),
                    "needed_tables": self._plan_tables(sub_plan),
                    "is_kelvin": inst in kelvin_ids,
                    "live": {inst: 1},
                    "epoch": 1,
                    "done": False,
                    "tried": {inst},
                    "bufs": {(inst, 1): []},
                    "retried": [],
                    "retries": 0,
                    "hedge": None,
                    "hedge_at": None,
                    "lost_at": None,
                }
                for bid in slots[inst]["bridges"]:
                    self.router.authorize_producer(qid, bid, inst, 1)
            # Track BEFORE publishing (r12): if the agent re-registers
            # between our publish and its subscribe, the register
            # listener re-offers this launch instead of losing it to
            # the reconnect gap until the reaper degrades the query.
            with self._launch_lock:
                self._inflight_launches.setdefault(inst, {})[qid] = msg
            self.bus.publish(agent_topic(inst), msg)
        if hedging:
            now = time.monotonic()
            for st in slots.values():
                d = self._hedge_delay_s(st["plan"])
                st["hedge_at"] = now + d if d is not None else None

        # Forward results (query_result_forwarder.go:502,571).
        partial_ok = flags.partial_results
        tables: dict[str, list] = {}
        exec_stats: dict[str, dict] = {}
        pending: set = set(by_instance)
        deadline = time.monotonic() + timeout_s
        agent_errors: dict[str, str] = {}
        lost_agents: list[str] = []
        timed_out_agents: list[str] = []
        forward_dropped = 0
        # Spans shipped back by agents on fragment_done/fragment_error,
        # keyed by span_id: in-process agents share this module's buffer,
        # so the final merge dedups instead of double-counting.
        agent_spans: dict[str, dict] = {}
        # r15: forwarding (receiving/relaying this query's result
        # batches on the caller's thread) is per-query work too.
        fwd_attr = trace.attribution(qid, tenant or "default", "forward")
        fwd_attr.__enter__()

        # -- r17 failover machinery (no-ops when the flag is off) ------------
        def _revoke_attempt(st, slot_id, aid, ep):
            st["bufs"].pop((aid, ep), None)
            for bid in st["bridges"]:
                self.router.revoke_producer(qid, bid, slot_id, ep)

        def _launch_attempt(slot_id, st, aid, remaining):
            st["epoch"] += 1
            ep = st["epoch"]
            st["live"][aid] = ep
            st["tried"].add(aid)
            st["bufs"][(aid, ep)] = []
            for bid in st["bridges"]:
                self.router.authorize_producer(qid, bid, slot_id, ep)
            msg2 = {
                "type": "execute_fragment",
                "query_id": qid,
                "plan": st["plan"],
                "analyze": st["analyze"],
                "deadline_s": max(remaining, 0.1),
                "trace": {"trace_id": qid, "span_id": root_span_id},
                "tenant": tenant or "default",
                "slot": slot_id,
                "result_epoch": ep,
            }
            with self._launch_lock:
                self._inflight_launches.setdefault(aid, {})[qid] = msg2
            self.bus.publish(agent_topic(aid), msg2)
            return ep

        def _try_failover(slot_id, st, failed_agent, reason) -> bool:
            remaining = deadline - time.monotonic()
            if (
                st["retries"] >= max(int(flags.fragment_max_retries), 0)
                or remaining <= 0.05
            ):
                return False
            cand = self._failover_candidate(
                st["needed_tables"], st["tried"], st["is_kelvin"],
                exclude={failed_agent},
            )
            if cand is None:
                return False
            st["retries"] += 1
            ep = _launch_attempt(slot_id, st, cand, remaining)
            _RETRIES.inc(reason=reason)
            entry = {
                "slot": slot_id,
                "from": failed_agent,
                "to": cand,
                "reason": reason,
                "epoch": ep,
            }
            st["retried"].append(entry)
            emit({"type": "fragment_retry", **entry})
            if trace.ACTIVE:
                trace.record(
                    "broker.fragment_retry", 0, trace_id=qid,
                    parent_id=root_span_id, instance="broker",
                    attrs=entry,
                )
            _log.info(
                "query %s: fragment slot %s lost on %s (%s); retrying "
                "on %s at epoch %d",
                qid, slot_id, failed_agent, reason, cand, ep,
            )
            return True

        def _attempt_lost(slot_id, st, aid, ep, reason, error, kind="error"):
            """One live attempt died: revoke its bridge authorization
            and discard its buffered output (exactly-once: a dead
            attempt contributes NOTHING). A live hedge sibling keeps
            the slot; else retry; else give the slot up exactly the way
            r9 would have degraded it. Returns True while the slot is
            still going to complete (sibling or retry)."""
            st["live"].pop(aid, None)
            _revoke_attempt(st, slot_id, aid, ep)
            if st["lost_at"] is None:
                st["lost_at"] = time.monotonic()
            if st["live"]:
                return True  # a hedge sibling still owns the slot
            if _try_failover(slot_id, st, aid, reason):
                return True
            pending.discard(slot_id)
            agent_errors.setdefault(aid, error)
            if reason == "agent_lost":
                lost_agents.append(aid)
                emit({"type": "agent_lost", "agent_id": aid,
                      "error": error})
            else:
                if kind == "deadline":
                    timed_out_agents.append(aid)
                emit({
                    "type": "agent_error", "agent_id": aid,
                    "error": error, "error_kind": kind,
                })
            for bid in st["bridges"]:
                self.router.unregister_producer(qid, bid)
            return False

        def _maybe_hedge():
            now = time.monotonic()
            for s2 in list(pending):
                st = slots[s2]
                if (
                    st["hedge_at"] is None
                    or now < st["hedge_at"]
                    or len(st["live"]) != 1
                    or st["hedge"] is not None
                ):
                    continue
                (orig_aid,) = st["live"]
                cand = self._failover_candidate(
                    st["needed_tables"], st["tried"], st["is_kelvin"],
                    exclude=set(st["live"]),
                )
                if cand is None:
                    st["hedge_at"] = None  # nobody to hedge onto
                    continue
                _launch_attempt(s2, st, cand, deadline - now)
                _HEDGES.inc()
                st["hedge"] = {
                    "slot": s2, "original": orig_aid,
                    "duplicate": cand, "winner": None,
                }
                emit({
                    "type": "fragment_hedged", "slot": s2,
                    "original": orig_aid, "duplicate": cand,
                })
                if trace.ACTIVE:
                    trace.record(
                        "broker.fragment_hedged", 0, trace_id=qid,
                        parent_id=root_span_id, instance="broker",
                        attrs={"slot": s2, "duplicate": cand},
                    )

        try:
            while pending:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    if failover:
                        timed_out_agents = sorted(
                            {
                                aid
                                for s in pending
                                for aid in slots[s]["live"]
                            }
                            | {s for s in pending if not slots[s]["live"]}
                        )
                    else:
                        timed_out_agents = sorted(pending)
                    if not partial_ok:
                        raise TimeoutError(
                            f"query {qid}: {len(pending)} agents still "
                            f"running ({timed_out_agents})"
                        )
                    for inst in timed_out_agents:
                        agent_errors.setdefault(
                            inst, "deadline exceeded: no result"
                        )
                        emit({"type": "agent_timeout", "agent_id": inst})
                    break
                msg = results_sub.get(timeout=min(remaining, 0.1))
                if msg is None:
                    # Reap agents that stopped heartbeating mid-query:
                    # with failover, their attempts retry onto survivors;
                    # otherwise release their bridges so merge fragments
                    # finalize with partial input instead of stalling.
                    if failover:
                        live_agents = {
                            aid
                            for s in pending
                            for aid in slots[s]["live"]
                        }
                        for aid in self.tracker.expired_among(live_agents):
                            for s in list(pending):
                                st = slots[s]
                                if st["done"] or aid not in st["live"]:
                                    continue
                                _attempt_lost(
                                    s, st, aid, st["live"][aid],
                                    "agent_lost",
                                    "agent lost: heartbeat expired "
                                    "mid-query",
                                )
                        if hedging:
                            _maybe_hedge()
                    elif partial_ok:
                        for inst in self.tracker.expired_among(pending):
                            pending.discard(inst)
                            lost_agents.append(inst)
                            agent_errors.setdefault(
                                inst, "agent lost: heartbeat expired "
                                "mid-query"
                            )
                            emit(
                                {
                                    "type": "agent_lost",
                                    "agent_id": inst,
                                    "error": agent_errors[inst],
                                }
                            )
                            for bid in bridges_by_instance.get(inst, ()):
                                self.router.unregister_producer(qid, bid)
                    continue
                if failover and msg["type"] in (
                    "result_batch", "fragment_done", "fragment_error"
                ):
                    s = msg.get("slot")
                    st = slots.get(s)
                    aid = msg.get("agent_id")
                    ep = msg.get("result_epoch")
                    if (
                        st is None
                        or st["done"]
                        or st["live"].get(aid) != ep
                    ):
                        # Stale attempt (zombie the reaper declared dead,
                        # hedge loser, superseded epoch): exactly-once is
                        # THIS drop.
                        if msg["type"] == "fragment_done":
                            _HEDGE_BOTH.inc()
                        continue
                    if msg["type"] == "result_batch":
                        if faults.ACTIVE and faults.fires("broker.forward"):
                            # The attempt's stream is now incomplete —
                            # fail the ATTEMPT over instead of silently
                            # applying a truncated buffer. Only an
                            # UNRECOVERED drop degrades the result.
                            _FORWARD_DROPPED.inc()
                            if not _attempt_lost(
                                s, st, aid, ep, "forward_dropped",
                                "result batch dropped in the broker "
                                "forwarder",
                            ):
                                forward_dropped += 1
                            continue
                        st["bufs"][(aid, ep)].append(
                            (msg["table"], msg["batch"])
                        )
                    elif msg["type"] == "fragment_done":
                        # First completed attempt wins the slot: apply
                        # its buffered output atomically, cancel any
                        # sibling through the r9 abort path.
                        st["done"] = True
                        pending.discard(s)
                        self._launch_done(aid, qid)
                        for table, batch in st["bufs"].pop((aid, ep), ()):
                            if on_batch is not None:
                                on_batch(table, batch)
                            else:
                                tables.setdefault(table, []).append(batch)
                        for k, v in msg.get("exec_stats", {}).items():
                            exec_stats[f"{aid}/{k}"] = v
                        for sp in msg.get("spans") or ():
                            agent_spans[sp["span_id"]] = sp
                        if st["lost_at"] is not None:
                            _RECOVERY_SECONDS_H.observe(
                                time.monotonic() - st["lost_at"]
                            )
                        if st["hedge"] is not None:
                            st["hedge"]["winner"] = aid
                        siblings = {
                            a: e for a, e in st["live"].items() if a != aid
                        }
                        st["live"] = {aid: ep}
                        if siblings and not (
                            faults.ACTIVE
                            and faults.fires("hedge.both_complete")
                        ):
                            for sib, sib_ep in siblings.items():
                                _revoke_attempt(st, s, sib, sib_ep)
                                self.bus.publish(
                                    agent_topic(sib),
                                    {
                                        "type": "cancel_query",
                                        "query_id": qid,
                                        "slot": s,
                                        "result_epoch": sib_ep,
                                    },
                                )
                    else:  # fragment_error
                        self._launch_done(aid, qid)
                        for sp in msg.get("spans") or ():
                            agent_spans[sp["span_id"]] = sp
                        kind = msg.get("error_kind", "error")
                        reason = (
                            kind
                            if kind in ("restart_lost", "deadline")
                            else "agent_error"
                        )
                        _attempt_lost(
                            s, st, aid, ep, reason, msg["error"],
                            kind=kind,
                        )
                    continue
                if msg["type"] == "result_batch":
                    if faults.ACTIVE and faults.fires("broker.forward"):
                        forward_dropped += 1
                        _FORWARD_DROPPED.inc()
                        continue
                    if on_batch is not None:
                        on_batch(msg["table"], msg["batch"])
                    else:
                        tables.setdefault(msg["table"], []).append(
                            msg["batch"]
                        )
                elif msg["type"] == "fragment_done":
                    for k, v in msg.get("exec_stats", {}).items():
                        exec_stats[f"{msg['agent_id']}/{k}"] = v
                    for s in msg.get("spans") or ():
                        agent_spans[s["span_id"]] = s
                    pending.discard(msg["agent_id"])
                    self._launch_done(msg["agent_id"], qid)
                elif msg["type"] == "fragment_error":
                    aid = msg["agent_id"]
                    self._launch_done(aid, qid)
                    agent_errors[aid] = msg["error"]
                    for s in msg.get("spans") or ():
                        agent_spans[s["span_id"]] = s
                    if msg.get("error_kind") == "deadline":
                        timed_out_agents.append(aid)
                    emit(
                        {
                            "type": "agent_error",
                            "agent_id": aid,
                            "error": msg["error"],
                            "error_kind": msg.get("error_kind", "error"),
                        }
                    )
                    pending.discard(aid)
                    if partial_ok:
                        # The failed fragments produced no (or partial)
                        # bridge output: release their producer slots so
                        # downstream merge fragments finalize with what
                        # they have instead of stalling on eos markers
                        # that will never come.
                        for bid in bridges_by_instance.get(aid, ()):
                            self.router.unregister_producer(qid, bid)
        finally:
            fwd_attr.__exit__(None, None, None)
            results_sub.unsubscribe()
            if placed_agent is not None and self.placement is not None:
                # Inflight occupancy feeds the placement load tie-break.
                self.placement.release(placed_agent)
            # cleanup_query also tombstones the id: late pushes from
            # still-running fragments are dropped and their polls abort
            # (BridgeCancelled) instead of leaking buffers.
            self.router.cleanup_query(qid)
            # Drop any remaining launch records (timed-out/lost agents):
            # a finished query must never be re-offered.
            with self._launch_lock:
                for inst in list(self._inflight_launches):
                    self._inflight_launches[inst].pop(qid, None)
                    if not self._inflight_launches[inst]:
                        del self._inflight_launches[inst]
        if results_sub.dropped:
            # Result messages were dropped after the flow-control timeout:
            # the stream is incomplete because the CONSUMER is too slow —
            # that is a local flow-control failure, not a degraded cluster;
            # fail loudly rather than return partial data as success
            # (ref: the forwarder cancels the query,
            # query_result_forwarder.go:571).
            raise RuntimeError(
                f"query {qid}: consumer too slow — {results_sub.dropped} "
                "result messages dropped after "
                f"{flags.broker_publish_timeout_s}s of backpressure"
            )
        if agent_errors and not partial_ok:
            raise RuntimeError(
                f"query {qid} failed on agents:\n"
                + "\n".join(f"{a}: {e}" for a, e in sorted(agent_errors.items()))
            )
        # r17: what failover did for this query. A fully-recovered query
        # carries a ``recovered`` annotation INSTEAD of the degraded one
        # (the rows are complete and bit-identical to an unfaulted run);
        # a query that still degraded carries the attempt history inside
        # the degraded annotation for diagnosis.
        retried_all = [
            e for st in slots.values() for e in st["retried"]
        ]
        hedged_all = [
            dict(st["hedge"])
            for st in slots.values()
            if st["hedge"] is not None
        ]
        recovered = None
        degraded = None
        if partial_ok and (
            agent_errors
            or lost_agents
            or timed_out_agents
            or skipped_agents
            or forward_dropped
        ):
            reasons = []
            if lost_agents:
                reasons.append("agent_lost")
            if timed_out_agents:
                reasons.append("deadline")
            if agent_errors and set(agent_errors) - set(lost_agents) - set(
                timed_out_agents
            ):
                reasons.append("agent_error")
            if skipped_agents:
                reasons.append("agents_skipped")
            if breaker_skipped:
                reasons.append("breaker_open")
            if forward_dropped:
                reasons.append("forward_dropped")
            degraded = {
                "partial": True,
                "reasons": reasons,
                "agent_errors": dict(sorted(agent_errors.items())),
                "lost_agents": sorted(lost_agents),
                "timed_out_agents": sorted(set(timed_out_agents)),
                "skipped_agents": list(skipped_agents),
                # Structured skip entries (r10): who planning left out
                # and WHY (heartbeat_expired | breaker_open).
                "skipped": skipped,
                "forward_dropped": forward_dropped,
                # Joins the annotation to the query's spans and inline
                # events (r11 satellite; trace_id == query_id).
                "trace_id": qid,
            }
            if retried_all or hedged_all:
                degraded["failover"] = {
                    "retried": retried_all, "hedged": hedged_all,
                }
            _DEGRADED.inc()
        elif retried_all or hedged_all or promoted_replica:
            recovered = {
                "retried": retried_all,
                "hedged": hedged_all,
                "trace_id": qid,
            }
            if promoted_replica:
                recovered["promoted_replica"] = promoted_replica
            _RECOVERED_Q.inc()
        exec_ns = time.perf_counter_ns() - t1
        _QUERY_SECONDS.observe(
            (compile_ns + exec_ns) / 1e9, tenant=tenant or "default"
        )
        trace_spans = None
        if root is not None:
            root_attrs2 = None
            if degraded:
                root_attrs2 = {
                    "degraded_reasons": ",".join(degraded["reasons"])
                }
            elif recovered:
                root_attrs2 = {
                    "recovered_fragments": len(retried_all)
                    + len(hedged_all)
                }
            trace.finish(
                root,
                status="degraded" if degraded else "ok",
                attrs=root_attrs2,
            )
            # Merge broker-side spans with agent-shipped ones by span_id
            # (one trace_id across the cluster; agents that died mid-query
            # simply contribute fewer spans — the profile marks them via
            # the degraded annotation).
            merged = {
                s.span_id: s.to_dict() for s in trace.spans_for(qid)
            }
            merged.update(agent_spans)
            trace_spans = sorted(
                merged.values(), key=lambda s: s["start_unix_ns"]
            )
            if flags.trace_otel_export and trace_spans:
                self._export_otel_spans(trace_spans)
        return QueryResult(
            query_id=qid,
            tables=tables,
            exec_stats=exec_stats,
            compile_time_ns=compile_ns,
            exec_time_ns=exec_ns,
            degraded=degraded,
            recovered=recovered,
            trace_spans=trace_spans,
        )

    def _export_otel_spans(self, spans: list[dict]) -> None:
        """Optional OTel export of a finished query trace through the
        same payload shape the exec/otel_sink_node.py sink emits. The
        exporter is pluggable (``self.otel_exporter``); unset drops."""
        exporter = getattr(self, "otel_exporter", None)
        if exporter is None:
            return
        try:
            exporter(trace.spans_to_otel(spans, service="broker"))
        except Exception:
            _log.exception("otel span export failed (ignored)")

    def stop(self) -> None:
        from pixie_tpu.serving import shared_scan as _shared_scan

        _shared_scan.clear_queue_depth_fn(self._queue_depth_fn)
        if self.admission_controller is not None:
            self.admission_controller.stop()
            self.admission_controller = None
        if self.ring_rebalancer is not None:
            self.ring_rebalancer.stop()
            self.ring_rebalancer = None
        if self.views is not None:
            self.views.stop()
            self.views = None
        self.tracker.stop()
        if self._health_srv is not None:
            self._health_srv.stop()
            self._health_srv = None
