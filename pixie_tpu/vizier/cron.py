"""Cron script runner — scheduled PxL execution in the broker.

Ref: src/vizier/services/query_broker/script_runner/script_runner.go —
`ScriptRunner` syncs a persisted cron-script set (cloud `cron_script`
store; ours is the datastore-backed `CronScriptStore`), keeps one `runner`
per script with a ticker at the script's frequency
(script_runner.go:90,112), executes each tick through the query engine,
and forwards results to a sink (cloud plugins there; a table store here —
the retention-script posture without the SaaS side).
"""

from __future__ import annotations

import json
import threading
import uuid
from typing import Callable, Optional

from pixie_tpu.utils import metrics_registry
from pixie_tpu.vizier.datastore import Datastore

_M = metrics_registry()
_RUNS = _M.counter("cron_script_runs_total", "Cron script executions.")
_ERRORS = _M.counter(
    "cron_script_errors_total", "Cron script executions that failed."
)

_PREFIX = "/cron_scripts/"


class CronScript:
    """A stored scheduled script (ref: cvmsgspb CronScript fields)."""

    def __init__(
        self,
        script_id: str,
        script: str,
        frequency_s: float,
        configs: Optional[dict] = None,
    ):
        self.script_id = script_id
        self.script = script
        self.frequency_s = float(frequency_s)
        self.configs = configs or {}

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "script_id": self.script_id,
                "script": self.script,
                "frequency_s": self.frequency_s,
                "configs": self.configs,
            }
        ).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "CronScript":
        d = json.loads(raw)
        return cls(
            d["script_id"], d["script"], d["frequency_s"], d.get("configs")
        )


class CronScriptStore:
    """Persisted cron-script set (ref: metadata controllers/cronscript/
    backed by the datastore; survives broker restarts).

    ``prefix`` namespaces the stored set: planes that ride the same
    ticker machinery but own a different script population (r20: the
    materialized-view registry's maintenance scripts) get their own
    keyspace instead of leaking into the default cron set — a runner
    syncing ``/cron_scripts/`` must never tick a view script."""

    def __init__(self, datastore: Datastore, prefix: str = _PREFIX):
        self._ds = datastore
        self._prefix = prefix

    def upsert(self, script: CronScript) -> None:
        self._ds.set(self._prefix + script.script_id, script.to_json())

    def delete(self, script_id: str) -> None:
        self._ds.delete(self._prefix + script_id)

    def all(self) -> dict[str, CronScript]:
        return {
            k[len(self._prefix) :]: CronScript.from_json(v)
            for k, v in self._ds.get_prefix(self._prefix)
        }


class _Runner:
    """One scheduled script (ref: script_runner.go `runner` struct with its
    ticker goroutine)."""

    def __init__(self, script: CronScript, execute: Callable, on_error):
        self.script = script
        self._execute = execute
        self._on_error = on_error
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def _loop(self) -> None:
        # Tick at the script frequency; the first run happens one period in
        # (matches time.NewTicker semantics in script_runner.go:112).
        while not self._stop.wait(self.script.frequency_s):
            try:
                self._execute(self.script)
                _RUNS.inc(script=self.script.script_id)
            except Exception as e:  # keep ticking (ref logs and continues)
                _ERRORS.inc(script=self.script.script_id)
                self._on_error(self.script, e)


class ScriptRunner:
    """Syncs the stored script set and runs each on schedule.

    ``sink(script, query_result)`` receives each run's result; the default
    writes every result table into ``result_store`` under
    ``cron_<script_id>_<table>`` (the reference forwards to cloud plugin
    retention; a local table store is our retention surface)."""

    def __init__(
        self,
        broker,
        store: CronScriptStore,
        result_store=None,
        sink: Optional[Callable] = None,
        timeout_s: float = 30.0,
        executor: Optional[Callable] = None,
    ):
        self._broker = broker
        self.store = store
        self._result_store = result_store
        self._sink = sink
        self._timeout_s = timeout_s
        # r15: an ``executor(script)`` override replaces the default
        # broker execution per tick — the SLO manager (vizier/slo.py)
        # rides the same persisted store + ticker machinery with its
        # rule evaluator plugged in here.
        self._executor = executor
        self._runners: dict[str, _Runner] = {}
        # One lock serializes store mutation + reconcile: without it, a
        # concurrent sync() that read the store BEFORE a delete can
        # resurrect the deleted script's runner AFTER the deleter's
        # reconcile ran (caught by the r4 concurrency stress suite).
        self._lock = threading.RLock()
        self.last_errors: dict[str, str] = {}

    # -- script set management (ref: SyncScripts + update channel) ----------
    def sync(self) -> None:
        """Reconcile running tickers with the persisted set."""
        with self._lock:
            want = self.store.all()
            for sid in [s for s in self._runners if s not in want]:
                self._runners.pop(sid).stop()
            for sid, script in want.items():
                cur = self._runners.get(sid)
                if cur is not None and (
                    cur.script.script == script.script
                    and cur.script.frequency_s == script.frequency_s
                ):
                    continue
                if cur is not None:
                    cur.stop()
                r = _Runner(script, self._run_one, self._record_error)
                self._runners[sid] = r
                r.start()

    def upsert_script(self, script: CronScript) -> None:
        """Persist + (re)schedule (ref: upsert on the updates channel)."""
        with self._lock:
            self.store.upsert(script)
            self.sync()

    def delete_script(self, script_id: str) -> None:
        with self._lock:
            self.store.delete(script_id)
            self.sync()

    def stop(self) -> None:
        with self._lock:
            for r in self._runners.values():
                r.stop()
            self._runners.clear()

    # -- execution -----------------------------------------------------------
    def _run_one(self, script: CronScript) -> None:
        if self._executor is not None:
            self._executor(script)
            return
        result = self._broker.execute_script(
            script.script,
            timeout_s=self._timeout_s,
            script_args=script.configs.get("args"),
        )
        if getattr(result, "degraded", None) is not None:
            # Partial results (r9) still store/sink, but the degradation
            # is surfaced where cron failures already are.
            self.last_errors[script.script_id] = (
                f"degraded: {','.join(result.degraded['reasons'])}"
            )
        if self._sink is not None:
            self._sink(script, result)
        elif self._result_store is not None:
            self._store_result(script, result)

    def _store_result(self, script: CronScript, result) -> None:
        from pixie_tpu.table.row_batch import RowBatch

        for name, batches in result.tables.items():
            batches = [b for b in batches if b.num_rows]
            if not batches:
                continue
            merged = RowBatch.concat(batches)
            tname = f"cron_{script.script_id}_{name}"
            table = self._result_store.get_table(tname)
            if table is None:
                table = self._result_store.create_table(
                    tname, merged.relation
                )
            table.write(merged)

    def _record_error(self, script: CronScript, e: Exception) -> None:
        self.last_errors[script.script_id] = str(e)
