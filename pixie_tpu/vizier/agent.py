"""Agent runtime: an engine instance on the bus.

Ref: src/vizier/services/agent/manager/ — Manager (manager.h:102) runs the
event loop with registered MessageHandlers (:257): registration
(registration.*), heartbeats every ~5s (heartbeat.{h,cc}), query execution
(exec.{h,cc} ExecuteQueryMessageHandler). PEM-role agents hold a table
store fed by ingest; the Kelvin-role agent holds no tables and executes
merge fragments (pem_main.cc / kelvin_main.cc).
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from typing import Optional

from pixie_tpu.engine import Carnot
from pixie_tpu.exec import BridgeRouter, QueryDeadlineExceeded
from pixie_tpu.plan.plan import Plan
from pixie_tpu.vizier.bus import MessageBus, agent_topic

from pixie_tpu.utils import faults, flags, metrics_registry, trace

_log = logging.getLogger("pixie_tpu.agent")

_RECOVERY_SECONDS = metrics_registry().gauge(
    "agent_recovery_seconds",
    "Wall seconds the last agent restart recovery took (identity "
    "restore -> WAL replay -> ring re-stage -> re-register), by agent.",
)

# scaled-down from the reference's ~5s; PIXIE_TPU_AGENT_HEARTBEAT_INTERVAL_S.
HEARTBEAT_INTERVAL_S = flags.agent_heartbeat_interval_s
AGENT_STATUS_TOPIC = "agent_status"  # ref: agent_topic_listener's channel
RESULTS_TOPIC_PREFIX = "results/"


class Agent:
    """One engine instance; subscribes to Agent/<id> and executes plan
    fragments pushed by the broker (launch_query.go:36-82 pattern)."""

    def __init__(
        self,
        agent_id: str,
        bus: MessageBus,
        router: BridgeRouter,
        table_store=None,
        registry=None,
        metadata_state=None,
        is_kelvin: bool = False,
        device_executor=None,
        vizier_ctx=None,
        wal_dir: Optional[str] = None,
    ):
        self.agent_id = agent_id
        self.bus = bus
        self.is_kelvin = is_kelvin
        # Durable restart recovery (r14): with a per-agent wal_dir, the
        # agent persists its registration epoch and per-query
        # started/done markers (durability.AgentDurableState) so a
        # restarted process supersedes its zombie with a higher epoch
        # and handles re-offered launches exactly-once.
        self.durable = None
        self.recovery_info: "dict | None" = None
        self._restarted_pending = False
        if wal_dir is None and flags.wal_dir and (
            flags.durable_transport or flags.durable_resident
        ):
            # Flag-driven deployments get agent durability from the same
            # wal_dir the transport/ring spills use (RemoteBus applies
            # the identical fallback).
            wal_dir = flags.wal_dir
        if wal_dir:
            from pixie_tpu.vizier.durability import AgentDurableState

            self.durable = AgentDurableState(wal_dir, agent_id)
        self.carnot = Carnot(
            table_store=table_store,
            registry=registry,
            metadata_state=metadata_state,
            router=router,
            instance=agent_id,
            device_executor=device_executor,
            vizier_ctx=vizier_ctx,
        )
        self._sub = None
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # Registration epoch: bumped on every (re-)registration so the
        # broker's tracker can drop stale stragglers from a superseded
        # incarnation (an old connection's buffered heartbeat must not
        # resurrect pre-reconnect state; r10 satellite).
        self._epoch = 0
        # Executed-query dedup (r12): the broker re-offers unacked
        # fragment launches when we re-register after a reconnect gap;
        # when BOTH the original and the re-offer arrive, the second is
        # dropped here (one sub-plan per agent per query, so query_id is
        # the dedup key). Bounded so a long-lived agent never leaks.
        import collections

        self._seen_queries: "collections.OrderedDict[str, bool]" = (
            collections.OrderedDict()
        )

    # -- lifecycle ----------------------------------------------------------
    def _recover(self) -> None:
        """Restart recovery phase (r14), BEFORE the agent subscribes or
        registers: restore the persisted registration epoch, re-stage
        resident rings from their spill files, and collect the transport
        WAL's replay stats — so by the time the broker learns we exist,
        the rings are hot and the unacked window is already replayed
        (the RemoteBus replays at connect, i.e. before Agent.start)."""
        t0 = time.perf_counter()
        prior_epoch = self.durable.epoch()
        restarted = prior_epoch > 0
        span = trace.begin(
            "agent.recover",
            trace_id=f"recover:{self.agent_id}:{prior_epoch}",
            parent_id="",
            instance=self.agent_id,
            attrs={"agent_id": self.agent_id, "prior_epoch": prior_epoch},
        )
        self._epoch = prior_epoch
        restaged = 0
        dev = getattr(self.carnot, "device_executor", None)
        if dev is not None and hasattr(dev, "enable_resident_ingest"):
            # Sweep tables that existed BEFORE this process (the create
            # listeners only cover tables made after Carnot init): each
            # enable() recovers that table's ring from its spill.
            for t in self.carnot.table_store.tables():
                try:
                    ring = dev.enable_resident_ingest(t)
                except Exception:
                    _log.exception(
                        "ring recovery failed for table %r", t.name
                    )
                    ring = None
                if ring is not None:
                    restaged += getattr(ring, "recovered_windows", 0)
        if restarted:
            self.durable.bump_restarts()
            self._restarted_pending = True
        self.recovery_info = {
            "restarted": restarted,
            "restart_count": self.durable.restarts(),
            "wal_replayed_frames": int(
                getattr(self.bus, "wal_restored_frames", 0)
            ),
            "ring_restaged_windows": int(restaged),
            "recovery_seconds": round(time.perf_counter() - t0, 6),
        }
        _RECOVERY_SECONDS.labels(agent=self.agent_id).set(
            self.recovery_info["recovery_seconds"]
        )
        trace.finish(span, attrs=self.recovery_info)
        if restarted:
            _log.info(
                "agent %s recovered from restart #%d: %d WAL frames, "
                "%d ring windows re-staged, %.3fs",
                self.agent_id,
                self.recovery_info["restart_count"],
                self.recovery_info["wal_replayed_frames"],
                restaged,
                self.recovery_info["recovery_seconds"],
            )

    def start(self) -> None:
        if self.durable is not None:
            self._recover()
        self._sub = self.bus.subscribe(agent_topic(self.agent_id))
        # On a transport reconnect (RemoteBus backoff, r9), re-register so
        # the broker's tracker re-learns our tables without waiting a full
        # heartbeat interval (ref: re-registration after NATS reconnect).
        add_listener = getattr(self.bus, "add_reconnect_listener", None)
        if add_listener is not None:
            add_listener(self._register)
        self._register()
        t = threading.Thread(target=self._run_loop, daemon=True)
        hb = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t.start()
        hb.start()
        self._threads = [t, hb]

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        if self._sub is not None:
            self._sub.unsubscribe()

    # -- registration + heartbeat (registration.*, heartbeat.{h,cc}) --------
    def _health(self) -> "dict | None":
        """Device-executor health riding every heartbeat (r10): breaker
        state per program key, staging/compile queue depth, last fold
        latency. None when this agent has no device executor (host-only
        agents have nothing to trip)."""
        dev = getattr(self.carnot, "device_executor", None)
        snap = getattr(dev, "health_snapshot", None)
        health = None
        if snap is not None:
            try:
                health = snap()
            except Exception:
                health = None  # advisory; never fail the heartbeat
        if self.recovery_info is not None:
            # Recovery stats ride every heartbeat into the broker's
            # health plane and /statusz (wal_replayed_frames,
            # ring_restaged_windows, recovery_seconds).
            health = dict(health or {})
            health["recovery"] = self.recovery_info
        return health

    def _register(self) -> None:
        self._epoch += 1
        if self.durable is not None:
            # Persist BEFORE publishing: a crash right after this
            # register restarts with a strictly higher epoch, so the
            # tracker always supersedes the zombie entry.
            self.durable.save_epoch(self._epoch)
        msg = {
            "type": "register",
            "agent_id": self.agent_id,
            "epoch": self._epoch,
            "is_kelvin": self.is_kelvin,
            "tables": sorted(self.carnot.table_store.table_names()),
            "health": self._health(),
        }
        if self._restarted_pending:
            # First registration of a restarted incarnation: the tracker
            # distinguishes it from a plain reconnect re-register.
            msg["restarted"] = True
            self._restarted_pending = False
        self.bus.publish(AGENT_STATUS_TOPIC, msg)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(HEARTBEAT_INTERVAL_S):
            # Fault site: a silent agent (chaos tests prove the broker
            # reaps it from plans and from in-flight queries).
            if faults.ACTIVE and faults.fires_scoped(
                "agent.heartbeat", self.agent_id
            ):
                continue
            try:
                self.bus.publish(
                    AGENT_STATUS_TOPIC,
                    {
                        "type": "heartbeat",
                        "agent_id": self.agent_id,
                        "epoch": self._epoch,
                        "is_kelvin": self.is_kelvin,
                        "tables": sorted(
                            self.carnot.table_store.table_names()
                        ),
                        "ts": time.monotonic(),
                        "health": self._health(),
                    },
                )
            except (OSError, ConnectionError):
                # A dead transport must not kill the loop: the bus
                # reconnects (or the process is crashing and stop() is
                # imminent); the broker reaps us via the heartbeat
                # window either way.
                continue

    # -- query execution (exec.{h,cc}) --------------------------------------
    def _run_loop(self) -> None:
        while not self._stop.is_set():
            msg = self._sub.get(timeout=0.05)
            if msg is None:
                continue
            if msg.get("type") == "execute_fragment":
                qid = msg.get("query_id")
                if qid in self._seen_queries:
                    continue  # re-offered launch we already ran
                if self.durable is not None:
                    # Exactly-once across restart (r14): a durable
                    # ``done`` marker means the dead incarnation windowed
                    # the query's ENTIRE result stream into the transport
                    # WAL — the replay completes it; re-executing would
                    # double-apply. A ``started``-but-not-done marker
                    # means execution died mid-flight with partial output
                    # possibly applied — refuse the re-offer with a
                    # structured error (the broker degrades the query and
                    # releases our bridges) rather than re-execute into
                    # duplicate application.
                    state = self.durable.query_state(qid)
                    if state == "done":
                        continue
                    if state == "started":
                        self._refuse_restarted_query(msg)
                        continue
                self._seen_queries[qid] = True
                while len(self._seen_queries) > 512:
                    self._seen_queries.popitem(last=False)
                threading.Thread(
                    target=self._execute_fragment, args=(msg,), daemon=True
                ).start()

    def _refuse_restarted_query(self, msg: dict) -> None:
        """A launch re-offered for a query our previous incarnation died
        executing: its partial output may already be applied, so the only
        exactly-once answer is a structured failure — the broker returns
        the surviving agents' rows with a ``degraded`` annotation, exactly
        as if the agent had stayed lost (r9 contract)."""
        qid = msg["query_id"]
        _log.warning(
            "agent %s: refusing re-offered query %s (execution died "
            "mid-flight in a previous incarnation)", self.agent_id, qid,
        )
        try:
            self.bus.publish(
                RESULTS_TOPIC_PREFIX + qid,
                {
                    "type": "fragment_error",
                    "agent_id": self.agent_id,
                    "error": "agent restarted mid-execution; partial "
                    "output withheld for exactly-once delivery",
                    "error_kind": "restart_lost",
                },
            )
        except (OSError, ConnectionError):
            pass  # broker will reap us via the heartbeat window instead

    def _trace_spans_for(self, trace_id: str) -> "list | None":
        """Wire-ready copies of this process's buffered spans for one
        trace, shipped on fragment_done/fragment_error so the broker can
        assemble the cross-agent profile (dedup by span_id covers the
        in-process case where broker and agents share a buffer)."""
        if not trace.ACTIVE:
            return None
        return [s.to_dict() for s in trace.spans_for(trace_id)]

    def _execute_fragment(self, msg: dict) -> None:
        query_id = msg["query_id"]
        plan: Plan = msg["plan"]  # in-process handoff; DCN would serialize
        # Adopt the broker's propagated trace context (Dapper-style): this
        # agent's execute span — and the exec-node/device spans nested
        # under it — join the query's trace tree.
        tctx = msg.get("trace") or {}
        trace_id = tctx.get("trace_id") or query_id
        span = trace.begin(
            "agent.execute",
            trace_id=trace_id,
            parent_id=tctx.get("span_id", ""),
            instance=self.agent_id,
            attrs={"agent_id": self.agent_id},
        )
        if self.durable is not None:
            # Durably mark BEFORE any result frame can exist: a crash
            # from here until mark_done leaves a ``started`` marker, and
            # the restarted incarnation refuses the re-offer instead of
            # re-executing into duplicate application.
            self.durable.mark_started(query_id)
        try:
            if faults.ACTIVE:
                if faults.fires_scoped("agent.execute_hang", self.agent_id):
                    # Simulate an agent wedged mid-query (alive but never
                    # reporting): park until the agent stops. Chaos tests
                    # assert the broker's deadline/reaper handles us.
                    self._stop.wait(timeout=30.0)
                    return
                if faults.fires_scoped("agent.execute", self.agent_id):
                    raise faults.FaultInjectedError("agent.execute")
            # r15: this thread (and the pack/compile workers it spawns,
            # via trace.attributed) works for (query_id, tenant) — host
            # profiler stack samples and device dispatch records label
            # themselves with it.
            with trace.attribution(
                query_id, msg.get("tenant") or "default", "execute"
            ):
                with trace.context_of(span):
                    result = self.carnot.execute_plan(
                        plan,
                        analyze=msg.get("analyze", False),
                        manage_router=False,
                        deadline_s=msg.get("deadline_s"),
                    )
            rows_out = sum(
                b.num_rows for bs in result.tables.values() for b in bs
            )
            trace.finish(span, attrs={"rows_out": rows_out})
            for name, batches in result.tables.items():
                for b in batches:
                    self.bus.publish(
                        RESULTS_TOPIC_PREFIX + query_id,
                        {
                            "type": "result_batch",
                            "agent_id": self.agent_id,
                            "table": name,
                            "batch": b,
                        },
                    )
            self.bus.publish(
                RESULTS_TOPIC_PREFIX + query_id,
                {
                    "type": "fragment_done",
                    "agent_id": self.agent_id,
                    "exec_stats": result.exec_stats,
                    "spans": self._trace_spans_for(trace_id),
                },
            )
            if self.durable is not None:
                # Every result frame (batches + fragment_done) is now in
                # the transport window/WAL: replay alone completes the
                # query, so a re-offered launch is dropped, not re-run.
                self.durable.mark_done(query_id)
        except Exception as e:  # surfaced to the forwarder (ref: error chunks)
            trace.finish(span, status="error", attrs={"error": str(e)[:200]})
            self.bus.publish(
                RESULTS_TOPIC_PREFIX + query_id,
                {
                    "type": "fragment_error",
                    "agent_id": self.agent_id,
                    "error": f"{e}\n{traceback.format_exc()}",
                    # Lets the broker's degraded annotation distinguish a
                    # propagated-deadline abort from a genuine failure.
                    "error_kind": (
                        "deadline"
                        if isinstance(e, QueryDeadlineExceeded)
                        else "error"
                    ),
                    "spans": self._trace_spans_for(trace_id),
                },
            )
            if self.durable is not None:
                # The structured error is windowed: replay delivers it,
                # so this query is complete for exactly-once purposes.
                self.durable.mark_done(query_id)
