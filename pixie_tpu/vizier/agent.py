"""Agent runtime: an engine instance on the bus.

Ref: src/vizier/services/agent/manager/ — Manager (manager.h:102) runs the
event loop with registered MessageHandlers (:257): registration
(registration.*), heartbeats every ~5s (heartbeat.{h,cc}), query execution
(exec.{h,cc} ExecuteQueryMessageHandler). PEM-role agents hold a table
store fed by ingest; the Kelvin-role agent holds no tables and executes
merge fragments (pem_main.cc / kelvin_main.cc).
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from typing import Optional

from pixie_tpu.engine import Carnot
from pixie_tpu.exec import BridgeRouter, QueryDeadlineExceeded
from pixie_tpu.plan.plan import Plan
from pixie_tpu.vizier.bus import MessageBus, agent_topic

from pixie_tpu.utils import faults, flags, metrics_registry, trace

_log = logging.getLogger("pixie_tpu.agent")

_RECOVERY_SECONDS = metrics_registry().gauge(
    "agent_recovery_seconds",
    "Wall seconds the last agent restart recovery took (identity "
    "restore -> WAL replay -> ring re-stage -> re-register), by agent.",
)

# scaled-down from the reference's ~5s; PIXIE_TPU_AGENT_HEARTBEAT_INTERVAL_S.
HEARTBEAT_INTERVAL_S = flags.agent_heartbeat_interval_s
AGENT_STATUS_TOPIC = "agent_status"  # ref: agent_topic_listener's channel
RESULTS_TOPIC_PREFIX = "results/"
# Ring-replication plane (r17, flag ring_replication_factor > 1): ring
# leaders publish each staged window's encoded payload here; replica-
# capable followers subscribe and adopt windows for tables they hold.
RING_REPLICA_TOPIC = "ring_replica"


class Agent:
    """One engine instance; subscribes to Agent/<id> and executes plan
    fragments pushed by the broker (launch_query.go:36-82 pattern)."""

    def __init__(
        self,
        agent_id: str,
        bus: MessageBus,
        router: BridgeRouter,
        table_store=None,
        registry=None,
        metadata_state=None,
        is_kelvin: bool = False,
        device_executor=None,
        vizier_ctx=None,
        wal_dir: Optional[str] = None,
        owned_tables: "Optional[list[str]]" = None,
        ingest_core=None,
    ):
        self.agent_id = agent_id
        self.bus = bus
        self.is_kelvin = is_kelvin
        # r24: a PEM agent running an IngestCore advertises its ingest
        # accounting (events/drops/ladder/quarantine gauges) on every
        # heartbeat, so the broker's /statusz shows overload shedding
        # fleet-wide without scraping each host.
        self.ingest_core = ingest_core
        # Data-plane ownership (r17): ``owned_tables`` is what this agent
        # ADVERTISES for query planning. None = every table in its store
        # (the pre-r17 behavior). A REPLICA agent passes an explicit
        # subset (typically []): its store (shared/durable) holds the
        # data and its HBM may hold replicated ring windows, but the
        # planner never scans it — only fragment failover lands here,
        # via the heartbeat's ``replica_tables`` advertisement.
        self.owned_tables = (
            None if owned_tables is None else sorted(owned_tables)
        )
        # Simulated process death (fault site agent.kill_holding_fragment):
        # heartbeats stop, in-flight results are withheld, and the run
        # loop goes deaf — exactly what the broker sees when a node dies.
        self._killed = threading.Event()
        # Durable restart recovery (r14): with a per-agent wal_dir, the
        # agent persists its registration epoch and per-query
        # started/done markers (durability.AgentDurableState) so a
        # restarted process supersedes its zombie with a higher epoch
        # and handles re-offered launches exactly-once.
        self.durable = None
        self.recovery_info: "dict | None" = None
        self._restarted_pending = False
        if wal_dir is None and flags.wal_dir and (
            flags.durable_transport or flags.durable_resident
        ):
            # Flag-driven deployments get agent durability from the same
            # wal_dir the transport/ring spills use (RemoteBus applies
            # the identical fallback).
            wal_dir = flags.wal_dir
        if wal_dir:
            from pixie_tpu.vizier.durability import AgentDurableState

            self.durable = AgentDurableState(wal_dir, agent_id)
        self.carnot = Carnot(
            table_store=table_store,
            registry=registry,
            metadata_state=metadata_state,
            router=router,
            instance=agent_id,
            device_executor=device_executor,
            vizier_ctx=vizier_ctx,
        )
        self._sub = None
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # Registration epoch: bumped on every (re-)registration so the
        # broker's tracker can drop stale stragglers from a superseded
        # incarnation (an old connection's buffered heartbeat must not
        # resurrect pre-reconnect state; r10 satellite).
        self._epoch = 0
        # Executed-query dedup (r12): the broker re-offers unacked
        # fragment launches when we re-register after a reconnect gap;
        # when BOTH the original and the re-offer arrive, the second is
        # dropped here (one sub-plan per agent per query, so query_id is
        # the dedup key). Bounded so a long-lived agent never leaks.
        import collections

        # Keyed (query_id, slot, epoch) since r17: a failover RETRY of
        # the same query (higher epoch) is a fresh execution, while the
        # broker's re-offer of the SAME attempt still dedups.
        self._seen_queries: "collections.OrderedDict[tuple, bool]" = (
            collections.OrderedDict()
        )
        # Ring replication (r17): leader-side publish queue + follower-
        # side peer view, wired in start() when the factor enables it.
        self._replica_pub: "Optional[object]" = None
        self._replica_sub = None
        self._status_sub = None
        self._replica_peers: dict[str, float] = {}
        # r18: rebalancer-assigned follower sets, table -> (seq, frozenset
        # of agent ids). Overrides the deterministic rank when present.
        self._replica_assignments: dict[str, tuple[int, frozenset]] = {}

    # -- lifecycle ----------------------------------------------------------
    def _recover(self) -> None:
        """Restart recovery phase (r14), BEFORE the agent subscribes or
        registers: restore the persisted registration epoch, re-stage
        resident rings from their spill files, and collect the transport
        WAL's replay stats — so by the time the broker learns we exist,
        the rings are hot and the unacked window is already replayed
        (the RemoteBus replays at connect, i.e. before Agent.start)."""
        t0 = time.perf_counter()
        prior_epoch = self.durable.epoch()
        restarted = prior_epoch > 0
        span = trace.begin(
            "agent.recover",
            trace_id=f"recover:{self.agent_id}:{prior_epoch}",
            parent_id="",
            instance=self.agent_id,
            attrs={"agent_id": self.agent_id, "prior_epoch": prior_epoch},
        )
        self._epoch = prior_epoch
        restaged = 0
        dev = getattr(self.carnot, "device_executor", None)
        if dev is not None and hasattr(dev, "enable_resident_ingest"):
            # Sweep tables that existed BEFORE this process (the create
            # listeners only cover tables made after Carnot init): each
            # enable() recovers that table's ring from its spill.
            for t in self.carnot.table_store.tables():
                try:
                    ring = dev.enable_resident_ingest(t)
                except Exception:
                    _log.exception(
                        "ring recovery failed for table %r", t.name
                    )
                    ring = None
                if ring is not None:
                    restaged += getattr(ring, "recovered_windows", 0)
        if restarted:
            self.durable.bump_restarts()
            self._restarted_pending = True
        self.recovery_info = {
            "restarted": restarted,
            "restart_count": self.durable.restarts(),
            "wal_replayed_frames": int(
                getattr(self.bus, "wal_restored_frames", 0)
            ),
            "ring_restaged_windows": int(restaged),
            "recovery_seconds": round(time.perf_counter() - t0, 6),
        }
        _RECOVERY_SECONDS.labels(agent=self.agent_id).set(
            self.recovery_info["recovery_seconds"]
        )
        trace.finish(span, attrs=self.recovery_info)
        if restarted:
            _log.info(
                "agent %s recovered from restart #%d: %d WAL frames, "
                "%d ring windows re-staged, %.3fs",
                self.agent_id,
                self.recovery_info["restart_count"],
                self.recovery_info["wal_replayed_frames"],
                restaged,
                self.recovery_info["recovery_seconds"],
            )

    def start(self) -> None:
        if self.durable is not None:
            self._recover()
        self._sub = self.bus.subscribe(agent_topic(self.agent_id))
        self._start_replication()
        # On a transport reconnect (RemoteBus backoff, r9), re-register so
        # the broker's tracker re-learns our tables without waiting a full
        # heartbeat interval (ref: re-registration after NATS reconnect).
        add_listener = getattr(self.bus, "add_reconnect_listener", None)
        if add_listener is not None:
            add_listener(self._register)
        self._register()
        t = threading.Thread(target=self._run_loop, daemon=True)
        hb = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t.start()
        hb.start()
        self._threads = [t, hb]

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        if self._sub is not None:
            self._sub.unsubscribe()
        for sub in (self._replica_sub, self._status_sub):
            if sub is not None:
                sub.unsubscribe()

    # -- ring replication (r17) ---------------------------------------------
    def _replica_capable(self) -> bool:
        return (
            int(flags.ring_replication_factor) > 1
            and not self.is_kelvin
            and getattr(self.carnot, "device_executor", None) is not None
            and hasattr(
                self.carnot.device_executor, "adopt_replica_window"
            )
        )

    def _start_replication(self) -> None:
        """Wire both replication roles when the factor enables them:
        leader (every staged ring window's encoded payload republishes
        on RING_REPLICA_TOPIC through a small publish queue — the ring
        hook runs under the ring lock and must not block) and follower
        (a loop adopting windows for tables this agent holds, with a
        peer view from agent_status heartbeats bounding adoption to the
        first factor-1 replica-capable followers)."""
        if not self._replica_capable():
            return
        import queue

        dev = self.carnot.device_executor
        self._replica_pub = queue.Queue(maxsize=256)

        def hook(table, k, start_row, rows, wire_cols, latest_k):
            try:
                self._replica_pub.put_nowait(
                    {
                        "type": "ring_replica_window",
                        "origin": self.agent_id,
                        "table": table,
                        "window_rows": int(flags.resident_window_rows),
                        "k": int(k),
                        "start_row": int(start_row),
                        "rows": int(rows),
                        "cols": wire_cols,
                        "latest_k": int(latest_k),
                    }
                )
            except queue.Full:
                pass  # replication is best-effort; followers just lag

        dev.set_ring_replication_hook(hook)
        self._replica_sub = self.bus.subscribe(RING_REPLICA_TOPIC)
        self._status_sub = self.bus.subscribe(AGENT_STATUS_TOPIC)
        rt = threading.Thread(target=self._replica_loop, daemon=True)
        rt.start()
        self._threads.append(rt)

    def _my_replica_rank_ok(self, origin: str, table: str = None) -> bool:
        """Bound adoption to ``ring_replication_factor - 1`` followers.

        r18: a rebalancer assignment (ring_replica_assign from the
        broker) overrides the default — the agent adopts the table's
        windows iff it is in the assigned follower set. Without an
        assignment, the r17 deterministic rank applies: replica-capable
        agents learn each other from heartbeats and adopt only when
        they rank among the first factor-1 peer ids (sorted, origin
        excluded) — a choice every follower computes identically."""
        if table is not None:
            assigned = self._replica_assignments.get(table)
            if assigned is not None:
                return self.agent_id in assigned[1]
        cap = max(int(flags.ring_replication_factor) - 1, 0)
        now = time.monotonic()
        peers = sorted(
            aid
            for aid, seen in self._replica_peers.items()
            if aid != origin and now - seen < 10 * HEARTBEAT_INTERVAL_S
        )
        if self.agent_id not in peers:
            peers.append(self.agent_id)
            peers.sort()
        return self.agent_id in peers[:cap]

    def _replica_loop(self) -> None:
        dev = self.carnot.device_executor
        while not self._stop.is_set():
            msg = self._status_sub.get(timeout=0.0) if (
                self._status_sub.depth()
            ) else None
            if msg is not None:
                if msg.get("type") in ("register", "heartbeat") and (
                    msg.get("replica_ok")
                ):
                    self._replica_peers[msg["agent_id"]] = time.monotonic()
                continue
            msg = self._replica_sub.get(timeout=0.05)
            if msg is None or self._killed.is_set():
                continue
            mtype = msg.get("type")
            if mtype == "ring_replica_assign":
                # r18: rebalancer-directed follower set for one table.
                # Monotonic seq guard drops reordered/stale deliveries.
                seq = int(msg.get("seq", 0))
                cur = self._replica_assignments.get(msg["table"])
                if cur is None or seq >= cur[0]:
                    self._replica_assignments[msg["table"]] = (
                        seq,
                        frozenset(msg.get("followers") or ()),
                    )
                continue
            if mtype != "ring_replica_window":
                continue
            if msg.get("origin") == self.agent_id:
                continue  # our own publish looping back
            table = msg["table"]
            if self.carnot.table_store.get_table(table) is None:
                continue  # we could never serve a failover scan of it
            if not self._my_replica_rank_ok(msg["origin"], table):
                continue
            try:
                dev.adopt_replica_window(
                    table, msg["window_rows"], msg["k"],
                    msg["start_row"], msg["rows"], msg["cols"],
                    msg["latest_k"],
                )
            except Exception:
                _log.exception(
                    "replica adoption failed for %r (ignored)", table
                )

    def _publish_replicas(self) -> None:
        """Drain the leader-side publish queue (called from the
        heartbeat loop cadence AND opportunistically from the run
        loop so replication lag stays ~one poll interval)."""
        q = self._replica_pub
        if q is None:
            return
        while True:
            try:
                msg = q.get_nowait()
            except Exception:
                return
            try:
                self.bus.publish(RING_REPLICA_TOPIC, msg)
            except (OSError, ConnectionError):
                return

    # -- registration + heartbeat (registration.*, heartbeat.{h,cc}) --------
    def _health(self) -> "dict | None":
        """Device-executor health riding every heartbeat (r10): breaker
        state per program key, staging/compile queue depth, last fold
        latency. None when this agent has no device executor (host-only
        agents have nothing to trip)."""
        dev = getattr(self.carnot, "device_executor", None)
        snap = getattr(dev, "health_snapshot", None)
        health = None
        if snap is not None:
            try:
                health = snap()
            except Exception:
                health = None  # advisory; never fail the heartbeat
        if self.recovery_info is not None:
            # Recovery stats ride every heartbeat into the broker's
            # health plane and /statusz (wal_replayed_frames,
            # ring_restaged_windows, recovery_seconds).
            health = dict(health or {})
            health["recovery"] = self.recovery_info
        if self.ingest_core is not None:
            # r24 ingest gauges: a compact subset of each source's
            # ingest_status() — enough for the broker to see shedding
            # and quarantine fleet-wide without the full cause ledger.
            try:
                ingest = {}
                for name, st in self.ingest_core.status().items():
                    ingest[name] = {
                        "events_fed": st.get("events_fed", 0),
                        "rows_emitted": st.get("rows_emitted", 0),
                        "trackers": st.get("trackers", 0),
                        "buffer_bytes": st.get("buffer_bytes", 0),
                        "shed_level": st.get("shed_level", 0),
                        "quarantined": st.get("quarantined", 0),
                        "drops": sum(
                            n
                            for c, n in st.get("causes", {}).items()
                            if c not in ("parsed", "parsed_meta")
                        )
                        + st.get("rows_dropped_table_cap", 0)
                        + st.get("rows_dropped_push", 0),
                    }
                if ingest:
                    health = dict(health or {})
                    health["ingest"] = ingest
            except Exception:
                pass  # advisory; never fail the heartbeat
        return health

    def _advertised_tables(self) -> list[str]:
        if self.owned_tables is not None:
            return list(self.owned_tables)
        return sorted(self.carnot.table_store.table_names())

    def _replica_tables(self) -> list[str]:
        """Tables this agent can serve a failover scan for WITHOUT
        owning them (r17): every store table it does not advertise.
        Rides register/heartbeat so the broker's failover candidate
        selection (and no-owner planning fallback) can route here."""
        owned = set(self._advertised_tables())
        return sorted(
            set(self.carnot.table_store.table_names()) - owned
        )

    def _status_msg(self, kind: str) -> dict:
        msg = {
            "type": kind,
            "agent_id": self.agent_id,
            "epoch": self._epoch,
            "is_kelvin": self.is_kelvin,
            "tables": self._advertised_tables(),
            "replica_tables": self._replica_tables(),
            "health": self._health(),
        }
        if self._replica_capable():
            msg["replica_ok"] = True
        return msg

    def _register(self) -> None:
        if self._killed.is_set():
            return  # a "dead" process does not re-register
        self._epoch += 1
        if self.durable is not None:
            # Persist BEFORE publishing: a crash right after this
            # register restarts with a strictly higher epoch, so the
            # tracker always supersedes the zombie entry.
            self.durable.save_epoch(self._epoch)
        msg = self._status_msg("register")
        if self._restarted_pending:
            # First registration of a restarted incarnation: the tracker
            # distinguishes it from a plain reconnect re-register.
            msg["restarted"] = True
            self._restarted_pending = False
        self.bus.publish(AGENT_STATUS_TOPIC, msg)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(HEARTBEAT_INTERVAL_S):
            if self._killed.is_set():
                # Simulated process death (agent.kill_holding_fragment):
                # the broker must see a silent agent.
                continue
            self._publish_replicas()
            # Fault site: a silent agent (chaos tests prove the broker
            # reaps it from plans and from in-flight queries).
            if faults.ACTIVE and faults.fires_scoped(
                "agent.heartbeat", self.agent_id
            ):
                continue
            try:
                msg = self._status_msg("heartbeat")
                msg["ts"] = time.monotonic()
                self.bus.publish(AGENT_STATUS_TOPIC, msg)
            except (OSError, ConnectionError):
                # A dead transport must not kill the loop: the bus
                # reconnects (or the process is crashing and stop() is
                # imminent); the broker reaps us via the heartbeat
                # window either way.
                continue

    # -- query execution (exec.{h,cc}) --------------------------------------
    @staticmethod
    def _attempt_key(msg: dict) -> tuple:
        """Execution-attempt identity: (query_id, slot, epoch). A r17
        failover retry re-launches the SAME query_id at a higher result
        epoch — a fresh attempt, not a duplicate — while the broker's
        reconnect-gap re-offer of the same attempt still dedups."""
        return (
            msg.get("query_id"),
            msg.get("slot", ""),
            msg.get("result_epoch", 0),
        )

    @staticmethod
    def _marker_key(msg: dict) -> str:
        """Durable-marker key for an attempt: plain query_id pre-r17;
        with failover fields, each (slot, epoch) attempt is its own
        exactly-once unit (the broker's epoch filter guarantees at most
        one attempt's output is ever applied)."""
        qid = msg["query_id"]
        if msg.get("result_epoch"):
            return f"{qid}@{msg.get('slot', '')}#{msg['result_epoch']}"
        return qid

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            self._publish_replicas()
            msg = self._sub.get(timeout=0.05)
            if msg is None or self._killed.is_set():
                continue
            if msg.get("type") == "cancel_query":
                # r17 hedge-loser / failover cancellation: advisory
                # abort through the r9 cancel machinery, scoped to ONE
                # attempt — this agent may host several attempts of the
                # same query (a hedged merge landing here), and only
                # the named loser may die. Exactly-once never depends
                # on it (stale epochs drop everywhere).
                token = None
                if msg.get("result_epoch") is not None:
                    token = (msg.get("slot"), msg["result_epoch"])
                try:
                    self.carnot.cancel_query(msg["query_id"], token=token)
                except Exception:
                    _log.exception("cancel_query failed (ignored)")
                continue
            if msg.get("type") == "execute_fragment":
                akey = self._attempt_key(msg)
                if akey in self._seen_queries:
                    continue  # re-offered launch we already ran
                if self.durable is not None:
                    # Exactly-once across restart (r14): a durable
                    # ``done`` marker means the dead incarnation windowed
                    # the query's ENTIRE result stream into the transport
                    # WAL — the replay completes it; re-executing would
                    # double-apply. A ``started``-but-not-done marker
                    # means execution died mid-flight with partial output
                    # possibly applied — refuse the re-offer with a
                    # structured error (the broker degrades the query —
                    # or, with fragment_failover, retries it at a HIGHER
                    # epoch, a fresh attempt) rather than re-execute into
                    # duplicate application.
                    state = self.durable.query_state(self._marker_key(msg))
                    if state == "done":
                        continue
                    if state == "started":
                        self._refuse_restarted_query(msg)
                        continue
                self._seen_queries[akey] = True
                while len(self._seen_queries) > 512:
                    self._seen_queries.popitem(last=False)
                threading.Thread(
                    target=self._execute_fragment, args=(msg,), daemon=True
                ).start()

    def _refuse_restarted_query(self, msg: dict) -> None:
        """A launch re-offered for a query our previous incarnation died
        executing: its partial output may already be applied, so the only
        exactly-once answer is a structured failure — the broker returns
        the surviving agents' rows with a ``degraded`` annotation, exactly
        as if the agent had stayed lost (r9 contract)."""
        qid = msg["query_id"]
        _log.warning(
            "agent %s: refusing re-offered query %s (execution died "
            "mid-flight in a previous incarnation)", self.agent_id, qid,
        )
        try:
            self.bus.publish(
                RESULTS_TOPIC_PREFIX + qid,
                {
                    "type": "fragment_error",
                    "agent_id": self.agent_id,
                    "slot": msg.get("slot"),
                    "result_epoch": msg.get("result_epoch"),
                    "error": "agent restarted mid-execution; partial "
                    "output withheld for exactly-once delivery",
                    "error_kind": "restart_lost",
                },
            )
        except (OSError, ConnectionError):
            pass  # broker will reap us via the heartbeat window instead

    def _trace_spans_for(self, trace_id: str) -> "list | None":
        """Wire-ready copies of this process's buffered spans for one
        trace, shipped on fragment_done/fragment_error so the broker can
        assemble the cross-agent profile (dedup by span_id covers the
        in-process case where broker and agents share a buffer)."""
        if not trace.ACTIVE:
            return None
        return [s.to_dict() for s in trace.spans_for(trace_id)]

    def _execute_fragment(self, msg: dict) -> None:
        query_id = msg["query_id"]
        plan: Plan = msg["plan"]  # in-process handoff; DCN would serialize
        # Failover attempt identity (r17): echoed on every result frame
        # so the broker's epoch filter applies exactly one attempt's
        # output, and threaded into the exec state so bridge pushes
        # commit atomically per attempt.
        slot = msg.get("slot")
        epoch = msg.get("result_epoch")
        echo = (
            {"slot": slot, "result_epoch": epoch}
            if epoch is not None
            else {}
        )
        bridge_token = (slot, epoch) if epoch is not None else None
        marker = self._marker_key(msg)
        # Adopt the broker's propagated trace context (Dapper-style): this
        # agent's execute span — and the exec-node/device spans nested
        # under it — join the query's trace tree.
        tctx = msg.get("trace") or {}
        trace_id = tctx.get("trace_id") or query_id
        span = trace.begin(
            "agent.execute",
            trace_id=trace_id,
            parent_id=tctx.get("span_id", ""),
            instance=self.agent_id,
            attrs={"agent_id": self.agent_id},
        )
        if self.durable is not None:
            # Durably mark BEFORE any result frame can exist: a crash
            # from here until mark_done leaves a ``started`` marker, and
            # the restarted incarnation refuses the re-offer instead of
            # re-executing into duplicate application.
            self.durable.mark_started(marker)
        try:
            if faults.ACTIVE:
                if faults.fires_scoped(
                    "agent.kill_holding_fragment", self.agent_id
                ):
                    # Simulated process death while holding a fragment
                    # (r17): heartbeats stop, this attempt's results are
                    # withheld, the run loop goes deaf. The broker's
                    # reaper must fail the fragment over to a survivor.
                    self._killed.set()
                    trace.finish(
                        span, status="error",
                        attrs={"error": "killed holding fragment"},
                    )
                    return
                if faults.fires_scoped("agent.execute_hang", self.agent_id):
                    # Simulate an agent wedged mid-query (alive but never
                    # reporting): park until the agent stops. Chaos tests
                    # assert the broker's deadline/reaper handles us.
                    self._stop.wait(timeout=30.0)
                    return
                if faults.fires_scoped("agent.execute", self.agent_id):
                    raise faults.FaultInjectedError("agent.execute")
            # r15: this thread (and the pack/compile workers it spawns,
            # via trace.attributed) works for (query_id, tenant) — host
            # profiler stack samples and device dispatch records label
            # themselves with it.
            with trace.attribution(
                query_id, msg.get("tenant") or "default", "execute"
            ):
                with trace.context_of(span):
                    result = self.carnot.execute_plan(
                        plan,
                        analyze=msg.get("analyze", False),
                        manage_router=False,
                        deadline_s=msg.get("deadline_s"),
                        bridge_token=bridge_token,
                    )
            rows_out = sum(
                b.num_rows for bs in result.tables.values() for b in bs
            )
            trace.finish(span, attrs={"rows_out": rows_out})
            if self._killed.is_set():
                return  # "died" while executing: withhold everything
            if self.carnot.attempt_cancelled(query_id, bridge_token):
                # r17: the broker cancelled THIS attempt (another won
                # the slot). Its partially-aborted output must never
                # masquerade as a completed fragment — withhold it; the
                # winner's results complete the query.
                return
            for name, batches in result.tables.items():
                for b in batches:
                    self.bus.publish(
                        RESULTS_TOPIC_PREFIX + query_id,
                        {
                            "type": "result_batch",
                            "agent_id": self.agent_id,
                            "table": name,
                            "batch": b,
                            **echo,
                        },
                    )
            self.bus.publish(
                RESULTS_TOPIC_PREFIX + query_id,
                {
                    "type": "fragment_done",
                    "agent_id": self.agent_id,
                    "exec_stats": result.exec_stats,
                    "spans": self._trace_spans_for(trace_id),
                    **echo,
                },
            )
            if self.durable is not None:
                # Every result frame (batches + fragment_done) is now in
                # the transport window/WAL: replay alone completes the
                # query, so a re-offered launch is dropped, not re-run.
                self.durable.mark_done(marker)
        except Exception as e:  # surfaced to the forwarder (ref: error chunks)
            trace.finish(span, status="error", attrs={"error": str(e)[:200]})
            self.bus.publish(
                RESULTS_TOPIC_PREFIX + query_id,
                {
                    "type": "fragment_error",
                    "agent_id": self.agent_id,
                    "error": f"{e}\n{traceback.format_exc()}",
                    # Lets the broker's degraded annotation distinguish a
                    # propagated-deadline abort from a genuine failure.
                    "error_kind": (
                        "deadline"
                        if isinstance(e, QueryDeadlineExceeded)
                        else "error"
                    ),
                    "spans": self._trace_spans_for(trace_id),
                    **echo,
                },
            )
            if self.durable is not None:
                # The structured error is windowed: replay delivers it,
                # so this query is complete for exactly-once purposes.
                self.durable.mark_done(marker)
