"""SLO rules + alerting over the self-telemetry plane (r15).

Ref posture: Monarch (Adya et al., VLDB 2020) — keep the monitoring
time series queryable in memory NEXT TO the alerting layer that
evaluates declarative rules over them. Here the series are this engine's
own metrics registry and self-telemetry tables, and the evaluator rides
the existing cron machinery (vizier/cron.py): each registered rule is a
``CronScript`` persisted in a datastore-backed ``CronScriptStore``
(rules survive broker restarts) whose ticker fires the rule's
evaluation instead of a PxL execution.

Two rule kinds:

- ``metric``: a windowed predicate over the shared MetricsRegistry —
  e.g. "``broker_query_seconds`` p99 > 2s over 60s" or "``device_staged_
  bytes`` value > 80% of budget". Quantiles are computed over the
  WINDOW's bucket-count delta (the evaluator keeps the previous
  cumulative snapshot per rule), ``rate`` over the window's counter
  delta; ``value`` reads the current gauge. Label filters
  (``labels={"tenant": "X"}``) select per-tenant series — the r15
  serving metrics carry tenant labels natively.
- ``pxl``: an arbitrary PxL script executed through the broker — an
  ordinary fold over the telemetry tables (``engine_metrics``,
  ``query_spans``, ``device_dispatches``, ``hbm_usage``, ...); the
  first row of ``column`` in the result's single displayed table is the
  observed value.

On every firing/ok transition the manager (1) buffers a row for the
``alerts`` self-telemetry table (drained by
ingest/self_telemetry.flush_into like spans, so distributed queries see
it), (2) emits a structured event through
``QueryBroker.emit_alert`` (same shape family as the r10 on_event
degradation events), and (3) updates the live status served at the
broker health server's ``/alertz`` route.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Optional

from pixie_tpu.utils import metrics_registry
from pixie_tpu.utils.metrics import Histogram
from pixie_tpu.vizier.cron import CronScript, CronScriptStore, ScriptRunner
from pixie_tpu.vizier.datastore import Datastore

_M = metrics_registry()
_TRANSITIONS = _M.counter(
    "slo_alert_transitions_total",
    "SLO rule state transitions, by rule and new state.",
)
_ACTIVE_ALERTS = _M.gauge(
    "slo_active_alerts", "SLO rules currently in the firing state."
)
_EVALS = _M.counter(
    "slo_rule_evaluations_total", "SLO rule evaluations, by rule."
)

_OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}

# Pending alert-table rows (fire/clear transitions), drained by the
# self-telemetry flush exactly like the finished-span buffer.
_ROWS_LOCK = threading.Lock()
_ALERT_ROWS: "collections.deque[dict]" = collections.deque(maxlen=4096)


def drain_alert_rows() -> list[dict]:
    with _ROWS_LOCK:
        out = list(_ALERT_ROWS)
        _ALERT_ROWS.clear()
    return out


# -- windowed metric views (shared by the SLO evaluator's delta logic and
# the r16 admission controller) ----------------------------------------------


class HistogramWindow:
    """Windowed view over a cumulative Histogram: each ``tick()``
    returns the per-bucket count DELTA since the previous tick (None
    until the metric exists, an all-zero delta on an empty window), so
    quantiles reflect only the observations of the last control
    interval — the same previous-cumulative-snapshot scheme
    SLOManager._metric_value uses per rule."""

    def __init__(self, metric_name: str, registry=None, **labels):
        self._name = metric_name
        self._labels = dict(labels)
        self._registry = registry or metrics_registry()
        self._prev: Optional[list[int]] = None

    def _metric(self) -> Optional[Histogram]:
        with self._registry._lock:
            m = self._registry._metrics.get(self._name)
        return m if isinstance(m, Histogram) else None

    def tick(self) -> Optional[list[int]]:
        m = self._metric()
        if m is None:
            return None
        counts = m.merged_counts(**self._labels)
        prev = self._prev or [0] * len(counts)
        self._prev = counts
        return [c - p for c, p in zip(counts, prev)]

    def quantile(self, q: float, delta: list[int]) -> float:
        m = self._metric()
        return m.quantile_of_counts(q, delta) if m is not None else 0.0


class CounterWindow:
    """Windowed counter rate-ish view: ``tick()`` returns the total's
    delta since the previous tick (0.0 before the metric exists)."""

    def __init__(self, metric_name: str, registry=None, **labels):
        self._name = metric_name
        self._labels = dict(labels)
        self._registry = registry or metrics_registry()
        self._prev: Optional[float] = None

    def tick(self) -> float:
        with self._registry._lock:
            m = self._registry._metrics.get(self._name)
        if m is None:
            return 0.0
        total = m.total(**self._labels)
        prev = self._prev
        self._prev = total
        return max(total - prev, 0.0) if prev is not None else 0.0


@dataclasses.dataclass
class SLORule:
    """One declarative service-level objective.

    metric kind: ``metric`` + ``agg`` (p50/p90/p99 for histograms over
    the window's bucket delta; ``rate`` for counters over the window;
    ``value`` for the current gauge/counter reading) + optional
    ``labels`` filter. pxl kind: ``script`` + ``column``."""

    name: str
    kind: str = "metric"  # "metric" | "pxl"
    metric: str = ""
    labels: dict = dataclasses.field(default_factory=dict)
    agg: str = "p99"
    script: str = ""
    column: str = ""
    op: str = ">"
    threshold: float = 0.0
    window_s: float = 60.0
    interval_s: float = 5.0
    severity: str = "warning"
    description: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SLORule":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    @property
    def tenant(self) -> str:
        return str(self.labels.get("tenant", ""))


class _RuleState:
    __slots__ = (
        "state", "since_ns", "last_value", "last_eval_ns", "evals",
        "prev_counts", "prev_total", "prev_total_ns",
    )

    def __init__(self):
        self.state = "ok"
        self.since_ns = 0
        self.last_value: Optional[float] = None
        self.last_eval_ns = 0
        self.evals = 0
        # Window bookkeeping: previous cumulative histogram bucket counts
        # (quantile-over-delta) / previous counter total (rate).
        self.prev_counts: Optional[list[int]] = None
        self.prev_total: Optional[float] = None
        self.prev_total_ns = 0


class SLOManager:
    """Evaluates registered SLO rules on the cron runner's tickers and
    closes the loop: alerts table + broker events + /alertz."""

    _PREFIX = "slo-"

    def __init__(
        self,
        broker,
        datastore: Optional[Datastore] = None,
        pxl_timeout_s: float = 10.0,
    ):
        self._broker = broker
        self._registry = metrics_registry()
        self._pxl_timeout_s = pxl_timeout_s
        self._lock = threading.RLock()
        self._rules: dict[str, SLORule] = {}
        self._states: dict[str, _RuleState] = {}
        self._recent: "collections.deque[dict]" = collections.deque(
            maxlen=256
        )
        # The rules ARE cron scripts: persisted in the store (restart
        # survival), one ticker per rule at its interval, evaluation
        # plugged in as the runner's executor.
        self.store = CronScriptStore(datastore or Datastore())
        self.runner = ScriptRunner(
            broker, self.store, executor=self._evaluate_cron
        )
        # Adopt persisted rules from a previous incarnation.
        for sid, script in self.store.all().items():
            rule_d = (script.configs or {}).get("slo")
            if sid.startswith(self._PREFIX) and rule_d:
                rule = SLORule.from_dict(rule_d)
                self._rules[rule.name] = rule
                self._states[rule.name] = _RuleState()
        self.runner.sync()
        if broker is not None:
            broker.slo = self

    # -- registration --------------------------------------------------------
    def register(self, rule: SLORule) -> None:
        """Persist + schedule a rule (idempotent upsert)."""
        with self._lock:
            self._rules[rule.name] = rule
            self._states.setdefault(rule.name, _RuleState())
            self.runner.upsert_script(
                CronScript(
                    self._PREFIX + rule.name,
                    rule.script,
                    rule.interval_s,
                    configs={"slo": rule.to_dict()},
                )
            )

    def unregister(self, name: str) -> None:
        with self._lock:
            self._rules.pop(name, None)
            self._states.pop(name, None)
            self.runner.delete_script(self._PREFIX + name)

    def stop(self) -> None:
        self.runner.stop()

    # -- evaluation ----------------------------------------------------------
    def _evaluate_cron(self, script: CronScript) -> None:
        rule_d = (script.configs or {}).get("slo")
        if not rule_d:
            return
        self.evaluate(SLORule.from_dict(rule_d))

    def evaluate_all(self) -> None:
        """Deterministic tick over every registered rule (tests and the
        /alertz freshness path don't wait for the cron tickers)."""
        with self._lock:
            rules = list(self._rules.values())
        for rule in rules:
            self.evaluate(rule)

    def evaluate(self, rule: SLORule) -> Optional[float]:
        """One evaluation: observe the value, compare, transition.
        Returns the observed value (None = no data this window: the rule
        HOLDS its current state rather than flapping)."""
        _EVALS.inc(rule=rule.name)
        with self._lock:
            # The registered rule object carries the state; a cron tick
            # for a stale spec still lands on the current state slot.
            st = self._states.setdefault(rule.name, _RuleState())
            value = (
                self._pxl_value(rule)
                if rule.kind == "pxl"
                else self._metric_value(rule, st)
            )
            now_ns = time.time_ns()
            st.last_eval_ns = now_ns
            st.evals += 1
            if value is None:
                return None
            st.last_value = value
            breach = _OPS.get(rule.op, _OPS[">"])(value, rule.threshold)
            new_state = "firing" if breach else "ok"
            if new_state != st.state:
                st.state = new_state
                st.since_ns = now_ns
                self._transition(rule, new_state, value, now_ns)
            return value

    def _metric_value(
        self, rule: SLORule, st: _RuleState
    ) -> Optional[float]:
        reg = self._registry
        with reg._lock:
            metric = reg._metrics.get(rule.metric)
        if metric is None:
            return None  # metric not registered (yet): hold state
        agg = rule.agg
        if isinstance(metric, Histogram) and agg.startswith("p"):
            q = float(agg[1:]) / 100.0
            counts = metric.merged_counts(**rule.labels)
            prev = st.prev_counts or [0] * len(counts)
            delta = [c - p for c, p in zip(counts, prev)]
            st.prev_counts = counts
            if sum(delta) <= 0:
                return None  # no observations this window
            return metric.quantile_of_counts(q, delta)
        if agg == "rate":
            total = metric.total(**rule.labels)
            now_ns = time.time_ns()
            prev, prev_ns = st.prev_total, st.prev_total_ns
            st.prev_total, st.prev_total_ns = total, now_ns
            if prev is None or now_ns <= prev_ns:
                return None
            return (total - prev) / ((now_ns - prev_ns) / 1e9)
        # "value" (gauges, totals): the current reading.
        return metric.total(**rule.labels)

    def _pxl_value(self, rule: SLORule) -> Optional[float]:
        """Execute the rule's PxL through the broker — an ordinary fold
        over the (freshly flushed) telemetry tables — and read the first
        row of ``column`` from its single displayed table."""
        try:
            result = self._broker.execute_script(
                rule.script, timeout_s=self._pxl_timeout_s
            )
            table = result.table()
            if not table:
                return None
            col = rule.column or next(
                (k for k in table if k != "time_"), None
            )
            if col is None or not len(table[col]):
                return None
            return float(table[col][0])
        except Exception:
            return None  # evaluation failure holds state; cron counts it

    # -- transitions ---------------------------------------------------------
    def _transition(
        self, rule: SLORule, state: str, value: float, now_ns: int
    ) -> None:
        row = {
            "time_ns": now_ns,
            "rule": rule.name,
            "state": state,
            "severity": rule.severity,
            "value": float(value),
            "threshold": float(rule.threshold),
            "tenant": rule.tenant,
            "window_s": float(rule.window_s),
            "detail": (
                f"{rule.metric or 'pxl'} {rule.agg if rule.kind == 'metric' else rule.column} "
                f"{rule.op} {rule.threshold:g} over {rule.window_s:g}s"
            ),
        }
        with _ROWS_LOCK:
            _ALERT_ROWS.append(row)
        self._recent.append(dict(row))
        _TRANSITIONS.inc(rule=rule.name, state=state)
        _ACTIVE_ALERTS.set(
            sum(1 for s in self._states.values() if s.state == "firing")
        )
        if self._broker is not None:
            self._broker.emit_alert(
                {
                    "type": "slo_alert",
                    "rule": rule.name,
                    "state": state,
                    "severity": rule.severity,
                    "value": float(value),
                    "threshold": float(rule.threshold),
                    "tenant": rule.tenant,
                    "window_s": float(rule.window_s),
                    "description": rule.description,
                }
            )

    # -- status (/alertz) ----------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            rules = []
            for name, rule in sorted(self._rules.items()):
                st = self._states.get(name) or _RuleState()
                rules.append(
                    {
                        "rule": name,
                        "kind": rule.kind,
                        "expr": (
                            f"{rule.metric} {rule.agg}"
                            if rule.kind == "metric"
                            else f"pxl:{rule.column or 'auto'}"
                        ),
                        "labels": dict(rule.labels),
                        "op": rule.op,
                        "threshold": rule.threshold,
                        "window_s": rule.window_s,
                        "interval_s": rule.interval_s,
                        "severity": rule.severity,
                        "state": st.state,
                        "since_unix_ns": st.since_ns,
                        "last_value": st.last_value,
                        "evaluations": st.evals,
                        "description": rule.description,
                    }
                )
            return {
                "rules": rules,
                "active": [r["rule"] for r in rules if r["state"] == "firing"],
                "recent": list(self._recent)[-32:],
            }
