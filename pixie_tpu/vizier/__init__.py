"""Vizier-equivalent control plane (thin, single-process or multi-thread).

Ref: src/vizier/ — the query broker (services/query_broker/), the agent
manager runtime (services/agent/manager/), NATS message bus topics
(utils/messagebus/topic.go), heartbeat-based agent tracking with expiry
(services/metadata/controllers/agent_topic_listener.go:41,322).

TPU-native scope note (SURVEY.md §2.6): between devices the data plane is
ICI collectives inside the compiled pipeline; this control plane exists for
the host-level architecture — multiple engine instances (PEM-role data
bearers + a Kelvin-role merger) coordinated over an in-process bus that a
DCN transport can replace one-for-one.
"""

from pixie_tpu.vizier.agent import Agent
from pixie_tpu.vizier.broker import QueryBroker
from pixie_tpu.vizier.bus import MessageBus

__all__ = ["Agent", "MessageBus", "QueryBroker"]
