"""TCP transport: the cross-process/cross-host control bus + data plane.

Ref: the reference runs NATS for control (src/common/event/nats.{h,cc},
messagebus/topic.go) and gRPC TransferResultChunk streams for data
(src/carnot/exec/grpc_router.h:53, carnotpb/carnot.proto:99) — both
TLS-authenticated protobuf planes (src/shared/services/). Here one framed
TCP connection per remote agent carries both: bus publishes /
subscriptions (control) and bridge register/push frames (data). Every
frame crosses as the typed wire format (pixie_tpu/vizier/wire.py — the
planpb-equivalent closed schema); network bytes are NEVER unpickled.
Connections start with a mutual HMAC-SHA256 challenge/response over the
pre-shared ``cluster_secret`` flag — the trusted-cluster floor standing in
for the reference's TLS+JWT bootstrap. Without a secret configured, only
loopback binds/connects are allowed.

Topology: the broker process runs a BusTransportServer bound to its local
MessageBus + BridgeRouter; each remote agent process connects a RemoteBus
(+ RemoteRouter on the same connection). PEM-side fragments only *push*
to bridges (the splitter cuts before blocking ops), so RemoteRouter is
send-only; merge-side consumption happens in the broker process's router.
"""

from __future__ import annotations

import collections
import hmac
import ipaddress
import logging
import os
import random
import socket
import ssl
import struct
import threading
import time
import uuid
from typing import Any, Optional

from pixie_tpu.exec.router import BridgeRouter
from pixie_tpu.utils import faults, flags, metrics_registry, trace
from pixie_tpu.utils.config import define_flag
from pixie_tpu.vizier import wire
from pixie_tpu.vizier.bus import MessageBus

define_flag(
    "cluster_secret",
    "",
    help_="Pre-shared secret authenticating transport connections "
    "(HMAC-SHA256 challenge/response). Empty restricts the transport to "
    "loopback (ref posture: src/shared/services/ TLS+JWT bootstrap).",
)

define_flag(
    "transport_handshake_timeout_s",
    10.0,
    help_="Socket timeout covering the TLS+HMAC handshake on both ends "
    "(was hard-coded 10s server-side). A silent peer's half-open "
    "connection is closed at the timeout instead of pinning a thread.",
)

define_flag(
    "transport_ack_window",
    256,
    help_="Max in-flight (sent-but-unacked) frames a RemoteBus plane "
    "keeps for cross-reconnect replay (Kafka idempotent-producer shape: "
    "identity + epoch + per-plane seq surviving reconnects, cumulative "
    "acks bounding the window). 0 disables acked delivery entirely — no "
    "window bookkeeping, no server acks (r9 retry-on-fresh-connection "
    "behavior, but with the per-identity dedup watermark kept).",
)
define_flag(
    "transport_ack_window_mb",
    8.0,
    help_="Byte bound on the in-flight window (encoded frame bytes); "
    "whichever of frames/bytes fills first blocks the sender.",
)
define_flag(
    "transport_ack_interval",
    32,
    help_="Server emits a cumulative ack at least every N applied "
    "seq-carrying frames (piggybacked on the receive loop).",
)
define_flag(
    "transport_ack_interval_ms",
    25.0,
    help_="Server ack flush period: acks for a quiet tail of frames are "
    "batched at most this long before a standalone ack frame is sent.",
)
define_flag(
    "transport_window_block_s",
    10.0,
    help_="How long a sender blocks on a full in-flight window "
    "(backpressure) before TransportBackpressureError is raised — a "
    "structured transport error, never silent loss.",
)

_RECONNECTS = metrics_registry().counter(
    "transport_reconnect_total",
    "Successful RemoteBus plane reconnects after a connection failure.",
)
_DEDUP_DROPS = metrics_registry().counter(
    "transport_dedup_dropped_total",
    "Duplicate/replayed frames dropped by the server's per-identity "
    "(agent_id, plane) seq watermark.",
)
_REPLAYS = metrics_registry().counter(
    "transport_replayed_total",
    "Unacked window frames replayed onto a fresh connection.",
)
_ACKS_SENT = metrics_registry().counter(
    "transport_ack_sent_total",
    "Cumulative ack frames emitted by the server.",
)
_SESSION_REJECTS = metrics_registry().counter(
    "transport_session_rejected_total",
    "Session frames rejected for a stale epoch (zombie connections).",
)
_ACK_LATENCY = metrics_registry().histogram(
    "transport_ack_latency_seconds",
    "Client-observed send->cumulative-ack latency per windowed frame, "
    "by plane (reconnect replays keep the ORIGINAL send time: the span "
    "covers first transmission to final acknowledgement).",
)
_WAL_REPLAYS = metrics_registry().counter(
    "transport_wal_replayed_total",
    "Window frames replayed from the durable WAL spill (restart "
    "recovery and spilled-entry reconnect replays), by plane.",
)
_RESTART_SESSIONS = metrics_registry().counter(
    "transport_restart_sessions_total",
    "Sessions presented by a RESTARTED process (persisted identity with "
    "a bumped epoch) — distinct from plain reconnects, by plane.",
)


class TransportBackpressureError(ConnectionError):
    """The in-flight ack window stayed full past transport_window_block_s:
    the peer is not draining (or acks are lost). Structured so callers can
    distinguish backpressure from a dead connection."""

    def __init__(self, plane: str, frames: int, nbytes: int):
        super().__init__(
            f"transport {plane} plane: in-flight window full "
            f"({frames} frames / {nbytes} bytes) for "
            f"{flags.transport_window_block_s}s — peer not acking"
        )
        self.plane = plane
        self.frames = frames
        self.nbytes = nbytes


class _AckWindow:
    """Client-side bounded window of stamped-but-unacked frames, one per
    plane. The seq counter is per-IDENTITY, not per-connection: it never
    resets for the life of the RemoteBus, so the server's (agent_id,
    plane) watermark stays meaningful across reconnects. After a
    reconnect, ``replay_payloads`` returns everything above the server's
    applied watermark — the replay source that closes the r9 retry
    ambiguity (frames the OLD connection may have delivered are either
    trimmed here via the server's watermark, or dropped server-side by
    per-identity dedup).

    Durable spill (r14, ``wal`` = a durability.TransportWAL): every
    windowed frame's encoded bytes are appended to the WAL before the
    wire sees them, and only ``transport_wal_mem_frames`` frames stay
    decoded in memory — older entries keep (seq, nbytes) only and are
    re-read from the WAL at replay time. On restart the window restores
    its pending set, seq counter, and ack watermark from the WAL, so the
    replay that closes the crash hole is exactly the reconnect replay."""

    def __init__(self, plane: str, wal=None):
        self.plane = plane
        self._cv = threading.Condition()
        # [seq, encoded bytes, stamped frame | None (spilled), first-send
        # perf_counter_ns] in ascending-seq order. The send time is
        # stamped ONCE — replays keep it, so the ack-latency span covers
        # first transmission to final acknowledgement across reconnects.
        # send_ns == 0 marks a frame restored from the WAL (no latency
        # span: its original send time died with the old process).
        self._entries: "collections.deque" = collections.deque()
        self._bytes = 0
        self.next_seq = 0
        self.acked = -1
        self._wal = wal
        self._mem_frames = 0  # entries currently holding a decoded frame
        self.restored_frames = 0
        if wal is not None:
            pending = wal.pending(plane)
            for seq, nbytes in pending:
                self._entries.append([seq, nbytes, None, 0])
                self._bytes += nbytes
            self.next_seq = wal.next_seq(plane)
            self.acked = wal.released(plane)
            self.restored_frames = len(pending)

    @property
    def enabled(self) -> bool:
        return flags.transport_ack_window > 0

    def stamp(self, obj: dict) -> dict:
        frame = dict(obj)
        frame["seq"] = self.next_seq
        self.next_seq += 1
        if trace.ACTIVE and "trace_id" not in frame:
            # Propagate the sender thread's trace context onto the wire
            # (wire.py OPTIONAL_FRAME_FIELDS): ack spans for this frame
            # join the originating query's trace.
            ctx = trace.current()
            if ctx is not None:
                frame["trace_id"], frame["span_id"] = ctx
        return frame

    def _release(self, entry, now_pc_ns: "int | None" = None) -> None:
        """One windowed frame left the window (cumulative ack or a
        reconnect's watermark trim — either way the server APPLIED it):
        emit its send->ack latency exactly once per seq, as a histogram
        sample always and a trace span when the frame carried (or the
        window owns) a trace context."""
        seq, _, frame, send_ns = entry
        if send_ns == 0:
            return  # WAL-restored: the original send time died with us
        now = now_pc_ns if now_pc_ns is not None else time.perf_counter_ns()
        lat_ns = max(0, now - send_ns)
        _ACK_LATENCY.observe(lat_ns / 1e9, plane=self.plane)
        if frame is None:
            return  # spilled to the WAL: no trace context in memory
        if trace.ACTIVE:
            trace.record(
                "transport.ack",
                lat_ns,
                trace_id=frame.get("trace_id")
                or f"transport:{self.plane}",
                parent_id=frame.get("span_id", ""),
                attrs={
                    "plane": self.plane,
                    "seq": seq,
                    "kind": str(frame.get("kind", "")),
                },
            )

    def depth(self) -> tuple[int, int]:
        with self._cv:
            return len(self._entries), self._bytes

    def add(self, frame: dict, payload: bytes, force: bool = False) -> None:
        """Track a stamped frame until acked. Blocks (backpressure) while
        the window is full, up to transport_window_block_s, then raises
        TransportBackpressureError. ``force`` skips the bound (internal
        reconnect frames must not deadlock inside the replay path). With
        a WAL attached, the encoded payload is appended durably BEFORE
        the entry joins the window, and frames beyond the
        transport_wal_mem_frames bound spill: the window keeps only
        (seq, nbytes) and replay re-reads the bytes from disk."""
        nbytes = len(payload)
        max_frames = flags.transport_ack_window
        max_bytes = int(flags.transport_ack_window_mb * (1 << 20))
        with self._cv:
            if not force:
                deadline = time.monotonic() + flags.transport_window_block_s
                while self._entries and (
                    len(self._entries) >= max_frames
                    or self._bytes + nbytes > max_bytes
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TransportBackpressureError(
                            self.plane, len(self._entries), self._bytes
                        )
                    self._cv.wait(remaining)
            keep: "dict | None" = frame
            if self._wal is not None:
                self._wal.append_frame(self.plane, frame["seq"], payload)
                if self._mem_frames >= max(
                    int(flags.transport_wal_mem_frames), 1
                ):
                    keep = None  # spilled: the WAL holds the bytes
                else:
                    self._mem_frames += 1
            self._entries.append(
                [frame["seq"], nbytes, keep, time.perf_counter_ns()]
            )
            self._bytes += nbytes

    def ack(self, seq: int) -> None:
        """Cumulative ack: release every entry with seq' <= seq."""
        released = []
        with self._cv:
            if seq <= self.acked:
                return
            self.acked = seq
            while self._entries and self._entries[0][0] <= seq:
                entry = self._entries.popleft()
                self._bytes -= entry[1]
                if entry[2] is not None and self._wal is not None:
                    self._mem_frames -= 1
                released.append(entry)
            self._cv.notify_all()
        if released and self._wal is not None:
            self._wal.release(self.plane, released[-1][0])
        now = time.perf_counter_ns()
        for entry in released:
            self._release(entry, now)

    def wait_drained(self, deadline: float) -> bool:
        """Block until every in-flight frame is acked (graceful close)
        or ``deadline`` (monotonic) passes. True iff drained."""
        with self._cv:
            while self._entries:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True

    def replay_payloads(self, server_applied_seq: int) -> list[bytes]:
        """Encoded frames to resend on a fresh connection: everything
        above the server's per-identity applied watermark. Entries at or
        below it WERE delivered by the old connection — trimmed here
        (and were a replay to happen anyway, the server's watermark
        drops it; the transport.replay_dup fault site forces exactly
        that path). Spilled/restored entries (frame is None) re-read
        their bytes from the WAL — the restart-recovery replay source."""
        released = []
        with self._cv:
            if not (faults.ACTIVE and faults.fires("transport.replay_dup")):
                while (
                    self._entries
                    and self._entries[0][0] <= server_applied_seq
                ):
                    entry = self._entries.popleft()
                    self._bytes -= entry[1]
                    if entry[2] is not None and self._wal is not None:
                        self._mem_frames -= 1
                    released.append(entry)
                if server_applied_seq > self.acked:
                    self.acked = server_applied_seq
                self._cv.notify_all()
            entries = list(self._entries)
        if released and self._wal is not None:
            self._wal.release(self.plane, released[-1][0])
        # Watermark-trimmed entries WERE applied by the old connection:
        # their ack span closes here, once, with the original send time.
        for entry in released:
            self._release(entry)
        spilled = [e[0] for e in entries if e[2] is None]
        from_wal = (
            self._wal.payloads(self.plane, spilled)
            if spilled and self._wal is not None
            else {}
        )
        out: list[bytes] = []
        wal_count = 0
        for seq, _nbytes, frame, _send_ns in entries:
            if frame is not None:
                out.append(wire.encode(frame))
                continue
            payload = from_wal.get(seq)
            if payload is None:
                # Unrecoverable spill (should not happen: the WAL append
                # precedes windowing). Skipping is safe for delivery
                # semantics — the server either already applied this seq
                # (watermark) or the sender will surface the loss.
                _log.error(
                    "transport %s: WAL lost spilled frame seq=%d",
                    self.plane, seq,
                )
                continue
            out.append(payload)
            wal_count += 1
        if wal_count:
            _WAL_REPLAYS.inc(wal_count, plane=self.plane)
        return out

define_flag(
    "tls_cert",
    "",
    help_="PEM certificate chain for transport TLS (ref: the reference "
    "runs TLS on every plane, src/shared/services/). Servers present it; "
    "clients present it too when tls_ca demands mutual auth. Empty "
    "disables TLS (HMAC-only trusted-cluster floor).",
)
define_flag(
    "tls_key", "", help_="PEM private key for tls_cert (empty: key is "
    "embedded in the cert file)."
)
define_flag(
    "tls_ca",
    "",
    help_="PEM CA bundle: servers require client certificates signed by "
    "it (mutual TLS); clients verify the server against it. Certificates "
    "are cluster-internal and pinned by this private CA, so hostname "
    "checking is off (agents dial IPs).",
)


def _tls_server_context() -> Optional[ssl.SSLContext]:
    if not flags.tls_cert:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(flags.tls_cert, flags.tls_key or None)
    if flags.tls_ca:
        ctx.load_verify_locations(flags.tls_ca)
        ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
    return ctx


def _tls_client_context() -> Optional[ssl.SSLContext]:
    if not (flags.tls_ca or flags.tls_cert):
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False  # private-CA-pinned certs, dialed by IP
    if flags.tls_ca:
        ctx.load_verify_locations(flags.tls_ca)
        ctx.verify_mode = ssl.CERT_REQUIRED
    else:
        ctx.verify_mode = ssl.CERT_NONE  # HMAC still authenticates
    if flags.tls_cert:
        ctx.load_cert_chain(flags.tls_cert, flags.tls_key or None)
    return ctx

_LEN = struct.Struct(">Q")
_NONCE_BYTES = 16
_log = logging.getLogger("pixie_tpu.transport")


def _is_loopback(host: str) -> bool:
    # NOTE: '' binds INADDR_ANY for servers — it is NOT loopback.
    if host == "localhost":
        return True
    try:
        return ipaddress.ip_address(host).is_loopback
    except ValueError:
        return False


def _mac(secret: str, nonce: bytes) -> bytes:
    return hmac.new(secret.encode(), nonce, "sha256").digest()


def _send_frame(sock: socket.socket, obj: dict) -> None:
    payload = wire.encode(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    # recv_into a preallocated buffer: O(n), not the quadratic bytes+=
    # (row-batch frames reach hundreds of MB).
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            return None
        got += r
    return bytes(buf)


_HANDSHAKE_MAX_FRAME = 1 << 12  # hello/challenge are ~100 bytes


def _recv_frame(
    sock: socket.socket,
    max_len: Optional[int] = None,
    pre_auth: bool = False,
) -> Optional[dict]:
    """Next decoded frame, or None on EOF. Raises wire.WireError (or a
    ValueError subclass) on malformed content — callers treat that as a
    hostile/broken peer and drop the connection. ``max_len`` caps the
    attacker-controlled length word BEFORE allocation; ``pre_auth`` reads
    additionally refuse array/batch nodes, whose forged numpy headers are
    allocation bombs the length cap cannot see. The two are independent:
    a capped post-auth read must still decode batches."""
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    if max_len is not None and n > max_len:
        raise wire.WireError(f"frame length {n} exceeds cap {max_len}")
    payload = _recv_exact(sock, n)
    if payload is None:
        return None
    try:
        frame = wire.decode(payload, allow_arrays=not pre_auth)
    except wire.WireError:
        raise
    except Exception as e:  # unhashable map keys, bad npy, ...
        raise wire.WireError(f"malformed frame: {e}") from None
    if not isinstance(frame, dict) or not isinstance(frame.get("kind"), str):
        raise wire.WireError("frame is not a kind-tagged message")
    return frame


def _server_handshake(conn: socket.socket, secret: str) -> bool:
    """Mutual challenge/response (server side). Server challenges first;
    the client's response proves it holds the secret before any frame is
    acted on; the server's counter-MAC proves the same to the client."""
    if faults.ACTIVE and faults.fires("transport.handshake"):
        return False
    nonce = os.urandom(_NONCE_BYTES)
    _send_frame(conn, {"kind": "challenge", "nonce": nonce})
    frame = _recv_frame(conn, max_len=_HANDSHAKE_MAX_FRAME, pre_auth=True)
    if (
        frame is None
        or frame.get("kind") != "hello"
        or not isinstance(frame.get("mac"), bytes)
        or not isinstance(frame.get("nonce"), bytes)
        or not hmac.compare_digest(frame["mac"], _mac(secret, nonce))
    ):
        return False
    _send_frame(conn, {"kind": "welcome", "mac": _mac(secret, frame["nonce"])})
    return True


def _client_handshake(sock: socket.socket, secret: str) -> None:
    if faults.ACTIVE and faults.fires("transport.handshake"):
        raise ConnectionError("fault injected: transport.handshake")
    frame = _recv_frame(sock, max_len=_HANDSHAKE_MAX_FRAME, pre_auth=True)
    if frame is None or frame.get("kind") != "challenge" or not isinstance(
        frame.get("nonce"), bytes
    ):
        raise ConnectionError("transport handshake: no challenge from server")
    nonce = os.urandom(_NONCE_BYTES)
    _send_frame(
        sock,
        {"kind": "hello", "mac": _mac(secret, frame["nonce"]), "nonce": nonce},
    )
    resp = _recv_frame(sock, max_len=_HANDSHAKE_MAX_FRAME, pre_auth=True)
    if (
        resp is None
        or resp.get("kind") != "welcome"
        or not isinstance(resp.get("mac"), bytes)
        or not hmac.compare_digest(resp["mac"], _mac(secret, nonce))
    ):
        raise ConnectionError("transport handshake: server failed to authenticate")


def _no_delay(sock: socket.socket) -> None:
    """Disable Nagle: the control plane is small back-to-back frames
    (session → replay → resubscribe → register), and Nagle + delayed-ACK
    holds every second small write for ~40ms — long enough for a broker
    to launch a query before the resubscribe lands. The reference's
    planes (gRPC, NATS) both run with TCP_NODELAY for the same reason."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # non-TCP transports (tests with socketpairs)


def _close(sock: socket.socket) -> None:
    """shutdown() before close(): a reader blocked in recv on either end
    only wakes on FIN, which close() alone does not send while another
    thread holds the fd open."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class BusTransportServer:
    """Accepts remote-agent connections; bridges them onto the local
    MessageBus and BridgeRouter (the broker side)."""

    def __init__(
        self,
        bus: MessageBus,
        router: BridgeRouter,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.bus = bus
        self.router = router
        self._secret = flags.cluster_secret
        self._tls = _tls_server_context()
        # Binding off-loopback needs a real authenticator: the HMAC secret
        # or mutual TLS (cert + required client CA).
        mutual_tls = self._tls is not None and bool(flags.tls_ca)
        if not self._secret and not mutual_tls and not _is_loopback(host):
            raise ValueError(
                f"refusing to bind transport on non-loopback {host!r} "
                "without a cluster_secret (set PIXIE_TPU_CLUSTER_SECRET) "
                "or mutual TLS (tls_cert + tls_ca)"
            )
        self._srv = socket.create_server((host, port))
        self.address = self._srv.getsockname()
        self._stop = threading.Event()
        self._conns: list[socket.socket] = []
        self._threads: list[threading.Thread] = []
        # Per-identity delivery state surviving reconnects (the tentpole):
        # (agent_id, plane) -> {"epoch", "last_seq" (dedup watermark),
        # "applied_seq" (ack watermark), "conn"}. A fresh connection
        # presenting a session with a HIGHER epoch takes the identity over
        # (the old socket is closed and its loop exits before it can
        # interleave); a stale epoch is rejected outright.
        self._idents: dict[tuple[str, str], dict] = {}
        self._idents_lock = threading.Lock()
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            _no_delay(conn)
            self._conns.append(conn)
            t = threading.Thread(
                target=self._conn_loop, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _establish_session(self, conn, send_lock, frame) -> Optional[dict]:
        """Register a session frame against the identity registry. Returns
        the shared per-identity entry, or None when the epoch is stale
        (the connection must be dropped; a zombie socket's identity was
        already taken over by a newer epoch)."""
        wire.validate_frame(frame)
        key = (frame["agent_id"], frame["plane"])
        epoch = frame["epoch"]
        old_conn = None
        with self._idents_lock:
            entry = self._idents.get(key)
            if entry is not None and epoch <= entry["epoch"]:
                stale_epoch = entry["epoch"]
                entry = None
            else:
                if entry is None:
                    entry = self._idents[key] = {
                        "epoch": epoch,
                        "last_seq": -1,
                        "applied_seq": -1,
                        "conn": conn,
                        "lock": threading.Lock(),
                    }
                else:
                    old_conn = entry["conn"]
                    # Under the entry lock so the takeover serializes
                    # with the zombie's claim-and-dispatch step.
                    with entry["lock"]:
                        entry["epoch"] = epoch
                        entry["conn"] = conn
        if entry is None:
            _SESSION_REJECTS.inc()
            _log.warning(
                "transport: rejecting stale epoch %d for %s (current %d)",
                epoch, key, stale_epoch,
            )
            try:
                with send_lock:
                    _send_frame(
                        conn,
                        {
                            "kind": "session_reject",
                            "reason": f"stale epoch {epoch}",
                        },
                    )
            except OSError:
                pass
            return None
        if old_conn is not None and old_conn is not conn:
            _close(old_conn)  # the superseded zombie cannot interleave
        if frame.get("restarted"):
            # Restart (persisted identity + bumped epoch after process
            # death) vs plain reconnect: counted separately so operators
            # can tell crash-recovery traffic from network flaps.
            _RESTART_SESSIONS.inc(plane=frame["plane"])
        with send_lock:
            _send_frame(
                conn,
                {"kind": "session_ok", "last_seq": entry["applied_seq"]},
            )
        return entry

    def _maybe_ack(self, conn, send_lock, entry, ack_state, force) -> None:
        """Cumulative ack of everything dispatched so far; batched every
        transport_ack_interval frames, flushed every
        transport_ack_interval_ms by the per-connection ack loop."""
        applied = entry["applied_seq"]
        if applied <= ack_state["acked"]:
            return
        if (
            not force
            and applied - ack_state["acked"] < flags.transport_ack_interval
        ):
            return
        if faults.ACTIVE and faults.fires("transport.ack_drop"):
            return  # the ack frame is lost on the wire; a later one covers
        with send_lock:
            _send_frame(conn, {"kind": "ack", "seq": applied})
        ack_state["acked"] = applied
        _ACKS_SENT.inc()

    def _ack_loop(self, conn, send_lock, conn_dead, entry, ack_state):
        """Flush a quiet tail of unacked frames so the client's window
        drains even when no further traffic piggybacks an ack."""
        while not (self._stop.is_set() or conn_dead.is_set()):
            if conn_dead.wait(flags.transport_ack_interval_ms / 1000.0):
                return
            if entry["conn"] is not conn:
                return  # superseded by a newer epoch
            try:
                self._maybe_ack(conn, send_lock, entry, ack_state, force=True)
            except OSError:
                return

    def _conn_loop(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        conn_dead = threading.Event()  # per-connection: stops forwarders
        subs: dict[str, tuple] = {}  # topic -> (bus sub, stop event)
        # Dedup watermark (r10: per-IDENTITY, surviving reconnects):
        # clients stamp a monotonically increasing per-plane ``seq`` on
        # every frame; a replayed/duplicated frame (reconnect replay,
        # injected duplication) is dropped at the watermark so result rows
        # and producer registrations stay exactly-once ACROSS connections.
        # A client that never sends a session frame gets a per-connection
        # entry (legacy r9 semantics).
        entry = {
            "epoch": -1,
            "last_seq": -1,
            "applied_seq": -1,
            "conn": conn,
            "lock": threading.Lock(),
        }
        want_ack = False
        plane = "legacy"  # session-declared plane (fault-site scope)
        ack_state = {"acked": -1}
        try:
            try:
                # Bounded pre-auth hold time: a silent peer must not pin
                # this thread forever (the half-open socket is closed in
                # the finally below). Cleared once authenticated.
                conn.settimeout(flags.transport_handshake_timeout_s)
                if self._tls is not None:
                    # TLS first; the HMAC challenge/response then runs
                    # INSIDE the tunnel (defense in depth: the secret
                    # never rides plaintext, frames get confidentiality
                    # + integrity the bare HMAC handshake lacked).
                    conn = self._tls.wrap_socket(conn, server_side=True)
                if not _server_handshake(conn, self._secret):
                    _log.warning("transport: rejecting unauthenticated peer")
                    return
                conn.settimeout(None)
            except (wire.WireError, OSError, ConnectionError) as e:
                _log.warning("transport: handshake failed: %s", e)
                return
            frame = None
            first = True
            while not self._stop.is_set():
                if frame is None:
                    try:
                        frame = _recv_frame(conn)
                    except wire.WireError as e:
                        # Hostile or corrupted peer: drop this connection.
                        _log.warning("transport: dropping connection: %s", e)
                        return
                    except OSError:
                        return  # closed under us (shutdown or peer reset)
                if frame is None:
                    return
                if first:
                    first = False
                    if frame.get("kind") == "session":
                        try:
                            entry = self._establish_session(
                                conn, send_lock, frame
                            )
                        except (wire.WireError, OSError) as e:
                            _log.warning(
                                "transport: bad session frame: %s", e
                            )
                            return
                        if entry is None:
                            return  # stale epoch
                        want_ack = bool(frame.get("want_ack"))
                        plane = frame["plane"]
                        if want_ack:
                            at = threading.Thread(
                                target=self._ack_loop,
                                args=(
                                    conn, send_lock, conn_dead, entry,
                                    ack_state,
                                ),
                                daemon=True,
                            )
                            at.start()
                        frame = None
                        continue
                frames = [frame]
                if (
                    faults.ACTIVE
                    and frame.get("kind") in ("publish", "bridge_push")
                    and faults.fires("transport.recv_dup")
                ):
                    frames.append(frame)  # injected duplicate delivery
                try:
                    for fr in frames:
                        seq = fr.get("seq")
                        # Supersede check + dedup + watermark claim are
                        # one atomic step per identity: a zombie racing
                        # its replacement's replay must either claim the
                        # seq first (the replay copy is then dropped) or
                        # see itself superseded — never apply twice.
                        with entry["lock"]:
                            if entry["conn"] is not conn:
                                return
                            dup = (
                                isinstance(seq, int)
                                and seq <= entry["last_seq"]
                            )
                            if isinstance(seq, int) and not dup:
                                entry["last_seq"] = seq
                        if dup:
                            _DEDUP_DROPS.inc()
                            continue
                        self._dispatch(fr, conn, send_lock, conn_dead, subs)
                        if isinstance(seq, int):
                            # Ack watermark moves only AFTER dispatch: an
                            # acked frame is an applied frame.
                            entry["applied_seq"] = seq
                        if (
                            faults.ACTIVE
                            and fr.get("kind") in ("publish", "bridge_push")
                            and faults.fires_scoped(
                                "transport.conn_kill_midflight", plane
                            )
                        ):
                            # The frame IS applied but the client will
                            # never see its ack — the previously-ambiguous
                            # retry case. The client must replay it and
                            # the per-identity watermark must drop it.
                            return
                except (KeyError, TypeError) as e:
                    # Wire-valid but schema-invalid (missing/mis-typed
                    # fields): same hostile-peer treatment as WireError.
                    _log.warning(
                        "transport: dropping connection on bad frame: %s", e
                    )
                    return
                if want_ack:
                    try:
                        self._maybe_ack(
                            conn, send_lock, entry, ack_state, force=False
                        )
                    except OSError:
                        return
                frame = None
        finally:
            conn_dead.set()
            for sub, stop in subs.values():
                stop.set()
                sub.unsubscribe()
            _close(conn)
            try:
                self._conns.remove(conn)
            except ValueError:
                pass

    def _dispatch(self, frame, conn, send_lock, conn_dead, subs) -> None:
        kind = frame["kind"]
        if kind == "publish":
            # May block on a full bounded subscription — that is
            # the flow control. Agents ship a separate control
            # connection for heartbeats (RemoteBus), so blocking a
            # data connection cannot starve liveness.
            self.bus.publish(frame["topic"], frame["msg"])
        elif kind == "subscribe":
            if frame["topic"] in subs:
                return
            sub = self.bus.subscribe(frame["topic"])
            stop = threading.Event()
            subs[frame["topic"]] = (sub, stop)

            def forward(sub=sub, stop=stop, topic=frame["topic"]):
                while not (
                    self._stop.is_set()
                    or conn_dead.is_set()
                    or stop.is_set()
                ):
                    msg = sub.get(timeout=0.05)
                    if msg is None:
                        continue
                    try:
                        with send_lock:
                            _send_frame(
                                conn,
                                {
                                    "kind": "message",
                                    "topic": topic,
                                    "msg": msg,
                                },
                            )
                    except OSError:
                        return
                    except wire.WireError as e:
                        # Local publisher handed the bus a non-encodable
                        # message (programming error, not a peer issue):
                        # count it as dropped so lossless consumers fail
                        # loudly, keep the subscription alive.
                        sub.dropped += 1
                        _log.error(
                            "transport: cannot forward message on %s: %s",
                            topic,
                            e,
                        )

            ft = threading.Thread(target=forward, daemon=True)
            ft.start()
        elif kind == "unsubscribe":
            entry = subs.pop(frame["topic"], None)
            if entry is not None:
                entry[1].set()
                entry[0].unsubscribe()
        elif kind == "bridge_register":
            self.router.register_producer(
                frame["query_id"], frame["bridge_id"]
            )
        elif kind == "bridge_push":
            token = frame.get("token")
            self.router.push(
                frame["query_id"], frame["bridge_id"], frame["item"],
                token=tuple(token) if token is not None else None,
            )

    def stop(self) -> None:
        self._stop.set()
        self._srv.close()
        for c in self._conns:
            _close(c)


class _RemoteSubscription:
    def __init__(self, topic: str, bus: "RemoteBus"):
        self.topic = topic
        self._bus = bus
        import collections

        self._q: "collections.deque" = collections.deque()
        self._cv = threading.Condition()

    def _deliver(self, msg: Any) -> None:
        with self._cv:
            self._q.append(msg)
            self._cv.notify()

    def get(self, timeout: float = None):
        with self._cv:
            if not self._q:
                self._cv.wait(timeout=timeout)
            return self._q.popleft() if self._q else None

    def unsubscribe(self) -> None:
        self._bus._drop(self)


class RemoteBus:
    """MessageBus facade over framed TCP (the agent side).

    Two connections, mirroring the reference's split planes (NATS control
    vs gRPC data streams): result-stream publishes and bridge pushes ride
    a DATA connection that may block under broker flow control; heartbeats,
    registration, and subscriptions ride the CONTROL connection so
    backpressure can never starve liveness and get the agent pruned.

    Acked, replayable delivery (r10; ref: Kafka's idempotent producer —
    producer id + epoch + per-partition seq surviving reconnects — and
    the NATS client's pending window replayed after reconnect): each
    RemoteBus owns a stable identity (``agent_id``) and a monotonically
    increasing epoch presented at session setup; the server keeps
    per-(identity, plane) seq watermarks that survive the connection, so
    a frame the OLD connection may (or may not) have delivered is no
    longer ambiguous — the client replays its bounded in-flight window
    (``transport_ack_window`` frames / ``transport_ack_window_mb``)
    above the server's applied watermark, and any half the old
    connection did deliver is silently dropped at the watermark. The
    server acks cumulatively (batched every ``transport_ack_interval``
    frames / ``transport_ack_interval_ms``); a full window blocks the
    sender up to ``transport_window_block_s`` then raises
    TransportBackpressureError. Stale-epoch connections are rejected so
    a zombie socket can't interleave with its replacement."""

    DATA_TOPIC_PREFIXES = ("results/",)

    def __init__(
        self,
        address,
        agent_id: Optional[str] = None,
        wal_dir: Optional[str] = None,
    ):
        self._address = tuple(address)
        self._secret = flags.cluster_secret
        self._tls = _tls_client_context()
        verified_tls = self._tls is not None and bool(flags.tls_ca)
        if not self._secret and not verified_tls and not _is_loopback(
            self._address[0]
        ):
            raise ValueError(
                f"refusing to connect to non-loopback {self._address[0]!r} "
                "without a cluster_secret (set PIXIE_TPU_CLUSTER_SECRET) "
                "or a verified TLS server (tls_ca)"
            )
        # Durable identity + window spill (r14, flag durable_transport +
        # wal_dir, or an explicit wal_dir): a restart restores the same
        # agent_id, continues the epoch counter, and replays the unacked
        # window from disk — exactly-once across crash.
        self._wal = None
        restored_ident = None
        if wal_dir is None and flags.durable_transport and flags.wal_dir:
            wal_dir = flags.wal_dir
        if wal_dir:
            from pixie_tpu.vizier import durability

            self._wal = durability.TransportWAL(
                durability.transport_wal_path(wal_dir)
            )
            restored_ident = self._wal.identity()
        # Stable delivery identity + per-process epoch counter: every
        # (re)connect on either plane presents a strictly higher epoch,
        # so the server can reject zombies deterministically.
        if agent_id is None and restored_ident is not None:
            agent_id = restored_ident[0]
        self._ident = agent_id or f"rbus-{uuid.uuid4().hex}"
        self._epoch = 0
        self._restarted = False
        if restored_ident is not None and restored_ident[0] == self._ident:
            self._epoch = restored_ident[1]
            self._restarted = self._epoch > 0
        self._epoch_lock = threading.Lock()
        self._ctrl_window = _AckWindow("control", wal=self._wal)
        self._data_window = _AckWindow("data", wal=self._wal)
        # Recovery observability: frames restored from the WAL at open
        # (the agent's recovery stats pick this up).
        self.wal_restored_frames = (
            self._ctrl_window.restored_frames
            + self._data_window.restored_frames
        )
        self._send_lock = threading.Lock()
        self._data_sock = None  # opened on first data-plane send
        self._data_lock = threading.Lock()
        self._subs_lock = threading.Lock()
        self._subs: dict[str, list[_RemoteSubscription]] = {}
        self._stop = threading.Event()
        # Reentrant: a reconnect listener may publish, whose send failure
        # would re-enter _reconnect on the same thread.
        self._reconnect_lock = threading.RLock()
        self._reconnect_listeners: list = []
        self._sock, server_applied = self._connect("control")
        if self._ctrl_window.enabled and self._ctrl_window.depth()[0]:
            # Restart recovery: replay restored control frames above the
            # server's applied watermark before anything else is sent.
            with self._send_lock:
                try:
                    self._replay_onto(
                        self._sock, self._ctrl_window, server_applied
                    )
                except OSError:
                    pass  # the read loop will redial + replay
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        if self._data_window.enabled and self._data_window.depth()[0]:
            # Stranded data frames (a crashed process's windowed result
            # stream) must not wait for the next data send: dial the
            # plane now so the replay delivers them.
            try:
                with self._data_lock:
                    if self._data_sock is None:
                        self._data_redial_locked(redialing=False)
            except (OSError, ConnectionError) as e:
                _log.warning(
                    "transport: data-plane WAL replay deferred "
                    "(redial failed: %s)", e
                )

    def add_reconnect_listener(self, fn) -> None:
        """``fn()`` runs after each successful control-plane reconnect
        (the Agent re-registers itself + its tables)."""
        self._reconnect_listeners.append(fn)

    @staticmethod
    def _replay_onto(sock, window: _AckWindow, server_applied: int) -> None:
        """Resend a window's unacked frames (in-memory or WAL-spilled)
        above the server's applied watermark onto a fresh socket."""
        for payload in window.replay_payloads(server_applied):
            sock.sendall(_LEN.pack(len(payload)) + payload)
            _REPLAYS.inc(plane=window.plane)

    def _hard_crash(self) -> None:
        """Simulate abrupt process death from inside a send path (the
        transport.crash_restart fault site): both sockets die with no
        drain and no graceful close; the WAL keeps exactly what a real
        SIGKILL would have left on disk. Callers must not hold
        ``_data_lock`` unless they close the data socket themselves."""
        self._stop.set()
        _close(self._sock)

    def crash(self) -> None:
        """Test/chaos helper: kill this bus as a SIGKILL would — no
        window drain, no unsubscribes; durable state stays as-is."""
        self._hard_crash()
        with self._data_lock:
            if self._data_sock is not None:
                _close(self._data_sock)
                self._data_sock = None

    def _connect(self, plane: str) -> tuple[socket.socket, int]:
        """Dial + authenticate + establish the delivery session for one
        plane. Returns (socket, server_applied_seq): the server's
        per-identity watermark bounds what the window must replay."""
        sock = socket.create_connection(
            self._address, timeout=flags.transport_handshake_timeout_s
        )
        _no_delay(sock)
        try:
            # The handshake runs under the timeout: a silent/half-open
            # server cannot park this thread; the socket is closed on the
            # way out instead of leaking.
            if self._tls is not None:
                sock = self._tls.wrap_socket(
                    sock, server_hostname=str(self._address[0])
                )
            _client_handshake(sock, self._secret)
            with self._epoch_lock:
                self._epoch += 1
                epoch = self._epoch
                if self._wal is not None:
                    # Persist identity + epoch BEFORE presenting them: a
                    # crash right after this connect must restart with a
                    # strictly higher epoch than any the server saw.
                    self._wal.save_identity(self._ident, epoch)
            session = {
                "kind": "session",
                "agent_id": self._ident,
                "plane": plane,
                "epoch": epoch,
                "want_ack": flags.transport_ack_window > 0,
            }
            if self._restarted:
                # Restart (persisted identity, bumped epoch), distinct
                # from a plain reconnect — servers count it.
                session["restarted"] = True
            _send_frame(sock, session)
            resp = _recv_frame(
                sock, max_len=_HANDSHAKE_MAX_FRAME, pre_auth=True
            )
            if resp is None or resp.get("kind") != "session_ok":
                reason = (
                    resp.get("reason", "no session_ok from server")
                    if isinstance(resp, dict)
                    else "connection closed before session_ok"
                )
                raise ConnectionError(f"transport session rejected: {reason}")
            sock.settimeout(None)
        except Exception:
            _close(sock)
            raise
        return sock, int(resp.get("last_seq", -1))

    def _backoff_delays(self):
        """Exponential backoff delays with jitter, bounded by
        agent_reconnect_max_tries (0 = forever)."""
        delay = flags.agent_backoff_initial_s
        max_tries = flags.agent_reconnect_max_tries
        attempt = 0
        while max_tries <= 0 or attempt < max_tries:
            attempt += 1
            yield delay * (1.0 + flags.agent_backoff_jitter * random.random())
            delay = min(delay * 2.0, flags.agent_backoff_max_s)

    def _reconnect(self, dead_sock) -> bool:
        """Replace the control connection after ``dead_sock`` failed.
        Returns True once a live connection exists (possibly made by a
        competing thread), False when giving up (closed or out of
        tries)."""
        with self._reconnect_lock:
            if self._stop.is_set():
                return False
            if self._sock is not dead_sock:
                return True  # another thread already replaced it
            _close(dead_sock)
            for delay in self._backoff_delays():
                if self._stop.is_set():
                    return False
                try:
                    sock, server_applied = self._connect("control")
                except (OSError, ConnectionError) as e:
                    _log.warning(
                        "transport: reconnect to %s failed (%s); retrying "
                        "in %.3fs", self._address, e, delay,
                    )
                    if self._stop.wait(delay):
                        return False
                    continue
                # Socket swap + window replay are one atomic step under
                # the send lock: any sender that windowed a frame did so
                # while HOLDING that lock, so a replay that runs after it
                # always covers the frame — no seq can be overtaken (a
                # skipped seq would be deduped away forever once a later
                # one lands).
                replay_failed = False
                with self._send_lock:
                    self._sock = sock
                    if self._ctrl_window.enabled:
                        try:
                            self._replay_onto(
                                sock, self._ctrl_window, server_applied
                            )
                        except OSError:
                            replay_failed = True
                if replay_failed:
                    continue  # fresh conn died mid-replay: keep backing off
                # The data plane redials lazily on its next send.
                with self._data_lock:
                    if self._data_sock is not None:
                        _close(self._data_sock)
                        self._data_sock = None
                # Restore server-side subscription state (per-connection
                # server state, re-issued with fresh seqs), then let
                # listeners (agent re-registration) run on the new conn.
                with self._subs_lock:
                    topics = sorted(self._subs)
                try:
                    for t in topics:
                        self._send_stamped(
                            sock,
                            {"kind": "subscribe", "topic": t},
                            force=True,
                        )
                except OSError:
                    continue  # new conn died instantly: keep backing off
                # An acked frame is a DISPATCHED frame, so waiting for
                # the resubscriptions' ack closes the window where the
                # tracker still shows this agent alive but its topic
                # forwarders don't exist yet (a query launched there
                # would silently miss it). The reconnect lock gives this
                # thread exclusive read access, so drain inline; bounded
                # — on timeout the plane still works, just with the r9
                # eventually-consistent subscription restore.
                if self._ctrl_window.enabled and topics:
                    self._drain_until_acked(
                        sock, self._ctrl_window.next_seq - 1
                    )
                _RECONNECTS.inc(plane="control")
                for fn in list(self._reconnect_listeners):
                    try:
                        fn()
                    except Exception:
                        _log.exception("transport: reconnect listener failed")
                return True
            _log.error(
                "transport: giving up on %s after %d reconnect attempts",
                self._address, flags.agent_reconnect_max_tries,
            )
            return False

    def _handle_frame(self, frame: dict) -> None:
        """One server->client control frame (shared by the read loop and
        the reconnect-time inline drain)."""
        kind = frame.get("kind")
        if kind == "message":
            with self._subs_lock:
                targets = list(self._subs.get(frame["topic"], ()))
            for sub in targets:
                sub._deliver(frame["msg"])
        elif kind == "ack" and isinstance(frame.get("seq"), int):
            self._ctrl_window.ack(frame["seq"])

    def _drain_until_acked(self, sock, seq: int) -> None:
        """Read frames off ``sock`` until the server's cumulative ack
        covers ``seq`` (bounded by ~4 ack intervals). Only called under
        the reconnect lock — every other reader is parked waiting for it,
        so this thread has exclusive read access. A timeout mid-frame can
        desync the stream; the resulting WireError on the next read drops
        the connection and redials, so it self-heals."""
        timeout = max(0.05, 4 * flags.transport_ack_interval_ms / 1000.0)
        deadline = time.monotonic() + timeout
        try:
            while self._ctrl_window.acked < seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                sock.settimeout(remaining)
                frame = _recv_frame(sock)
                if frame is None:
                    return
                self._handle_frame(frame)
        except (OSError, wire.WireError):
            return
        finally:
            try:
                sock.settimeout(None)
            except OSError:
                pass

    def _read_loop(self) -> None:
        while not self._stop.is_set():
            sock = self._sock
            try:
                frame = _recv_frame(sock)
            except OSError:
                frame = None
            except wire.WireError as e:
                # Desynced/corrupt stream: drop the connection (the only
                # way to re-sync framing) and redial.
                _log.warning("transport: dropping desynced connection: %s", e)
                frame = None
            if frame is None:
                if self._stop.is_set() or not self._reconnect(sock):
                    return
                continue
            self._handle_frame(frame)

    def _data_redial_locked(self, redialing: bool) -> None:
        """Dial + session + window replay for the data plane. Caller
        holds ``_data_lock`` and has verified ``_data_sock is None``."""
        sock, server_applied = self._connect("data")
        self._data_sock = sock
        if redialing:
            _RECONNECTS.inc(plane="data")
        if self._data_window.enabled:
            # Replay unacked frames above the server's applied watermark;
            # delivered-but-unacked halves are trimmed (or, under the
            # transport.replay_dup fault, deduped server-side).
            self._replay_onto(sock, self._data_window, server_applied)
            threading.Thread(
                target=self._data_read_loop, args=(sock,), daemon=True
            ).start()

    def _data_read_loop(self, sock) -> None:
        """Drain server acks off one data-plane socket (the data plane
        was send-only before r10). On socket death, proactively redial +
        replay when unacked frames are stranded in the window — a tail
        frame (e.g. a fragment_done publish) may have been buffered into
        a dying socket with no follow-up send to trigger the replay."""
        while not self._stop.is_set():
            try:
                frame = _recv_frame(sock)
            except (OSError, wire.WireError):
                break
            if frame is None:
                break
            if frame.get("kind") == "ack" and isinstance(
                frame.get("seq"), int
            ):
                self._data_window.ack(frame["seq"])
        with self._data_lock:
            if self._data_sock is sock:
                _close(sock)
                self._data_sock = None
            else:
                return  # a sender already replaced the socket
        if self._stop.is_set() or self._data_window.depth()[0] == 0:
            return
        attempts = self._backoff_delays()
        while not self._stop.is_set():
            try:
                with self._data_lock:
                    if self._data_sock is None:
                        self._data_redial_locked(redialing=True)
                return
            except (OSError, ConnectionError):
                with self._data_lock:
                    if self._data_sock is not None:
                        _close(self._data_sock)
                        self._data_sock = None
                try:
                    delay = next(attempts)
                except StopIteration:
                    return
                if self._stop.wait(delay):
                    return

    def _send_stamped(self, sock, obj: dict, force: bool = False) -> None:
        """One stamped + windowed control-plane send on ``sock``, no
        retry. The frame enters the in-flight window BEFORE the send: a
        send that dies mid-wire leaves the frame replayable. Stamp +
        window + transmit are atomic under the send lock — required for
        in-order seq delivery (the watermark dedup is only correct if a
        lower seq can never legitimately arrive after a higher one)."""
        with self._send_lock:
            frame = self._ctrl_window.stamp(obj)
            payload = wire.encode(frame)
            if self._ctrl_window.enabled:
                self._ctrl_window.add(frame, payload, force=force)
            sock.sendall(_LEN.pack(len(payload)) + payload)

    def _send(self, obj: dict) -> None:
        while True:
            try:
                with self._send_lock:
                    # self._sock is read under the lock: after a competing
                    # thread's reconnect (socket swap + replay hold this
                    # lock), we see the fresh socket, never the zombie.
                    sock = self._sock
                    if faults.ACTIVE and faults.fires("transport.send"):
                        # Simulated peer reset BEFORE the frame hits the
                        # wire: the frame is lost with the connection; with
                        # the window off the retry below is exactly-once,
                        # with it on the reconnect replay re-sends it (and
                        # dedup drops any server-applied copy).
                        _close(sock)
                    frame = self._ctrl_window.stamp(obj)
                    payload = wire.encode(frame)
                    windowed = self._ctrl_window.enabled
                    if windowed:
                        self._ctrl_window.add(frame, payload)
                    sock.sendall(_LEN.pack(len(payload)) + payload)
                    if faults.ACTIVE and faults.fires_scoped(
                        "transport.crash_restart", "control"
                    ):
                        # The frame IS on the wire (and in the WAL); the
                        # process dies before it can learn the outcome —
                        # a restart must replay it and the server's
                        # watermark must apply it exactly once.
                        self._hard_crash()
                        raise ConnectionError(
                            "fault injected: transport.crash_restart"
                        )
                return
            except TransportBackpressureError:
                raise  # structured: peer alive but not draining acks
            except OSError:
                if self._stop.is_set() or not self._reconnect(sock):
                    raise
                if windowed:
                    # The frame entered the window while we held the send
                    # lock; every reconnect replay runs under that lock
                    # afterwards, so whichever thread reconnected has
                    # already retransmitted it in seq order.
                    return

    def _send_data(self, obj: dict) -> None:
        attempts = self._backoff_delays()
        redialing = False
        windowed_frame = None
        while True:
            if faults.ACTIVE and faults.fires("transport.send_data"):
                with self._data_lock:
                    if self._data_sock is not None:
                        _close(self._data_sock)
                        self._data_sock = None
                    redialing = True
            try:
                with self._data_lock:
                    if self._data_sock is None:
                        self._data_redial_locked(redialing)
                    if windowed_frame is not None:
                        # Our frame was already windowed on a previous
                        # attempt: whichever redial made the socket live
                        # replayed (or the server acked) it.
                        return
                    frame = self._data_window.stamp(obj)
                    payload = wire.encode(frame)
                    if self._data_window.enabled:
                        self._data_window.add(frame, payload)
                        windowed_frame = frame
                    self._data_sock.sendall(
                        _LEN.pack(len(payload)) + payload
                    )
                    if faults.ACTIVE and faults.fires_scoped(
                        "transport.crash_restart", "data"
                    ):
                        # Applied-but-unobserved: the frame reached the
                        # wire (and the WAL), then the process dies.
                        _close(self._data_sock)
                        self._data_sock = None
                        self._hard_crash()
                        raise ConnectionError(
                            "fault injected: transport.crash_restart"
                        )
                return
            except TransportBackpressureError:
                raise  # structured: the peer is alive but not draining
            except (OSError, ConnectionError):
                with self._data_lock:
                    if self._data_sock is not None:
                        _close(self._data_sock)
                        self._data_sock = None
                redialing = True
                if self._stop.is_set():
                    raise
                try:
                    delay = next(attempts)
                except StopIteration:
                    raise
                if self._stop.wait(delay):
                    raise

    def window_depths(self) -> dict[str, tuple[int, int]]:
        """{plane: (frames, bytes)} currently in-flight (health plane)."""
        return {
            "control": self._ctrl_window.depth(),
            "data": self._data_window.depth(),
        }

    def publish(self, topic: str, msg: Any) -> None:
        frame = {"kind": "publish", "topic": topic, "msg": msg}
        if topic.startswith(self.DATA_TOPIC_PREFIXES):
            self._send_data(frame)
        else:
            self._send(frame)

    def subscribe(self, topic: str) -> _RemoteSubscription:
        sub = _RemoteSubscription(topic, self)
        with self._subs_lock:
            first = topic not in self._subs
            self._subs.setdefault(topic, []).append(sub)
        if first:
            self._send({"kind": "subscribe", "topic": topic})
        return sub

    def _drop(self, sub: _RemoteSubscription) -> None:
        last = False
        with self._subs_lock:
            if sub.topic in self._subs and sub in self._subs[sub.topic]:
                self._subs[sub.topic].remove(sub)
                if not self._subs[sub.topic]:
                    del self._subs[sub.topic]
                    last = True
        if last and not self._stop.is_set():
            # Tell the server so its forwarder thread + bus subscription
            # are released (they otherwise live until the conn closes).
            try:
                self._send({"kind": "unsubscribe", "topic": sub.topic})
            except OSError:
                pass

    def close(self) -> None:
        # Graceful drain first (acked mode): closing with frames still
        # in flight triggers an RST the moment the server writes an ack
        # at the dead socket — which destroys the server's receive
        # buffer, losing frames it never got to apply. Waiting for the
        # cumulative ack proves everything was applied; bounded, so a
        # dead peer can't park close() past the backpressure budget.
        if self._ctrl_window.enabled and not self._stop.is_set():
            deadline = time.monotonic() + min(
                flags.transport_window_block_s, 5.0
            )
            self._ctrl_window.wait_drained(deadline)
            self._data_window.wait_drained(deadline)
        self._stop.set()
        _close(self._sock)
        with self._data_lock:
            if self._data_sock is not None:
                _close(self._data_sock)
        if self._wal is not None:
            self._wal.close()


class RemoteRouter(BridgeRouter):
    """Send-only bridge router riding the agent's RemoteBus connection:
    pushes and producer registrations go to the broker-process router
    (ref: GRPCSinkNode streaming TransferResultChunk to the remote
    GRPCRouter). PEM fragments never consume bridges — the splitter cuts
    plans before blocking ops — so poll() on a remote bridge is a plan
    error, not a transport feature."""

    def __init__(self, bus: RemoteBus):
        super().__init__()
        self._bus = bus

    def register_producer(self, query_id: str, bridge_id: str) -> None:
        self._bus._send_data(
            {
                "kind": "bridge_register",
                "query_id": query_id,
                "bridge_id": bridge_id,
            }
        )

    def push(
        self, query_id: str, bridge_id: str, item: Any, token=None
    ) -> None:
        # Data plane: may block under flow control without starving the
        # control connection's heartbeats. The r17 attempt token rides
        # the frame so the broker-process router applies the same
        # per-attempt hold/commit gating for remote producers.
        frame = {
            "kind": "bridge_push",
            "query_id": query_id,
            "bridge_id": bridge_id,
            "item": item,
        }
        if token is not None:
            frame["token"] = tuple(token)
        self._bus._send_data(frame)

    def poll(self, query_id: str, bridge_id: str, consumer=None):
        raise NotImplementedError(
            "remote agents only produce into bridges; merge fragments run "
            "in the broker process (splitter invariant)"
        )
