"""TCP transport: the cross-process/cross-host control bus + data plane.

Ref: the reference runs NATS for control (src/common/event/nats.{h,cc},
messagebus/topic.go) and gRPC TransferResultChunk streams for data
(src/carnot/exec/grpc_router.h:53, carnotpb/carnot.proto:99) — both
TLS-authenticated protobuf planes (src/shared/services/). Here one framed
TCP connection per remote agent carries both: bus publishes /
subscriptions (control) and bridge register/push frames (data). Every
frame crosses as the typed wire format (pixie_tpu/vizier/wire.py — the
planpb-equivalent closed schema); network bytes are NEVER unpickled.
Connections start with a mutual HMAC-SHA256 challenge/response over the
pre-shared ``cluster_secret`` flag — the trusted-cluster floor standing in
for the reference's TLS+JWT bootstrap. Without a secret configured, only
loopback binds/connects are allowed.

Topology: the broker process runs a BusTransportServer bound to its local
MessageBus + BridgeRouter; each remote agent process connects a RemoteBus
(+ RemoteRouter on the same connection). PEM-side fragments only *push*
to bridges (the splitter cuts before blocking ops), so RemoteRouter is
send-only; merge-side consumption happens in the broker process's router.
"""

from __future__ import annotations

import hmac
import ipaddress
import logging
import os
import random
import socket
import ssl
import struct
import threading
from typing import Any, Optional

from pixie_tpu.exec.router import BridgeRouter
from pixie_tpu.utils import faults, flags, metrics_registry
from pixie_tpu.utils.config import define_flag
from pixie_tpu.vizier import wire
from pixie_tpu.vizier.bus import MessageBus

define_flag(
    "cluster_secret",
    "",
    help_="Pre-shared secret authenticating transport connections "
    "(HMAC-SHA256 challenge/response). Empty restricts the transport to "
    "loopback (ref posture: src/shared/services/ TLS+JWT bootstrap).",
)

define_flag(
    "transport_handshake_timeout_s",
    10.0,
    help_="Socket timeout covering the TLS+HMAC handshake on both ends "
    "(was hard-coded 10s server-side). A silent peer's half-open "
    "connection is closed at the timeout instead of pinning a thread.",
)

_RECONNECTS = metrics_registry().counter(
    "transport_reconnect_total",
    "Successful RemoteBus plane reconnects after a connection failure.",
)
_DEDUP_DROPS = metrics_registry().counter(
    "transport_dedup_dropped_total",
    "Duplicate/replayed frames dropped by per-connection seq dedup.",
)

define_flag(
    "tls_cert",
    "",
    help_="PEM certificate chain for transport TLS (ref: the reference "
    "runs TLS on every plane, src/shared/services/). Servers present it; "
    "clients present it too when tls_ca demands mutual auth. Empty "
    "disables TLS (HMAC-only trusted-cluster floor).",
)
define_flag(
    "tls_key", "", help_="PEM private key for tls_cert (empty: key is "
    "embedded in the cert file)."
)
define_flag(
    "tls_ca",
    "",
    help_="PEM CA bundle: servers require client certificates signed by "
    "it (mutual TLS); clients verify the server against it. Certificates "
    "are cluster-internal and pinned by this private CA, so hostname "
    "checking is off (agents dial IPs).",
)


def _tls_server_context() -> Optional[ssl.SSLContext]:
    if not flags.tls_cert:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(flags.tls_cert, flags.tls_key or None)
    if flags.tls_ca:
        ctx.load_verify_locations(flags.tls_ca)
        ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
    return ctx


def _tls_client_context() -> Optional[ssl.SSLContext]:
    if not (flags.tls_ca or flags.tls_cert):
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False  # private-CA-pinned certs, dialed by IP
    if flags.tls_ca:
        ctx.load_verify_locations(flags.tls_ca)
        ctx.verify_mode = ssl.CERT_REQUIRED
    else:
        ctx.verify_mode = ssl.CERT_NONE  # HMAC still authenticates
    if flags.tls_cert:
        ctx.load_cert_chain(flags.tls_cert, flags.tls_key or None)
    return ctx

_LEN = struct.Struct(">Q")
_NONCE_BYTES = 16
_log = logging.getLogger("pixie_tpu.transport")


def _is_loopback(host: str) -> bool:
    # NOTE: '' binds INADDR_ANY for servers — it is NOT loopback.
    if host == "localhost":
        return True
    try:
        return ipaddress.ip_address(host).is_loopback
    except ValueError:
        return False


def _mac(secret: str, nonce: bytes) -> bytes:
    return hmac.new(secret.encode(), nonce, "sha256").digest()


def _send_frame(sock: socket.socket, obj: dict) -> None:
    payload = wire.encode(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    # recv_into a preallocated buffer: O(n), not the quadratic bytes+=
    # (row-batch frames reach hundreds of MB).
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            return None
        got += r
    return bytes(buf)


_HANDSHAKE_MAX_FRAME = 1 << 12  # hello/challenge are ~100 bytes


def _recv_frame(
    sock: socket.socket,
    max_len: Optional[int] = None,
    pre_auth: bool = False,
) -> Optional[dict]:
    """Next decoded frame, or None on EOF. Raises wire.WireError (or a
    ValueError subclass) on malformed content — callers treat that as a
    hostile/broken peer and drop the connection. ``max_len`` caps the
    attacker-controlled length word BEFORE allocation; ``pre_auth`` reads
    additionally refuse array/batch nodes, whose forged numpy headers are
    allocation bombs the length cap cannot see. The two are independent:
    a capped post-auth read must still decode batches."""
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    if max_len is not None and n > max_len:
        raise wire.WireError(f"frame length {n} exceeds cap {max_len}")
    payload = _recv_exact(sock, n)
    if payload is None:
        return None
    try:
        frame = wire.decode(payload, allow_arrays=not pre_auth)
    except wire.WireError:
        raise
    except Exception as e:  # unhashable map keys, bad npy, ...
        raise wire.WireError(f"malformed frame: {e}") from None
    if not isinstance(frame, dict) or not isinstance(frame.get("kind"), str):
        raise wire.WireError("frame is not a kind-tagged message")
    return frame


def _server_handshake(conn: socket.socket, secret: str) -> bool:
    """Mutual challenge/response (server side). Server challenges first;
    the client's response proves it holds the secret before any frame is
    acted on; the server's counter-MAC proves the same to the client."""
    if faults.ACTIVE and faults.fires("transport.handshake"):
        return False
    nonce = os.urandom(_NONCE_BYTES)
    _send_frame(conn, {"kind": "challenge", "nonce": nonce})
    frame = _recv_frame(conn, max_len=_HANDSHAKE_MAX_FRAME, pre_auth=True)
    if (
        frame is None
        or frame.get("kind") != "hello"
        or not isinstance(frame.get("mac"), bytes)
        or not isinstance(frame.get("nonce"), bytes)
        or not hmac.compare_digest(frame["mac"], _mac(secret, nonce))
    ):
        return False
    _send_frame(conn, {"kind": "welcome", "mac": _mac(secret, frame["nonce"])})
    return True


def _client_handshake(sock: socket.socket, secret: str) -> None:
    if faults.ACTIVE and faults.fires("transport.handshake"):
        raise ConnectionError("fault injected: transport.handshake")
    frame = _recv_frame(sock, max_len=_HANDSHAKE_MAX_FRAME, pre_auth=True)
    if frame is None or frame.get("kind") != "challenge" or not isinstance(
        frame.get("nonce"), bytes
    ):
        raise ConnectionError("transport handshake: no challenge from server")
    nonce = os.urandom(_NONCE_BYTES)
    _send_frame(
        sock,
        {"kind": "hello", "mac": _mac(secret, frame["nonce"]), "nonce": nonce},
    )
    resp = _recv_frame(sock, max_len=_HANDSHAKE_MAX_FRAME, pre_auth=True)
    if (
        resp is None
        or resp.get("kind") != "welcome"
        or not isinstance(resp.get("mac"), bytes)
        or not hmac.compare_digest(resp["mac"], _mac(secret, nonce))
    ):
        raise ConnectionError("transport handshake: server failed to authenticate")


def _close(sock: socket.socket) -> None:
    """shutdown() before close(): a reader blocked in recv on either end
    only wakes on FIN, which close() alone does not send while another
    thread holds the fd open."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class BusTransportServer:
    """Accepts remote-agent connections; bridges them onto the local
    MessageBus and BridgeRouter (the broker side)."""

    def __init__(
        self,
        bus: MessageBus,
        router: BridgeRouter,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.bus = bus
        self.router = router
        self._secret = flags.cluster_secret
        self._tls = _tls_server_context()
        # Binding off-loopback needs a real authenticator: the HMAC secret
        # or mutual TLS (cert + required client CA).
        mutual_tls = self._tls is not None and bool(flags.tls_ca)
        if not self._secret and not mutual_tls and not _is_loopback(host):
            raise ValueError(
                f"refusing to bind transport on non-loopback {host!r} "
                "without a cluster_secret (set PIXIE_TPU_CLUSTER_SECRET) "
                "or mutual TLS (tls_cert + tls_ca)"
            )
        self._srv = socket.create_server((host, port))
        self.address = self._srv.getsockname()
        self._stop = threading.Event()
        self._conns: list[socket.socket] = []
        self._threads: list[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            self._conns.append(conn)
            t = threading.Thread(
                target=self._conn_loop, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _conn_loop(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        conn_dead = threading.Event()  # per-connection: stops forwarders
        subs: dict[str, tuple] = {}  # topic -> (bus sub, stop event)
        # Per-connection dedup watermark: clients stamp a monotonically
        # increasing ``seq`` on every frame; a replayed/duplicated frame
        # (retry ambiguity, injected duplication) is dropped here so
        # result rows and producer registrations stay exactly-once.
        last_seq = -1
        try:
            try:
                # Bounded pre-auth hold time: a silent peer must not pin
                # this thread forever (the half-open socket is closed in
                # the finally below). Cleared once authenticated.
                conn.settimeout(flags.transport_handshake_timeout_s)
                if self._tls is not None:
                    # TLS first; the HMAC challenge/response then runs
                    # INSIDE the tunnel (defense in depth: the secret
                    # never rides plaintext, frames get confidentiality
                    # + integrity the bare HMAC handshake lacked).
                    conn = self._tls.wrap_socket(conn, server_side=True)
                if not _server_handshake(conn, self._secret):
                    _log.warning("transport: rejecting unauthenticated peer")
                    return
                conn.settimeout(None)
            except (wire.WireError, OSError, ConnectionError) as e:
                _log.warning("transport: handshake failed: %s", e)
                return
            while not self._stop.is_set():
                try:
                    frame = _recv_frame(conn)
                except wire.WireError as e:
                    # Hostile or corrupted peer: drop just this connection.
                    _log.warning("transport: dropping connection: %s", e)
                    return
                except OSError:
                    return  # closed under us (shutdown or peer reset)
                if frame is None:
                    return
                frames = [frame]
                if (
                    faults.ACTIVE
                    and frame.get("kind") in ("publish", "bridge_push")
                    and faults.fires("transport.recv_dup")
                ):
                    frames.append(frame)  # injected duplicate delivery
                try:
                    for fr in frames:
                        seq = fr.get("seq")
                        if isinstance(seq, int):
                            if seq <= last_seq:
                                _DEDUP_DROPS.inc()
                                continue
                            last_seq = seq
                        self._dispatch(fr, conn, send_lock, conn_dead, subs)
                except (KeyError, TypeError) as e:
                    # Wire-valid but schema-invalid (missing/mis-typed
                    # fields): same hostile-peer treatment as WireError.
                    _log.warning(
                        "transport: dropping connection on bad frame: %s", e
                    )
                    return
        finally:
            conn_dead.set()
            for sub, stop in subs.values():
                stop.set()
                sub.unsubscribe()
            _close(conn)
            try:
                self._conns.remove(conn)
            except ValueError:
                pass

    def _dispatch(self, frame, conn, send_lock, conn_dead, subs) -> None:
        kind = frame["kind"]
        if kind == "publish":
            # May block on a full bounded subscription — that is
            # the flow control. Agents ship a separate control
            # connection for heartbeats (RemoteBus), so blocking a
            # data connection cannot starve liveness.
            self.bus.publish(frame["topic"], frame["msg"])
        elif kind == "subscribe":
            if frame["topic"] in subs:
                return
            sub = self.bus.subscribe(frame["topic"])
            stop = threading.Event()
            subs[frame["topic"]] = (sub, stop)

            def forward(sub=sub, stop=stop, topic=frame["topic"]):
                while not (
                    self._stop.is_set()
                    or conn_dead.is_set()
                    or stop.is_set()
                ):
                    msg = sub.get(timeout=0.05)
                    if msg is None:
                        continue
                    try:
                        with send_lock:
                            _send_frame(
                                conn,
                                {
                                    "kind": "message",
                                    "topic": topic,
                                    "msg": msg,
                                },
                            )
                    except OSError:
                        return
                    except wire.WireError as e:
                        # Local publisher handed the bus a non-encodable
                        # message (programming error, not a peer issue):
                        # count it as dropped so lossless consumers fail
                        # loudly, keep the subscription alive.
                        sub.dropped += 1
                        _log.error(
                            "transport: cannot forward message on %s: %s",
                            topic,
                            e,
                        )

            ft = threading.Thread(target=forward, daemon=True)
            ft.start()
        elif kind == "unsubscribe":
            entry = subs.pop(frame["topic"], None)
            if entry is not None:
                entry[1].set()
                entry[0].unsubscribe()
        elif kind == "bridge_register":
            self.router.register_producer(
                frame["query_id"], frame["bridge_id"]
            )
        elif kind == "bridge_push":
            self.router.push(
                frame["query_id"], frame["bridge_id"], frame["item"]
            )

    def stop(self) -> None:
        self._stop.set()
        self._srv.close()
        for c in self._conns:
            _close(c)


class _RemoteSubscription:
    def __init__(self, topic: str, bus: "RemoteBus"):
        self.topic = topic
        self._bus = bus
        import collections

        self._q: "collections.deque" = collections.deque()
        self._cv = threading.Condition()

    def _deliver(self, msg: Any) -> None:
        with self._cv:
            self._q.append(msg)
            self._cv.notify()

    def get(self, timeout: float = None):
        with self._cv:
            if not self._q:
                self._cv.wait(timeout=timeout)
            return self._q.popleft() if self._q else None

    def unsubscribe(self) -> None:
        self._bus._drop(self)


class RemoteBus:
    """MessageBus facade over framed TCP (the agent side).

    Two connections, mirroring the reference's split planes (NATS control
    vs gRPC data streams): result-stream publishes and bridge pushes ride
    a DATA connection that may block under broker flow control; heartbeats,
    registration, and subscriptions ride the CONTROL connection so
    backpressure can never starve liveness and get the agent pruned.

    Reconnection (r9; ref: the NATS client's reconnect-with-backoff that
    the reference's agents lean on): a failed plane redials with
    exponential backoff + jitter (``agent_backoff_*`` flags), re-issues
    server-side subscriptions, and invokes registered reconnect listeners
    (the Agent re-registers its tables). Failed sends retry on the fresh
    connection — a frame is only ever retried when the old socket died
    before it was sent, and every frame carries a per-plane monotonic
    ``seq`` the server dedups on, so result rows stay exactly-once."""

    DATA_TOPIC_PREFIXES = ("results/",)

    def __init__(self, address):
        self._address = tuple(address)
        self._secret = flags.cluster_secret
        self._tls = _tls_client_context()
        verified_tls = self._tls is not None and bool(flags.tls_ca)
        if not self._secret and not verified_tls and not _is_loopback(
            self._address[0]
        ):
            raise ValueError(
                f"refusing to connect to non-loopback {self._address[0]!r} "
                "without a cluster_secret (set PIXIE_TPU_CLUSTER_SECRET) "
                "or a verified TLS server (tls_ca)"
            )
        self._sock = self._connect()
        self._send_lock = threading.Lock()
        self._seq = 0  # control-plane frame sequence (dedup watermark)
        self._data_sock = None  # opened on first data-plane send
        self._data_lock = threading.Lock()
        self._data_seq = 0
        self._subs_lock = threading.Lock()
        self._subs: dict[str, list[_RemoteSubscription]] = {}
        self._stop = threading.Event()
        # Reentrant: a reconnect listener may publish, whose send failure
        # would re-enter _reconnect on the same thread.
        self._reconnect_lock = threading.RLock()
        self._reconnect_listeners: list = []
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def add_reconnect_listener(self, fn) -> None:
        """``fn()`` runs after each successful control-plane reconnect
        (the Agent re-registers itself + its tables)."""
        self._reconnect_listeners.append(fn)

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            self._address, timeout=flags.transport_handshake_timeout_s
        )
        try:
            # The handshake runs under the timeout: a silent/half-open
            # server cannot park this thread; the socket is closed on the
            # way out instead of leaking.
            if self._tls is not None:
                sock = self._tls.wrap_socket(
                    sock, server_hostname=str(self._address[0])
                )
            _client_handshake(sock, self._secret)
            sock.settimeout(None)
        except Exception:
            _close(sock)
            raise
        return sock

    def _backoff_delays(self):
        """Exponential backoff delays with jitter, bounded by
        agent_reconnect_max_tries (0 = forever)."""
        delay = flags.agent_backoff_initial_s
        max_tries = flags.agent_reconnect_max_tries
        attempt = 0
        while max_tries <= 0 or attempt < max_tries:
            attempt += 1
            yield delay * (1.0 + flags.agent_backoff_jitter * random.random())
            delay = min(delay * 2.0, flags.agent_backoff_max_s)

    def _reconnect(self, dead_sock) -> bool:
        """Replace the control connection after ``dead_sock`` failed.
        Returns True once a live connection exists (possibly made by a
        competing thread), False when giving up (closed or out of
        tries)."""
        with self._reconnect_lock:
            if self._stop.is_set():
                return False
            if self._sock is not dead_sock:
                return True  # another thread already replaced it
            _close(dead_sock)
            for delay in self._backoff_delays():
                if self._stop.is_set():
                    return False
                try:
                    sock = self._connect()
                except (OSError, ConnectionError) as e:
                    _log.warning(
                        "transport: reconnect to %s failed (%s); retrying "
                        "in %.3fs", self._address, e, delay,
                    )
                    if self._stop.wait(delay):
                        return False
                    continue
                self._sock = sock
                # The data plane redials lazily on its next send.
                with self._data_lock:
                    if self._data_sock is not None:
                        _close(self._data_sock)
                        self._data_sock = None
                _RECONNECTS.inc(plane="control")
                # Restore server-side subscription state, then let
                # listeners (agent re-registration) run on the new conn.
                # Direct sends (no retry recursion): if the fresh conn
                # dies mid-resubscribe, keep backing off.
                with self._subs_lock:
                    topics = sorted(self._subs)
                try:
                    for t in topics:
                        self._send_stamped(
                            sock, {"kind": "subscribe", "topic": t}
                        )
                except OSError:
                    continue  # new conn died instantly: keep backing off
                for fn in list(self._reconnect_listeners):
                    try:
                        fn()
                    except Exception:
                        _log.exception("transport: reconnect listener failed")
                return True
            _log.error(
                "transport: giving up on %s after %d reconnect attempts",
                self._address, flags.agent_reconnect_max_tries,
            )
            return False

    def _read_loop(self) -> None:
        while not self._stop.is_set():
            sock = self._sock
            try:
                frame = _recv_frame(sock)
            except OSError:
                frame = None
            except wire.WireError as e:
                # Desynced/corrupt stream: drop the connection (the only
                # way to re-sync framing) and redial.
                _log.warning("transport: dropping desynced connection: %s", e)
                frame = None
            if frame is None:
                if self._stop.is_set() or not self._reconnect(sock):
                    return
                continue
            if frame.get("kind") == "message":
                with self._subs_lock:
                    targets = list(self._subs.get(frame["topic"], ()))
                for sub in targets:
                    sub._deliver(frame["msg"])

    def _send_stamped(self, sock, obj: dict) -> None:
        """One stamped control-plane send on ``sock``, no retry."""
        with self._send_lock:
            obj = dict(obj)
            obj["seq"] = self._seq
            self._seq += 1
            _send_frame(sock, obj)

    def _send(self, obj: dict) -> None:
        while True:
            sock = self._sock
            if faults.ACTIVE and faults.fires("transport.send"):
                # Simulated peer reset BEFORE the frame hits the wire: the
                # frame is lost with the connection, so the retry below is
                # exactly-once.
                _close(sock)
            try:
                self._send_stamped(sock, obj)
                return
            except OSError:
                if self._stop.is_set() or not self._reconnect(sock):
                    raise

    def _send_data(self, obj: dict) -> None:
        attempts = self._backoff_delays()
        redialing = False
        while True:
            if faults.ACTIVE and faults.fires("transport.send_data"):
                with self._data_lock:
                    if self._data_sock is not None:
                        _close(self._data_sock)
                        self._data_sock = None
                    redialing = True
            try:
                with self._data_lock:
                    if self._data_sock is None:
                        self._data_sock = self._connect()
                        self._data_seq = 0
                        if redialing:
                            _RECONNECTS.inc(plane="data")
                    obj = dict(obj)
                    obj["seq"] = self._data_seq
                    self._data_seq += 1
                    _send_frame(self._data_sock, obj)
                return
            except (OSError, ConnectionError):
                with self._data_lock:
                    if self._data_sock is not None:
                        _close(self._data_sock)
                        self._data_sock = None
                redialing = True
                if self._stop.is_set():
                    raise
                try:
                    delay = next(attempts)
                except StopIteration:
                    raise
                if self._stop.wait(delay):
                    raise

    def publish(self, topic: str, msg: Any) -> None:
        frame = {"kind": "publish", "topic": topic, "msg": msg}
        if topic.startswith(self.DATA_TOPIC_PREFIXES):
            self._send_data(frame)
        else:
            self._send(frame)

    def subscribe(self, topic: str) -> _RemoteSubscription:
        sub = _RemoteSubscription(topic, self)
        with self._subs_lock:
            first = topic not in self._subs
            self._subs.setdefault(topic, []).append(sub)
        if first:
            self._send({"kind": "subscribe", "topic": topic})
        return sub

    def _drop(self, sub: _RemoteSubscription) -> None:
        last = False
        with self._subs_lock:
            if sub.topic in self._subs and sub in self._subs[sub.topic]:
                self._subs[sub.topic].remove(sub)
                if not self._subs[sub.topic]:
                    del self._subs[sub.topic]
                    last = True
        if last and not self._stop.is_set():
            # Tell the server so its forwarder thread + bus subscription
            # are released (they otherwise live until the conn closes).
            try:
                self._send({"kind": "unsubscribe", "topic": sub.topic})
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        _close(self._sock)
        with self._data_lock:
            if self._data_sock is not None:
                _close(self._data_sock)


class RemoteRouter(BridgeRouter):
    """Send-only bridge router riding the agent's RemoteBus connection:
    pushes and producer registrations go to the broker-process router
    (ref: GRPCSinkNode streaming TransferResultChunk to the remote
    GRPCRouter). PEM fragments never consume bridges — the splitter cuts
    plans before blocking ops — so poll() on a remote bridge is a plan
    error, not a transport feature."""

    def __init__(self, bus: RemoteBus):
        super().__init__()
        self._bus = bus

    def register_producer(self, query_id: str, bridge_id: str) -> None:
        self._bus._send_data(
            {
                "kind": "bridge_register",
                "query_id": query_id,
                "bridge_id": bridge_id,
            }
        )

    def push(self, query_id: str, bridge_id: str, item: Any) -> None:
        # Data plane: may block under flow control without starving the
        # control connection's heartbeats.
        self._bus._send_data(
            {
                "kind": "bridge_push",
                "query_id": query_id,
                "bridge_id": bridge_id,
                "item": item,
            }
        )

    def poll(self, query_id: str, bridge_id: str):
        raise NotImplementedError(
            "remote agents only produce into bridges; merge fragments run "
            "in the broker process (splitter invariant)"
        )
