"""healthz/statusz HTTP endpoints for fleet debugging.

Ref: src/shared/services/ — every reference service exposes /healthz
(liveness) and /statusz (human/machine-readable internal state) so
operators can probe a component without the message bus being up. Here a
stdlib ThreadingHTTPServer serves:

  /healthz  -> 200 "ok" (503 when the provided liveness probe fails)
  /statusz  -> JSON: component name, uptime, the metrics registry
               snapshot, and any extra status the owner provides
  /metrics  -> Prometheus-ish text rendering of the metrics registry

Brokers and agents attach one via ``serve_health(...)``; loopback by
default.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from pixie_tpu.utils import metrics_registry


class HealthServer:
    def __init__(
        self,
        component: str,
        status_fn: Optional[Callable[[], dict]] = None,
        live_fn: Optional[Callable[[], bool]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        extra_routes: Optional[dict[str, Callable[[], object]]] = None,
    ):
        self.component = component
        self._status_fn = status_fn
        self._live_fn = live_fn
        # Owner-provided JSON endpoints, e.g. the broker's /agentz view
        # of the cluster health plane (r10).
        self._extra_routes = dict(extra_routes or {})
        self._start = time.time()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _reply(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    live = outer._live_fn() if outer._live_fn else True
                    self._reply(
                        200 if live else 503,
                        b"ok" if live else b"unhealthy",
                        "text/plain",
                    )
                elif path == "/statusz":
                    self._reply(
                        200,
                        json.dumps(outer.status(), indent=1).encode(),
                        "application/json",
                    )
                elif path == "/metrics":
                    self._reply(
                        200,
                        metrics_registry().render_text().encode(),
                        "text/plain",
                    )
                elif path in outer._extra_routes:
                    try:
                        body = json.dumps(
                            outer._extra_routes[path](), indent=1
                        ).encode()
                        code = 200
                    except Exception as e:
                        body = json.dumps({"error": str(e)}).encode()
                        code = 500
                    self._reply(code, body, "application/json")
                else:
                    self._reply(404, b"not found", "text/plain")

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self.address = self._srv.server_address
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()

    def status(self) -> dict:
        out = {
            "component": self.component,
            "uptime_s": round(time.time() - self._start, 3),
            "metrics": {
                k: {"|".join(f"{a}={b}" for a, b in key) or "_": v
                    for key, v in samples.items()}
                for k, samples in metrics_registry().collect().items()
            },
        }
        if self._status_fn is not None:
            try:
                out["status"] = self._status_fn()
            except Exception as e:
                out["status_error"] = str(e)
        return out

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def serve_health(component: str, **kwargs) -> HealthServer:
    return HealthServer(component, **kwargs)
