"""Durable restart recovery (r14): WAL spill for in-flight state.

The r10 acked-delivery plane made delivery exactly-once across
*reconnects*; this module extends it across *process restarts* — the
OOM-kill/deploy/node-reboot cases a production serving tier must treat
as routine. Three durable stores, all under ``flags.wal_dir``:

- ``TransportWAL`` (ARIES-style write-ahead spill + Kafka's idempotent-
  producer identity): persists the RemoteBus delivery identity
  (agent_id + epoch counter) and every stamped-but-unacked in-flight
  frame. A restarted process restores its identity, bumps the epoch,
  and replays the window above the server's applied watermark — the
  per-identity seq watermark then dedups any half the dead process
  already delivered, so crash delivery is exactly-once, not just
  reconnect delivery.
- ``AgentDurableState``: the agent's registration epoch plus per-query
  started/done markers. A ``done`` marker means every result frame of
  the query (batches + fragment_done) was windowed into the transport
  WAL before the crash — the replay completes the query, so a
  re-offered launch is dropped. A ``started``-but-not-done marker means
  execution died mid-flight with partial output possibly applied — the
  restarted agent REFUSES the re-offer with a structured
  fragment_error instead of re-executing into duplicate application.
- ``RingSpill``: mirrors a ResidentRing's full HBM windows (raw host
  columns) and its partial append buffer to a per-table segment log, so
  a restarted agent re-stages its rings into HBM from disk instead of
  cold-staging every hot window again (``stage_resident_hits`` recover
  without replaying appends).

All three ride ``vizier.datastore`` machinery: ``FileDatastore`` for
small keyed state and ``SegmentLog`` (CRC-checked, torn-write-tolerant,
crash-safe compaction) for binary frame/column payloads. The fsync
policy is ``flags.wal_fsync`` ('always' | 'never').
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

import numpy as np

from pixie_tpu.utils import faults, flags
from pixie_tpu.vizier import wire
from pixie_tpu.vizier.datastore import FileDatastore, SegmentLog

_log = logging.getLogger("pixie_tpu.durability")


def wal_enabled() -> bool:
    """The transport-durability gate: flag on AND a wal_dir configured."""
    return bool(flags.durable_transport and flags.wal_dir)


def resident_spill_enabled() -> bool:
    return bool(flags.durable_resident and flags.wal_dir)


def _fsync_policy() -> bool:
    return flags.wal_fsync != "never"


class TransportWAL:
    """Write-ahead spill for one RemoteBus: identity + unacked frames.

    Record vocabulary (wire-encoded dicts; payload bytes ride as wire
    blobs, never base64):

    - ``{"op": "ident", "agent_id", "epoch"}`` — latest wins.
    - ``{"op": "frame", "plane", "seq", "payload"}`` — one stamped
      frame's encoded bytes, appended BEFORE the frame hits the wire.
    - ``{"op": "rel", "plane", "seq"}`` — cumulative release: every
      frame with seq' <= seq on that plane is acked/applied.

    Memory posture: only (plane, seq, nbytes) indexes live in RAM;
    payloads are re-read from the log on the rare replay path, so the
    WAL can hold a full 8MB window without doubling it in memory.
    Compaction rewrites the live set once dead records dominate.
    """

    def __init__(self, path: str):
        self._log = SegmentLog(path, fsync=_fsync_policy())
        self._lock = threading.Lock()
        self._ident: Optional[tuple[str, int]] = None
        # plane -> {seq: nbytes} pending (appended, not yet released).
        self._pending: dict[str, dict[int, int]] = {}
        self._released: dict[str, int] = {}
        self._live_bytes = 0
        for payload in self._log.scan():
            try:
                rec = wire.decode(payload)
                self._apply(rec)
            except (wire.WireError, KeyError, TypeError, ValueError):
                # A record that decodes but fails the schema is treated
                # like a torn tail would be: ignored.
                continue

    def _apply(self, rec: dict) -> None:
        op = rec.get("op")
        if op == "ident":
            self._ident = (str(rec["agent_id"]), int(rec["epoch"]))
        elif op == "frame":
            plane, seq = str(rec["plane"]), int(rec["seq"])
            if seq > self._released.get(plane, -1):
                n = len(rec["payload"])
                self._pending.setdefault(plane, {})[seq] = n
                self._live_bytes += n
        elif op == "rel":
            plane, seq = str(rec["plane"]), int(rec["seq"])
            self._released[plane] = max(self._released.get(plane, -1), seq)
            pend = self._pending.get(plane, {})
            for s in [s for s in pend if s <= seq]:
                self._live_bytes -= pend.pop(s)

    # -- identity -------------------------------------------------------------
    def identity(self) -> Optional[tuple[str, int]]:
        """(agent_id, last persisted epoch) or None on a fresh WAL."""
        with self._lock:
            return self._ident

    def save_identity(self, agent_id: str, epoch: int) -> None:
        with self._lock:
            self._ident = (agent_id, int(epoch))
            self._log.append(
                wire.encode(
                    {"op": "ident", "agent_id": agent_id, "epoch": int(epoch)}
                )
            )

    # -- frames ---------------------------------------------------------------
    def append_frame(self, plane: str, seq: int, payload: bytes) -> None:
        with self._lock:
            self._pending.setdefault(plane, {})[seq] = len(payload)
            self._live_bytes += len(payload)
            self._log.append(
                wire.encode(
                    {"op": "frame", "plane": plane, "seq": int(seq),
                     "payload": payload}
                )
            )

    def release(self, plane: str, seq: int) -> None:
        """Cumulative: frames <= seq left the window (acked or trimmed
        by the server's applied watermark)."""
        with self._lock:
            if seq <= self._released.get(plane, -1):
                return
            self._released[plane] = int(seq)
            pend = self._pending.get(plane, {})
            had = False
            for s in [s for s in pend if s <= seq]:
                self._live_bytes -= pend.pop(s)
                had = True
            if not had:
                return
            self._log.append(
                wire.encode({"op": "rel", "plane": plane, "seq": int(seq)})
            )
            self._maybe_compact_locked()

    def pending(self, plane: str) -> list[tuple[int, int]]:
        """Sorted (seq, nbytes) of unreleased frames for ``plane``."""
        with self._lock:
            return sorted(self._pending.get(plane, {}).items())

    def next_seq(self, plane: str) -> int:
        """First unused sequence number for ``plane`` — continues above
        everything ever stamped, so the server's per-identity watermark
        (which survived the restart server-side) stays meaningful."""
        with self._lock:
            top = self._released.get(plane, -1)
            pend = self._pending.get(plane)
            if pend:
                top = max(top, max(pend))
            return top + 1

    def released(self, plane: str) -> int:
        with self._lock:
            return self._released.get(plane, -1)

    def payloads(self, plane: str, seqs) -> dict[int, bytes]:
        """Encoded frame bytes for the requested seqs, re-read from the
        log (one sequential scan — replay-time only). Later records win
        (there are no frame overwrites, but scans are cheap to keep
        correct)."""
        want = set(seqs)
        out: dict[int, bytes] = {}
        if not want:
            return out
        for payload in self._log.scan():
            try:
                rec = wire.decode(payload)
            except wire.WireError:
                continue
            if (
                rec.get("op") == "frame"
                and rec.get("plane") == plane
                and int(rec.get("seq", -1)) in want
            ):
                out[int(rec["seq"])] = rec["payload"]
        return out

    def _maybe_compact_locked(self) -> None:
        # Compact when dead records dominate: rewrite ident + release
        # watermarks + still-pending frames (re-read via scan so payload
        # bytes never need a resident copy).
        if self._log.nbytes < max(1 << 16, 4 * (self._live_bytes + 1024)):
            return
        live_seqs = {
            plane: set(pend) for plane, pend in self._pending.items()
        }

        def records():
            if self._ident is not None:
                yield wire.encode(
                    {"op": "ident", "agent_id": self._ident[0],
                     "epoch": self._ident[1]}
                )
            for plane, seq in sorted(self._released.items()):
                yield wire.encode(
                    {"op": "rel", "plane": plane, "seq": int(seq)}
                )
            seen: dict[str, set] = {}
            for payload in self._log.scan():
                try:
                    rec = wire.decode(payload)
                except wire.WireError:
                    continue
                if rec.get("op") != "frame":
                    continue
                plane, seq = str(rec["plane"]), int(rec["seq"])
                if seq in live_seqs.get(plane, ()) and seq not in seen.setdefault(
                    plane, set()
                ):
                    seen[plane].add(seq)
                    yield payload

        self._log.rewrite(records())

    def nbytes(self) -> int:
        return self._log.nbytes

    def close(self) -> None:
        self._log.close()


def transport_wal_path(wal_dir: str) -> str:
    return os.path.join(wal_dir, "transport.wal")


class AgentDurableState:
    """The agent's durable registration epoch + per-query exactly-once
    markers, on a FileDatastore (CRC'd, fsync'd, compacting). Keyed by
    ``agent_id`` on disk: several agents (a PEM and the in-process
    kelvin, tests) may share one ``wal_dir`` without one agent's epoch
    making another believe IT restarted."""

    MAX_QUERIES = 512

    def __init__(self, wal_dir: str, agent_id: str):
        safe = agent_id.replace(os.sep, "_")
        self._ds = FileDatastore(
            os.path.join(wal_dir, f"agent-{safe}.db"),
            fsync=_fsync_policy(),
        )
        self._lock = threading.Lock()

    def epoch(self) -> int:
        v = self._ds.get("epoch")
        return int(v) if v else 0

    def save_epoch(self, epoch: int) -> None:
        self._ds.set("epoch", str(int(epoch)).encode())

    def restarts(self) -> int:
        v = self._ds.get("restarts")
        return int(v) if v else 0

    def bump_restarts(self) -> int:
        with self._lock:
            n = self.restarts() + 1
            self._ds.set("restarts", str(n).encode())
            return n

    # -- query markers --------------------------------------------------------
    def query_state(self, query_id: str) -> Optional[str]:
        v = self._ds.get(f"q/{query_id}")
        return v.decode() if v else None

    def mark_started(self, query_id: str) -> None:
        """Durably record that execution began — written BEFORE the
        first result frame can be produced, so a crash mid-execution is
        distinguishable from a crash after completion."""
        with self._lock:
            self._ds.set(f"q/{query_id}", b"started")
            self._trim_locked()

    def mark_done(self, query_id: str) -> None:
        """Every result frame (batches + fragment_done/error) is in the
        transport window/WAL: replay alone completes the query."""
        self._ds.set(f"q/{query_id}", b"done")

    def _trim_locked(self) -> None:
        keys = self._ds.keys("q/")
        # FIFO-ish bound: FileDatastore keys sort lexically, which is
        # arbitrary across uuids — a simple count cap is enough here
        # (markers only matter for the restart window).
        while len(keys) > self.MAX_QUERIES:
            self._ds.delete(keys.pop(0))

    def close(self) -> None:
        self._ds.close()


# -- resident-ring spill ------------------------------------------------------

_RESIDENT_DIR = "resident"


def ring_spill_path(wal_dir: str, table_name: str) -> str:
    safe = table_name.replace(os.sep, "_")
    return os.path.join(wal_dir, _RESIDENT_DIR, f"{safe}.wal")


class RingSpill:
    """Per-table mirror of a ResidentRing's recoverable state.

    Record vocabulary (wire-encoded; numpy columns ride as validated npy
    blobs):

    - ``{"op": "window", "k", "start_row", "rows", "cols"}`` — one full
      staged ring window's RAW host columns.
    - ``{"op": "release", "k"}`` — the ring rolled the window out.
    - ``{"op": "buf", "first_row", "cols"}`` — one append's ring-able
      columns (the partial host buffer, incrementally).
    - ``{"op": "trim", "buf_start"}`` — buffer rows below buf_start were
      consumed into a staged window.
    - ``{"op": "reset"}`` — the ring invalidated itself; nothing before
      this record is recoverable.

    Recovery replays in order; the ``resident.spill_corrupt`` fault site
    lets chaos tests force a window record to read as corrupt, proving
    recovery degrades (window skipped, queries fall back to staging)
    instead of serving bad data.
    """

    def __init__(self, path: str):
        self._log = SegmentLog(path, fsync=_fsync_policy())
        self._lock = threading.Lock()
        self._writes = 0

    def record_window(self, k: int, start_row: int, rows: int, cols) -> None:
        self._append(
            {"op": "window", "k": int(k), "start_row": int(start_row),
             "rows": int(rows), "cols": {n: np.asarray(a) for n, a in cols.items()}}
        )

    def record_release(self, k: int) -> None:
        self._append({"op": "release", "k": int(k)})

    def record_append(self, first_row: int, cols) -> None:
        self._append(
            {"op": "buf", "first_row": int(first_row),
             "cols": {n: np.asarray(a) for n, a in cols.items()}}
        )

    def record_trim(self, buf_start: int) -> None:
        self._append({"op": "trim", "buf_start": int(buf_start)})

    def record_reset(self) -> None:
        self._append({"op": "reset"})

    def _append(self, rec: dict) -> None:
        with self._lock:
            self._log.append(wire.encode(rec))
            self._writes += 1

    def recover(self) -> dict:
        """Replay the log into ``{"windows": {k: (start_row, rows,
        cols)}, "buf": [(first_row, cols)...], "buf_start": int|None,
        "corrupt": int}``. Window records that fail to decode (or that
        the ``resident.spill_corrupt`` fault marks corrupt) are skipped
        and counted — recovery never serves questionable data."""
        windows: dict[int, tuple] = {}
        buf: list[tuple] = []
        buf_start = None
        corrupt = 0
        for payload in self._log.scan():
            try:
                rec = wire.decode(payload)
                op = rec.get("op")
                if op == "window":
                    if faults.ACTIVE and faults.fires("resident.spill_corrupt"):
                        raise wire.WireError(
                            "fault injected: resident.spill_corrupt"
                        )
                    windows[int(rec["k"])] = (
                        int(rec["start_row"]), int(rec["rows"]), rec["cols"]
                    )
                elif op == "release":
                    windows.pop(int(rec["k"]), None)
                elif op == "buf":
                    buf.append((int(rec["first_row"]), rec["cols"]))
                elif op == "trim":
                    buf_start = int(rec["buf_start"])
                    buf = [
                        (r, cols) for r, cols in buf
                        if r + _chunk_rows(cols) > buf_start
                    ]
                elif op == "reset":
                    windows.clear()
                    buf = []
                    buf_start = None
            except (wire.WireError, KeyError, TypeError, ValueError) as e:
                corrupt += 1
                _log.warning("ring spill: skipping bad record: %s", e)
        return {
            "windows": windows, "buf": buf, "buf_start": buf_start,
            "corrupt": corrupt,
        }

    def maybe_compact(self, live_ks, buf_start: int, force: bool = False) -> None:
        """Rewrite the log down to the live state (windows still in the
        ring + buffer chunks at/after ``buf_start``) once dead records
        have accumulated. Scan-filter: live window payloads are re-read
        from the log itself, so compaction never needs a host-resident
        copy of HBM window columns. ``force`` skips the dead-record
        threshold — recovery uses it to persist EXACTLY its adopted
        state, so records it rejected (stale geometry, rows the table
        lost, corrupt payloads) can never resurrect on a later
        restart against a table whose rows they no longer match."""
        with self._lock:
            if (
                not force
                and self._writes < 64
                and self._log.nbytes < (8 << 20)
            ):
                return
            self._writes = 0
        live_ks = set(int(k) for k in live_ks)

        def records():
            seen: set = set()
            for payload in self._log.scan():
                try:
                    rec = wire.decode(payload)
                except wire.WireError:
                    continue
                op = rec.get("op")
                if op == "window":
                    k = int(rec.get("k", -1))
                    if k in live_ks and k not in seen:
                        seen.add(k)
                        yield payload
                elif op == "buf":
                    cols = rec.get("cols") or {}
                    if int(rec.get("first_row", 0)) + _chunk_rows(cols) > (
                        buf_start
                    ):
                        yield payload
            yield wire.encode({"op": "trim", "buf_start": int(buf_start)})

        with self._lock:
            self._log.rewrite(records())

    def nbytes(self) -> int:
        return self._log.nbytes

    def close(self) -> None:
        self._log.close()


def _chunk_rows(cols: dict) -> int:
    for a in cols.values():
        return len(a)
    return 0
