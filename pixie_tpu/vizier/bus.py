"""In-process message bus with NATS-style topics.

Ref: src/common/event/nats.{h,cc} (C++ agent side), src/shared/services/
msgbus/ (Go side), topic scheme src/vizier/utils/messagebus/topic.go:40-55
(``Agent/<id>``, ``v2c.*``/``c2v.*``). At-most-once pub/sub to current
subscribers, like NATS core.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Optional

from pixie_tpu.utils import metrics_registry

_DROPPED = metrics_registry().counter(
    "bus_publish_dropped_total",
    "Messages dropped after blocking on a full bounded subscription.",
)
_DEPTH = metrics_registry().gauge(
    "bus_subscription_depth", "Queued messages per topic (max across subs)."
)
# Lock contention at serving depth (r13, feeds the ~1k-client soak's
# profiling item): time publishers spend WAITING for the bus lock.
# Uncontended publishes pay one non-blocking try_acquire — no timer.
_LOCK_WAIT = metrics_registry().histogram(
    "bus_lock_wait_seconds",
    "Time a publisher waited to acquire the bus subscription lock "
    "(only contended acquisitions are observed).",
)


def agent_topic(agent_id: str) -> str:
    return f"Agent/{agent_id}"


def _topic_label(topic: str) -> str:
    """Metrics label for a topic: per-query/per-agent topics collapse to
    their prefix so the process-global registry stays bounded (per-UUID
    labels would leak one entry per query forever)."""
    return topic.split("/", 1)[0] if "/" in topic else topic


class Subscription:
    """Optionally bounded (maxsize): a full queue blocks publishers up to
    the bus's publish timeout, then drops — flow control for result
    streams (ref: query_result_forwarder.go:502's bounded channels), NATS
    at-most-once drop semantics past the deadline."""

    def __init__(
        self, topic: str, bus: "MessageBus", maxsize: int = 0
    ):
        self.topic = topic
        self._bus = bus
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=maxsize)
        # Messages dropped on THIS subscription after a full-queue timeout.
        # Consumers whose stream must be lossless (the broker's result
        # forwarder) check it and fail the query instead of silently
        # returning partial data.
        self.dropped = 0

    def get(self, timeout: float = None):
        try:
            msg = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        _DEPTH.set(self._q.qsize(), topic=_topic_label(self.topic))
        return msg

    def depth(self) -> int:
        return self._q.qsize()

    def unsubscribe(self) -> None:
        self._bus._unsubscribe(self)


class MessageBus:
    def __init__(self, publish_timeout_s: Optional[float] = None):
        self._lock = threading.Lock()
        self._subs: dict[str, list[Subscription]] = {}
        self._publish_timeout_s = publish_timeout_s

    def _timeout(self) -> float:
        if self._publish_timeout_s is not None:
            return self._publish_timeout_s
        from pixie_tpu.utils import flags

        return flags.broker_publish_timeout_s

    def subscribe(self, topic: str, maxsize: int = 0) -> Subscription:
        sub = Subscription(topic, self, maxsize=maxsize)
        with self._lock:
            self._subs.setdefault(topic, []).append(sub)
        return sub

    def publish(self, topic: str, msg: Any) -> None:
        if not self._lock.acquire(blocking=False):
            t0 = time.perf_counter()
            self._lock.acquire()
            _LOCK_WAIT.observe(time.perf_counter() - t0)
        try:
            subs = list(self._subs.get(topic, ()))
        finally:
            self._lock.release()
        for s in subs:
            try:
                s._q.put(msg, timeout=self._timeout())
            except queue.Full:
                s.dropped += 1
                _DROPPED.inc(topic=_topic_label(topic))
                continue
            _DEPTH.set(s._q.qsize(), topic=_topic_label(topic))

    def _unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            subs = self._subs.get(sub.topic, [])
            if sub in subs:
                subs.remove(sub)
