"""In-process message bus with NATS-style topics.

Ref: src/common/event/nats.{h,cc} (C++ agent side), src/shared/services/
msgbus/ (Go side), topic scheme src/vizier/utils/messagebus/topic.go:40-55
(``Agent/<id>``, ``v2c.*``/``c2v.*``). At-most-once pub/sub to current
subscribers, like NATS core.
"""

from __future__ import annotations

import queue
import threading
from typing import Any


def agent_topic(agent_id: str) -> str:
    return f"Agent/{agent_id}"


class Subscription:
    def __init__(self, topic: str, bus: "MessageBus"):
        self.topic = topic
        self._bus = bus
        self._q: "queue.Queue[Any]" = queue.Queue()

    def get(self, timeout: float = None):
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def unsubscribe(self) -> None:
        self._bus._unsubscribe(self)


class MessageBus:
    def __init__(self):
        self._lock = threading.Lock()
        self._subs: dict[str, list[Subscription]] = {}

    def subscribe(self, topic: str) -> Subscription:
        sub = Subscription(topic, self)
        with self._lock:
            self._subs.setdefault(topic, []).append(sub)
        return sub

    def publish(self, topic: str, msg: Any) -> None:
        with self._lock:
            subs = list(self._subs.get(topic, ()))
        for s in subs:
            s._q.put(msg)

    def _unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            subs = self._subs.get(sub.topic, [])
            if sub in subs:
                subs.remove(sub)
