"""Tracepoint mutation path: registry + deploy + agent-side manager.

Ref: the px.DeployTracepoint call stack (SURVEY §3.4) —
query_broker/controllers/mutation_executor.go compiles pxtrace programs,
the metadata service's tracepoint registry persists them
(metadata/controllers/tracepoint/tracepoint.go), agents' PEM
TracepointManager (agent/pem/tracepoint_manager.{h,cc}) deploys into
Stirling via RegisterTracepoint, and the new table schema becomes
queryable. Here the deploy lands a synthetic DynamicTraceConnector in
the agent's IngestCore (kernel uprobes are out of scope on TPU hosts;
the compile→registry→deploy→table lifecycle is the parity surface).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Optional

import numpy as np

from pixie_tpu.compiler.probes import (
    MutationsIR,
    TracepointDeployment,
    compile_trace,
)
from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.vizier.datastore import Datastore

TRACEPOINT_TOPIC = "tracepoint_updates"
_TP_PREFIX = "/tracepoint/"


class DynamicTraceConnector(SourceConnector):
    """Stands in for the BCC-deployed uprobe: emits synthetic events with
    the tracepoint's schema at the sampling cadence (ref: the
    dynamic_tracer's deployed probe filling its DataTable)."""

    sample_period_s = 0.02
    push_period_s = 0.05

    def __init__(self, deployment: TracepointDeployment, rows_per_sample=8):
        super().__init__()
        self.name = f"dynamic:{deployment.name}"
        self.deployment = deployment
        self.rows_per_sample = rows_per_sample
        self._rng = np.random.default_rng(abs(hash(deployment.name)) % 2**32)
        self._deadline = time.time_ns() + deployment.ttl_ns
        self.tables = [
            DataTable(deployment.table_name, deployment.output_relation())
        ]

    @property
    def expired(self) -> bool:
        return time.time_ns() > self._deadline

    def transfer_data_impl(self, ctx) -> None:
        if self.expired:
            return  # TTL expired: the probe stops producing
        n = self.rows_per_sample
        now = time.time_ns()
        data = {
            "time_": now + np.arange(n),
            "upid": np.array(
                [f"1:{100 + i % 4}:{i % 4 + 1}" for i in range(n)],
                dtype=object,
            ),
        }
        for c in self.deployment.columns:
            if c.kind == "latency":
                data[c.name] = self._rng.integers(10**3, 10**7, n)
            else:
                data[c.name] = np.array(
                    [f"{c.expr}={i}" for i in self._rng.integers(0, 50, n)],
                    dtype=object,
                )
        self.tables[0].append_columns(data)


class TracepointRegistry:
    """Durable tracepoint specs (metadata/controllers/tracepoint)."""

    def __init__(self, store: Datastore):
        self.store = store

    def upsert(self, dep: TracepointDeployment) -> None:
        self.store.set(
            _TP_PREFIX + dep.name,
            json.dumps(dataclasses.asdict(dep)).encode(),
        )

    def delete(self, name: str) -> None:
        self.store.delete(_TP_PREFIX + name)

    def get(self, name: str) -> Optional[TracepointDeployment]:
        raw = self.store.get(_TP_PREFIX + name)
        return _dep_from_json(raw) if raw is not None else None

    def list(self) -> list[TracepointDeployment]:
        return [
            _dep_from_json(raw)
            for _, raw in self.store.get_prefix(_TP_PREFIX)
        ]


def _dep_from_dict(d: dict) -> TracepointDeployment:
    from pixie_tpu.compiler.probes import TraceColumn

    d = dict(d)
    d["columns"] = tuple(TraceColumn(**c) for c in d["columns"])
    return TracepointDeployment(**d)


def _dep_from_json(raw: bytes) -> TracepointDeployment:
    return _dep_from_dict(json.loads(raw))


class MutationExecutor:
    """Broker-side: compile pxtrace -> persist -> broadcast deploys
    (ref: mutation_executor.go + CompileMutations)."""

    def __init__(self, registry: TracepointRegistry, bus=None):
        self.registry = registry
        self.bus = bus

    def execute(self, query: str) -> MutationsIR:
        mutations = compile_trace(query)
        for dep in mutations.deployments:
            self.registry.upsert(dep)
            self._broadcast(
                {"type": "tracepoint_deploy",
                 "deployment": dataclasses.asdict(dep)}
            )
        for name in mutations.deletions:
            self.registry.delete(name)
            self._broadcast({"type": "tracepoint_delete", "name": name})
        return mutations

    def _broadcast(self, msg: dict) -> None:
        if self.bus is not None:
            self.bus.publish(TRACEPOINT_TOPIC, msg)


class TracepointManager:
    """Agent-side: applies deploy/delete messages to the agent's
    IngestCore + table store (ref: pem/tracepoint_manager.{h,cc} →
    Stirling::RegisterTracepoint, stirling.h:114)."""

    def __init__(self, bus, ingest_core, table_store):
        self.core = ingest_core
        self.table_store = table_store
        self._connectors: dict[str, DynamicTraceConnector] = {}
        self._sub = bus.subscribe(TRACEPOINT_TOPIC)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            msg = self._sub.get(timeout=0.05)
            # Sweep TTL-expired probes: a dead tracepoint must not keep
            # ticking the ingest loop (the reference expires + removes).
            for name, conn in list(self._connectors.items()):
                if conn.expired:
                    self.remove(name)
            if msg is None:
                continue
            if msg["type"] == "tracepoint_deploy":
                self.deploy(_dep_from_dict(msg["deployment"]))
            elif msg["type"] == "tracepoint_delete":
                self.remove(msg["name"])

    def deploy(self, dep: TracepointDeployment) -> None:
        if dep.name in self._connectors:
            # UPSERT semantics: replace the running connector so schema/
            # target/TTL changes take effect (the registry already holds
            # the new spec).
            self.remove(dep.name)
        conn = DynamicTraceConnector(dep)
        conn.init()
        self._connectors[dep.name] = conn
        self.core.register_source(conn)
        # Publish the new table schema (ref: new schema published after
        # RegisterTracepoint so PxL can query it). A re-upsert that CHANGED
        # the schema must replace the table, or pushes built from the old
        # relation would KeyError and kill the ingest loop.
        rel = dep.output_relation()
        existing = self.table_store.get_table(dep.table_name)
        if existing is None or existing.relation != rel:
            self.table_store.create_table(dep.table_name, rel)

    def remove(self, name: str) -> None:
        conn = self._connectors.pop(name, None)
        if conn is not None:
            conn.stop()
            self.core.deregister_source(conn)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self._sub.unsubscribe()
