"""Typed wire format for the control plane — no pickle on network input.

Ref posture: the reference's control planes move TLS-authenticated
protobufs (NATS VizierMessage envelopes, src/vizier/messages/messagespb/;
gRPC TransferResultChunk, src/carnot/carnotpb/carnot.proto) — never
language-native object serialization. This module is the planpb-equivalent
schema layer for our TCP transport: a closed, self-describing encoding of
control messages, plan DAGs, and data batches. Decoding constructs ONLY
allowlisted types — a hostile peer can produce garbage values, not code
execution (the pickle transport this replaces was RCE-one-port-away;
ADVICE r3 medium).

Layout: ``b"PW" | version u8 | json_len u32 | json | blobs``, each blob
``len u64 | bytes``. The JSON tree uses ``$``-tagged nodes for non-JSON
types; RowBatch/StateBatch ride their existing explicit wire formats
(row_batch.py to_bytes / agg_node.StateBatch.to_bytes) as blob
attachments, so bulk data is never base64-inflated.
"""

from __future__ import annotations

import dataclasses
import io
import json
import struct
from typing import Any

import numpy as np

from pixie_tpu.plan.expressions import (
    AggregateExpression,
    ColumnRef,
    Constant,
    FuncCall,
)
from pixie_tpu.plan.operators import (
    AggOp,
    AggStage,
    BridgeSinkOp,
    BridgeSourceOp,
    EmptySourceOp,
    FilterOp,
    InlineSourceOp,
    JoinOp,
    JoinType,
    LimitOp,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    OTelExportSinkOp,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)
from pixie_tpu.plan.plan import Plan, PlanFragment
from pixie_tpu.types import DataType, Relation, SemanticType

_MAGIC = b"PW"
_VERSION = 1
_HDR = struct.Struct(">2sBI")
_BLOB_LEN = struct.Struct(">Q")

# Closed allowlists. Anything not here fails encode AND decode loudly —
# adding a message type is an explicit schema change, like editing a proto.
_DATACLASSES = {
    cls.__name__: cls
    for cls in (
        MemorySourceOp,
        UDTFSourceOp,
        EmptySourceOp,
        InlineSourceOp,
        BridgeSourceOp,
        MapOp,
        FilterOp,
        AggOp,
        JoinOp,
        LimitOp,
        UnionOp,
        MemorySinkOp,
        ResultSinkOp,
        OTelExportSinkOp,
        BridgeSinkOp,
        ColumnRef,
        Constant,
        FuncCall,
        AggregateExpression,
    )
}
_ENUMS = {
    cls.__name__: cls for cls in (DataType, SemanticType, AggStage, JoinType)
}


class WireError(ValueError):
    """Malformed or disallowed wire content."""


# -- transport control-frame schema (r10) ------------------------------------
# The connection-level protocol's closed frame vocabulary: ``kind`` ->
# {required field: type}. Adding a frame kind is an explicit schema change,
# like editing a proto. ``seq`` (per-identity-plane monotonic sequence) is
# optional on any client frame; the delivery-session frames are:
#
#   session        client->server, once, right after the auth handshake:
#                  stable identity + plane + strictly-increasing epoch
#                  (stale epochs are rejected; zombie sockets cannot
#                  interleave with their replacement).
#   session_ok     server->client: the server's per-(identity, plane)
#                  APPLIED watermark — the client replays its in-flight
#                  window strictly above it.
#   session_reject server->client, then close: epoch was stale.
#   ack            server->client: cumulative — every frame with
#                  seq' <= seq is applied; the client's window releases
#                  them (Kafka idempotent-producer / NATS pending-window
#                  shape).
FRAME_FIELDS: dict[str, dict[str, type]] = {
    "challenge": {"nonce": bytes},
    "hello": {"mac": bytes, "nonce": bytes},
    "welcome": {"mac": bytes},
    "session": {"agent_id": str, "plane": str, "epoch": int},
    "session_ok": {"last_seq": int},
    "session_reject": {"reason": str},
    "ack": {"seq": int},
    "publish": {"topic": str},
    "subscribe": {"topic": str},
    "unsubscribe": {"topic": str},
    "message": {"topic": str},
    "bridge_register": {"query_id": str, "bridge_id": str},
    "bridge_push": {"query_id": str, "bridge_id": str},
}

# Optional frame fields (r11): type-checked when present, never required.
# ``seq`` is the per-identity-plane delivery sequence (r10); ``trace_id``/
# ``span_id`` carry the Dapper-style trace context of the query whose data
# the frame moves (utils/trace.py), so transport-level send/ack latency
# spans can be joined back to the originating query's trace.
OPTIONAL_FRAME_FIELDS: dict[str, type] = {
    "seq": int,
    "want_ack": bool,
    "trace_id": str,
    "span_id": str,
    # r14: a session presented by a RESTARTED process (identity restored
    # from its WAL, epoch bumped past the persisted counter) — lets the
    # server distinguish crash recovery from a plain reconnect.
    "restarted": bool,
}


def validate_frame(frame: Any) -> dict:
    """Schema-check one decoded control frame: known ``kind``,
    correctly-typed required fields (bool is not an int here), and
    correctly-typed optional fields when present. Raises WireError —
    callers treat that as a hostile/broken peer."""
    if not isinstance(frame, dict) or not isinstance(frame.get("kind"), str):
        raise WireError("frame is not a kind-tagged message")
    spec = FRAME_FIELDS.get(frame["kind"])
    if spec is None:
        raise WireError(f"unknown frame kind {frame['kind']!r}")
    for field, typ in spec.items():
        v = frame.get(field)
        if not isinstance(v, typ) or (typ is int and isinstance(v, bool)):
            raise WireError(
                f"frame {frame['kind']!r}: field {field!r} must be "
                f"{typ.__name__}, got {type(v).__name__}"
            )
    for field, typ in OPTIONAL_FRAME_FIELDS.items():
        if field in spec or field not in frame:
            continue
        v = frame[field]
        if not isinstance(v, typ) or (typ is int and isinstance(v, bool)):
            raise WireError(
                f"frame {frame['kind']!r}: optional field {field!r} must "
                f"be {typ.__name__}, got {type(v).__name__}"
            )
    return frame


class _Encoder:
    def __init__(self):
        self.blobs: list[bytes] = []

    def _blob(self, data: bytes) -> int:
        self.blobs.append(data)
        return len(self.blobs) - 1

    def enc(self, obj: Any):
        if obj is None or isinstance(obj, (bool, str)):
            return obj
        # Enums before int: DataType/SemanticType are IntEnums.
        for name, cls in _ENUMS.items():
            if isinstance(obj, cls):
                return {"$e": f"{name}:{obj.name}"}
        if isinstance(obj, int):
            return obj
        if isinstance(obj, float):
            if obj != obj:
                return {"$f": "nan"}
            if obj in (float("inf"), float("-inf")):
                return {"$f": "inf" if obj > 0 else "-inf"}
            return obj
        if isinstance(obj, (bytes, bytearray, memoryview)):
            return {"$b": self._blob(bytes(obj))}
        if isinstance(obj, tuple):
            return {"$tu": [self.enc(v) for v in obj]}
        if isinstance(obj, list):
            return [self.enc(v) for v in obj]
        if isinstance(obj, (set, frozenset)):
            kind = "$fset" if isinstance(obj, frozenset) else "$set"
            return {kind: [self.enc(v) for v in obj]}
        if isinstance(obj, dict):
            return {"$map": [[self.enc(k), self.enc(v)] for k, v in obj.items()]}
        # numpy scalars widen to Python; arrays ride npy blobs.
        if isinstance(obj, np.generic):
            return self.enc(obj.item())
        if isinstance(obj, np.ndarray):
            if obj.dtype == object:
                raise WireError("object-dtype arrays are not wire-encodable")
            buf = io.BytesIO()
            np.save(buf, obj, allow_pickle=False)
            return {"$np": self._blob(buf.getvalue())}
        if isinstance(obj, Relation):
            return {"$rel": obj.to_dict()}
        if isinstance(obj, PlanFragment):
            return {
                "$frag": {
                    "fragment_id": obj.fragment_id,
                    "nodes": [
                        [nid, obj.parents(nid), self.enc(obj.node(nid))]
                        for nid in sorted(obj.nodes())
                    ],
                }
            }
        if isinstance(obj, Plan):
            return {
                "$plan": {
                    "query_id": obj.query_id,
                    "fragments": [self.enc(f) for f in obj.fragments],
                    "executing_instance": [
                        [k, v] for k, v in obj.executing_instance.items()
                    ],
                }
            }
        cls_name = type(obj).__name__
        if cls_name in _DATACLASSES and type(obj) is _DATACLASSES[cls_name]:
            fields = {
                f.name: self.enc(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
            }
            return {"$s": cls_name, "f": fields}
        # Batches last: they are classes with explicit wire formats.
        from pixie_tpu.exec.agg_node import StateBatch
        from pixie_tpu.table.row_batch import RowBatch

        if isinstance(obj, RowBatch):
            return {"$rb": self._blob(obj.to_bytes())}
        if isinstance(obj, StateBatch):
            return {"$sb": self._blob(obj.to_bytes())}
        raise WireError(f"type {type(obj).__name__} is not wire-encodable")


def _validated_npy_load(blob: bytes) -> np.ndarray:
    """np.load that verifies the header-claimed payload size against the
    actual blob BEFORE np.load allocates — np.empty(shape) happens before
    any data is read, so a ~100-byte forged header could otherwise demand
    a 128GiB allocation (verified in r4 review)."""
    f = io.BytesIO(blob)
    try:
        version = np.lib.format.read_magic(f)
        shape, fortran, dtype = np.lib.format._read_array_header(f, version)
    except Exception as e:
        raise WireError(f"bad npy header: {e}") from None
    if dtype.hasobject:
        raise WireError("object-dtype arrays are not wire-decodable")
    expected = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    remaining = len(blob) - f.tell()
    if expected != remaining:
        raise WireError(
            f"npy header claims {expected} payload bytes, blob has {remaining}"
        )
    f.seek(0)
    return np.load(f, allow_pickle=False)


class _Decoder:
    def __init__(self, blobs: list[bytes], allow_arrays: bool = True):
        self.blobs = blobs
        self.allow_arrays = allow_arrays

    def _blob(self, idx: Any) -> bytes:
        if not isinstance(idx, int) or not 0 <= idx < len(self.blobs):
            raise WireError(f"bad blob reference {idx!r}")
        return self.blobs[idx]

    def dec(self, node: Any):
        if node is None or isinstance(node, (bool, int, float, str)):
            return node
        if isinstance(node, list):
            return [self.dec(v) for v in node]
        if not isinstance(node, dict):
            raise WireError(f"bad wire node {type(node).__name__}")
        if len(node) == 1 or (len(node) == 2 and "$s" in node):
            return self._dec_tagged(node)
        raise WireError(f"bad wire node keys {sorted(node)}")

    def _dec_tagged(self, node: dict):
        if "$f" in node:
            return {"nan": float("nan"), "inf": float("inf"), "-inf": float("-inf")}[
                node["$f"]
            ]
        if "$b" in node:
            return self._blob(node["$b"])
        if "$tu" in node:
            return tuple(self.dec(v) for v in node["$tu"])
        if "$set" in node:
            return {self.dec(v) for v in node["$set"]}
        if "$fset" in node:
            return frozenset(self.dec(v) for v in node["$fset"])
        if "$map" in node:
            return {self.dec(k): self.dec(v) for k, v in node["$map"]}
        if "$np" in node:
            if not self.allow_arrays:
                raise WireError("arrays are not allowed in this context")
            return _validated_npy_load(self._blob(node["$np"]))
        if "$e" in node:
            enum_name, _, member = node["$e"].partition(":")
            cls = _ENUMS.get(enum_name)
            if cls is None or member not in cls.__members__:
                raise WireError(f"unknown enum {node['$e']!r}")
            return cls[member]
        if "$rel" in node:
            return Relation.from_dict(node["$rel"])
        if "$frag" in node:
            spec = node["$frag"]
            frag = PlanFragment(fragment_id=int(spec["fragment_id"]))
            # Nodes arrive in ascending nid order; re-adding preserves ids
            # only when they are dense from 0 — enforce rather than assume.
            for nid, parents, op_node in spec["nodes"]:
                op = self.dec(op_node)
                got = frag.add(op, [int(p) for p in parents])
                if got != int(nid):
                    raise WireError("fragment node ids are not dense from 0")
            return frag
        if "$plan" in node:
            spec = node["$plan"]
            plan = Plan(str(spec["query_id"]))
            for f in spec["fragments"]:
                frag = self.dec(f)
                plan.fragments.append(frag)
            plan.executing_instance = {
                int(k): (None if v is None else str(v))
                for k, v in spec["executing_instance"]
            }
            return plan
        if "$s" in node:
            cls = _DATACLASSES.get(node["$s"])
            if cls is None:
                raise WireError(f"unknown struct {node['$s']!r}")
            fields = node.get("f", {})
            names = {f.name for f in dataclasses.fields(cls)}
            if set(fields) - names:
                raise WireError(
                    f"unknown fields for {node['$s']}: {sorted(set(fields) - names)}"
                )
            return cls(**{k: self.dec(v) for k, v in fields.items()})
        if "$rb" in node:
            if not self.allow_arrays:
                raise WireError("batches are not allowed in this context")
            from pixie_tpu.table.row_batch import RowBatch

            return RowBatch.from_bytes(self._blob(node["$rb"]))
        if "$sb" in node:
            if not self.allow_arrays:
                raise WireError("batches are not allowed in this context")
            from pixie_tpu.exec.agg_node import StateBatch

            return StateBatch.from_bytes(self._blob(node["$sb"]))
        raise WireError(f"unknown wire tag {sorted(node)}")


def encode(obj: Any) -> bytes:
    enc = _Encoder()
    tree = enc.enc(obj)
    body = json.dumps(tree, separators=(",", ":"), allow_nan=False).encode()
    out = io.BytesIO()
    out.write(_HDR.pack(_MAGIC, _VERSION, len(body)))
    out.write(body)
    for b in enc.blobs:
        out.write(_BLOB_LEN.pack(len(b)))
        out.write(b)
    return out.getvalue()


def decode(data: bytes, allow_arrays: bool = True) -> Any:
    """Decode a frame. ``allow_arrays=False`` additionally refuses
    $np/$rb/$sb nodes — REQUIRED for pre-authentication reads, where a
    forged numpy header inside a tiny frame is an allocation bomb that the
    transport's frame-length cap cannot see."""
    if len(data) < _HDR.size:
        raise WireError("short frame")
    magic, version, json_len = _HDR.unpack_from(data, 0)
    if magic != _MAGIC or version != _VERSION:
        raise WireError(f"bad magic/version {magic!r}/{version}")
    off = _HDR.size
    if off + json_len > len(data):
        raise WireError("truncated frame body")
    try:
        tree = json.loads(data[off : off + json_len].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"bad frame json: {e}") from None
    off += json_len
    blobs: list[bytes] = []
    while off < len(data):
        if off + _BLOB_LEN.size > len(data):
            raise WireError("truncated blob header")
        (n,) = _BLOB_LEN.unpack_from(data, off)
        off += _BLOB_LEN.size
        if off + n > len(data):
            raise WireError("truncated blob")
        blobs.append(data[off : off + n])
        off += n
    try:
        return _Decoder(blobs, allow_arrays=allow_arrays).dec(tree)
    except WireError:
        raise
    except (KeyError, TypeError, ValueError, RecursionError) as e:
        # Keep the contract: malformed content surfaces as WireError only
        # (bad $f token, unhashable map keys, corrupt npy, depth bombs).
        raise WireError(f"malformed wire content: {e}") from None
