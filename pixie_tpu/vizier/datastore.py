"""Pluggable KV datastore.

Ref: src/vizier/utils/datastore/datastore.go — a small Get/Set/Delete/
GetWithPrefix interface with pebble (default), etcd, badger, buntdb
backends. Here: an in-memory store and a file-backed store whose
append-only JSON-lines log with periodic compaction fills pebble's role
(durable metadata that survives agent restarts) without a native KV
dependency. Values are bytes; keys are '/'-scoped strings.
"""

from __future__ import annotations

import base64
import json
import os
import threading
from typing import Optional


class Datastore:
    """In-memory backend (and the interface contract)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: dict[str, bytes] = {}

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._data[key] = bytes(value)
            self._on_write(key, value)

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)
            self._on_write(key, None)

    def delete_prefix(self, prefix: str) -> None:
        with self._lock:
            for k in [k for k in self._data if k.startswith(prefix)]:
                del self._data[k]
                self._on_write(k, None)

    def get_prefix(self, prefix: str) -> list[tuple[str, bytes]]:
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items() if k.startswith(prefix)
            )

    def keys(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))

    def close(self) -> None:
        pass

    # backend hook
    def _on_write(self, key: str, value: Optional[bytes]) -> None:
        pass


class FileDatastore(Datastore):
    """Durable backend: JSON-lines write-ahead log, replayed at open,
    compacted when the log grows past ``compact_every`` records (the role
    pebble plays for the reference's metadata service)."""

    def __init__(self, path: str, compact_every: int = 4096):
        super().__init__()
        self.path = path
        self.compact_every = compact_every
        self._writes_since_compact = 0
        self._f = None
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    if rec.get("v") is None:
                        self._data.pop(rec["k"], None)
                    else:
                        self._data[rec["k"]] = base64.b64decode(rec["v"])
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")

    def _on_write(self, key: str, value: Optional[bytes]) -> None:
        if self._f is None:
            return
        rec = {
            "k": key,
            "v": base64.b64encode(value).decode() if value is not None else None,
        }
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()
        self._writes_since_compact += 1
        if self._writes_since_compact >= self.compact_every:
            self._compact_locked()

    def _compact_locked(self) -> None:
        tmp = self.path + ".compact"
        with open(tmp, "w") as f:
            for k, v in sorted(self._data.items()):
                f.write(
                    json.dumps(
                        {"k": k, "v": base64.b64encode(v).decode()}
                    )
                    + "\n"
                )
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "a")
        self._writes_since_compact = 0

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
