"""Pluggable KV datastore.

Ref: src/vizier/utils/datastore/datastore.go — a small Get/Set/Delete/
GetWithPrefix interface with pebble (default), etcd, badger, buntdb
backends. Here three backends: in-memory, an append-only JSON-lines log
with CRC-checked records, torn-tail recovery, and periodic compaction
(the log-structured store), and a sqlite-backed store in WAL mode (the
pebble-class durable default — real fsync'd crash safety from a battle-
tested engine). Values are bytes; keys are '/'-scoped strings.
"""

from __future__ import annotations

import base64
import json
import os
import sqlite3
import threading
import zlib
from typing import Optional

from pixie_tpu.utils import faults


class Datastore:
    """In-memory backend (and the interface contract)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: dict[str, bytes] = {}

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def set(self, key: str, value: bytes) -> None:
        # Fault site BEFORE any mutation: an injected append failure must
        # leave the in-memory view and the log consistent (chaos tests
        # assert the failed write is absent from both).
        if faults.ACTIVE:
            faults.check("datastore.append")
        with self._lock:
            self._data[key] = bytes(value)
            self._on_write(key, value)

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)
            self._on_write(key, None)

    def delete_prefix(self, prefix: str) -> None:
        with self._lock:
            for k in [k for k in self._data if k.startswith(prefix)]:
                del self._data[k]
                self._on_write(k, None)

    def get_prefix(self, prefix: str) -> list[tuple[str, bytes]]:
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items() if k.startswith(prefix)
            )

    def keys(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))

    def close(self) -> None:
        pass

    # backend hook
    def _on_write(self, key: str, value: Optional[bytes]) -> None:
        pass


class FileDatastore(Datastore):
    """Log-structured backend: JSON-lines write-ahead log with a per-record
    CRC32, replayed at open, compacted when the log grows past
    ``compact_every`` records (the role pebble plays for the reference's
    metadata service).

    Crash posture: a record is ``<json>\\t<crc32-hex>\\n``. A torn tail
    (process killed mid-write) or a bit-flipped record fails the CRC or the
    JSON parse; replay stops at the first bad record, keeps everything
    before it, and truncates the log there — the pebble/WAL recovery
    contract (complete records survive, the torn suffix is discarded)."""

    def __init__(self, path: str, compact_every: int = 4096):
        super().__init__()
        self.path = path
        self.compact_every = compact_every
        self._writes_since_compact = 0
        self._f = None
        good_end = 0
        if os.path.exists(path):
            with open(path, "rb") as f:
                for line in f:
                    rec = self._parse_record(line)
                    if rec is None:
                        break  # torn/corrupt tail: discard from here on
                    key, value = rec
                    if value is None:
                        self._data.pop(key, None)
                    else:
                        self._data[key] = value
                    good_end += len(line)
            if good_end < os.path.getsize(path):
                with open(path, "r+b") as f:
                    f.truncate(good_end)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")

    @staticmethod
    def _parse_record(line: bytes) -> Optional[tuple[str, Optional[bytes]]]:
        if not line.endswith(b"\n"):
            return None  # torn write: no terminator
        body, sep, crc_hex = line.rstrip(b"\n").rpartition(b"\t")
        if not sep:
            # Legacy pre-CRC format (plain JSON line, r3): accept it —
            # treating old logs as torn tails would truncate the whole
            # store to zero on upgrade. JSON never emits a raw tab byte,
            # so the formats are unambiguous.
            body = line.rstrip(b"\n")
        else:
            try:
                if int(crc_hex, 16) != zlib.crc32(body):
                    return None
            except ValueError:
                return None
        try:
            rec = json.loads(body)
            v = rec.get("v")
            return rec["k"], (None if v is None else base64.b64decode(v))
        except (ValueError, KeyError, TypeError):
            return None

    @staticmethod
    def _format_record(key: str, value: Optional[bytes]) -> bytes:
        body = json.dumps(
            {
                "k": key,
                "v": base64.b64encode(value).decode()
                if value is not None
                else None,
            }
        ).encode()
        return body + b"\t" + format(zlib.crc32(body), "08x").encode() + b"\n"

    def _on_write(self, key: str, value: Optional[bytes]) -> None:
        if self._f is None:
            return
        self._f.write(self._format_record(key, value))
        self._f.flush()
        os.fsync(self._f.fileno())
        self._writes_since_compact += 1
        if self._writes_since_compact >= self.compact_every:
            self._compact_locked()

    def _compact_locked(self) -> None:
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for k, v in sorted(self._data.items()):
                f.write(self._format_record(k, v))
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        self._writes_since_compact = 0

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class SqliteDatastore(Datastore):
    """Durable default backend on sqlite in WAL mode — the pebble-class
    engine (ref: src/vizier/utils/datastore/pebbledb/ is the reference
    default). Crash safety comes from sqlite's own journal; every write is
    a committed transaction."""

    def __init__(self, path: str):
        super().__init__()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=FULL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v BLOB NOT NULL)"
        )
        self._db.commit()
        # Warm the in-memory mirror so reads never touch the DB and the
        # base-class interface (get/get_prefix under one lock) holds.
        for k, v in self._db.execute("SELECT k, v FROM kv"):
            self._data[k] = bytes(v)

    def _on_write(self, key: str, value: Optional[bytes]) -> None:
        if self._db is None:
            return
        if value is None:
            self._db.execute("DELETE FROM kv WHERE k = ?", (key,))
        else:
            self._db.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) "
                "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (key, bytes(value)),
            )
        self._db.commit()

    def close(self) -> None:
        with self._lock:
            if self._db is not None:
                self._db.close()
                self._db = None
