"""Pluggable KV datastore.

Ref: src/vizier/utils/datastore/datastore.go — a small Get/Set/Delete/
GetWithPrefix interface with pebble (default), etcd, badger, buntdb
backends. Here three backends: in-memory, an append-only JSON-lines log
with CRC-checked records, torn-tail recovery, and periodic compaction
(the log-structured store), and a sqlite-backed store in WAL mode (the
pebble-class durable default — real fsync'd crash safety from a battle-
tested engine). Values are bytes; keys are '/'-scoped strings.
"""

from __future__ import annotations

import base64
import json
import os
import sqlite3
import struct
import threading
import zlib
from typing import Optional

from pixie_tpu.utils import faults


def _fsync_dir(path: str) -> None:
    """fsync the directory containing ``path`` so a just-renamed file's
    directory entry is durable (the classic missing half of the
    write-temp + rename pattern)."""
    d = os.path.dirname(path) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class Datastore:
    """In-memory backend (and the interface contract)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: dict[str, bytes] = {}

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def set(self, key: str, value: bytes) -> None:
        # Fault site BEFORE any mutation: an injected append failure must
        # leave the in-memory view and the log consistent (chaos tests
        # assert the failed write is absent from both).
        if faults.ACTIVE:
            faults.check("datastore.append")
        with self._lock:
            self._data[key] = bytes(value)
            self._on_write(key, value)

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)
            self._on_write(key, None)

    def delete_prefix(self, prefix: str) -> None:
        with self._lock:
            for k in [k for k in self._data if k.startswith(prefix)]:
                del self._data[k]
                self._on_write(k, None)

    def get_prefix(self, prefix: str) -> list[tuple[str, bytes]]:
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items() if k.startswith(prefix)
            )

    def keys(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))

    def close(self) -> None:
        pass

    # backend hook
    def _on_write(self, key: str, value: Optional[bytes]) -> None:
        pass


class FileDatastore(Datastore):
    """Log-structured backend: JSON-lines write-ahead log with a per-record
    CRC32, replayed at open, compacted when the log grows past
    ``compact_every`` records (the role pebble plays for the reference's
    metadata service).

    Crash posture: a record is ``<json>\\t<crc32-hex>\\n``. A torn tail
    (process killed mid-write) or a bit-flipped record fails the CRC or the
    JSON parse; replay stops at the first bad record, keeps everything
    before it, and truncates the log there — the pebble/WAL recovery
    contract (complete records survive, the torn suffix is discarded)."""

    def __init__(
        self, path: str, compact_every: int = 4096, fsync: bool = True
    ):
        super().__init__()
        self.path = path
        self.compact_every = compact_every
        self._fsync = fsync
        self._writes_since_compact = 0
        self._f = None
        # A stale .compact temp means a previous process died mid-
        # compaction BEFORE the atomic rename: the main log is still the
        # authority (it holds every record the temp would), so the temp
        # is garbage — remove it rather than ever risk reading it.
        tmp = path + ".compact"
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
        good_end = 0
        if os.path.exists(path):
            with open(path, "rb") as f:
                for line in f:
                    rec = self._parse_record(line)
                    if rec is None:
                        break  # torn/corrupt tail: discard from here on
                    key, value = rec
                    if value is None:
                        self._data.pop(key, None)
                    else:
                        self._data[key] = value
                    good_end += len(line)
            if good_end < os.path.getsize(path):
                with open(path, "r+b") as f:
                    f.truncate(good_end)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")

    @staticmethod
    def _parse_record(line: bytes) -> Optional[tuple[str, Optional[bytes]]]:
        if not line.endswith(b"\n"):
            return None  # torn write: no terminator
        body, sep, crc_hex = line.rstrip(b"\n").rpartition(b"\t")
        if not sep:
            # Legacy pre-CRC format (plain JSON line, r3): accept it —
            # treating old logs as torn tails would truncate the whole
            # store to zero on upgrade. JSON never emits a raw tab byte,
            # so the formats are unambiguous.
            body = line.rstrip(b"\n")
        else:
            try:
                if int(crc_hex, 16) != zlib.crc32(body):
                    return None
            except ValueError:
                return None
        try:
            rec = json.loads(body)
            v = rec.get("v")
            return rec["k"], (None if v is None else base64.b64decode(v))
        except (ValueError, KeyError, TypeError):
            return None

    @staticmethod
    def _format_record(key: str, value: Optional[bytes]) -> bytes:
        body = json.dumps(
            {
                "k": key,
                "v": base64.b64encode(value).decode()
                if value is not None
                else None,
            }
        ).encode()
        return body + b"\t" + format(zlib.crc32(body), "08x").encode() + b"\n"

    def _on_write(self, key: str, value: Optional[bytes]) -> None:
        if self._f is None:
            return
        rec = self._format_record(key, value)
        if faults.ACTIVE and faults.fires("wal.torn_write"):
            # Simulated crash mid-write(): only a prefix of the record
            # reaches the file. Recovery must truncate it (the CRC/
            # terminator check) and the writer sees the crash.
            self._f.write(rec[: max(1, len(rec) // 2)])
            self._f.flush()
            raise faults.FaultInjectedError("wal.torn_write")
        self._f.write(rec)
        self._f.flush()
        if self._fsync:
            os.fsync(self._f.fileno())
        self._writes_since_compact += 1
        if self._writes_since_compact >= self.compact_every:
            self._compact_locked()

    def _compact_locked(self) -> None:
        """Crash-safe compaction: the full state is written to a temp
        file and fsync'd BEFORE the atomic rename, and the directory is
        fsync'd after, so a crash at any point leaves either the old
        complete log or the new complete log — never a partial one (a
        stale temp from a crash before the rename is removed at open)."""
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for k, v in sorted(self._data.items()):
                f.write(self._format_record(k, v))
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        _fsync_dir(self.path)
        self._f = open(self.path, "ab")
        self._writes_since_compact = 0

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class SegmentLog:
    """Binary append-only record log with per-record CRC32 and torn-tail
    recovery — the spill substrate for the r14 durability plane (the
    transport ack-window WAL and the resident-ring spill), sharing the
    FileDatastore crash posture for opaque binary payloads (no
    base64/JSON inflation on multi-MB frames).

    Record layout: ``u32 length | u32 crc32(payload) | payload``. A torn
    tail (short record, bad CRC) stops the scan; everything before it
    survives and the file is truncated there at open. ``rewrite``
    replaces the log with a fresh record sequence via the hardened
    write-temp + fsync + atomic-rename + dir-fsync pattern."""

    _HDR = struct.Struct(">II")

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self._fsync = fsync
        # Reentrant: compaction callers stream ``rewrite(records())``
        # where the generator re-reads live payloads via ``scan()`` —
        # both under this lock.
        self._lock = threading.RLock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".compact"
        if os.path.exists(tmp):
            try:
                os.remove(tmp)  # died mid-rewrite: the main log rules
            except OSError:
                pass
        good_end = 0
        if os.path.exists(path):
            with open(path, "rb") as f:
                for _, payload, end in self._scan_file(f):
                    good_end = end
            if good_end < os.path.getsize(path):
                with open(path, "r+b") as f:
                    f.truncate(good_end)
        self._f = open(path, "ab")
        self.nbytes = good_end

    @classmethod
    def _scan_file(cls, f):
        """Yield (offset, payload, end_offset) for every intact record;
        stop at the first torn/corrupt one."""
        off = 0
        while True:
            hdr = f.read(cls._HDR.size)
            if len(hdr) < cls._HDR.size:
                return
            n, crc = cls._HDR.unpack(hdr)
            payload = f.read(n)
            if len(payload) < n or zlib.crc32(payload) != crc:
                return
            end = off + cls._HDR.size + n
            yield off, payload, end
            off = end

    def append(self, payload: bytes) -> None:
        rec = self._HDR.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            if self._f is None:
                raise ValueError("SegmentLog is closed")
            if faults.ACTIVE and faults.fires("wal.torn_write"):
                # Simulated crash inside write(): a prefix lands, the
                # writer dies. Recovery truncates at the torn record.
                self._f.write(rec[: max(1, len(rec) // 2)])
                self._f.flush()
                raise faults.FaultInjectedError("wal.torn_write")
            self._f.write(rec)
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
            self.nbytes += len(rec)

    def scan(self):
        """List of intact payloads, re-read from disk (recovery and the
        rare replay-of-spilled-frames path — never the hot path)."""
        with self._lock:
            if self._f is not None:
                self._f.flush()
        out = []
        try:
            with open(self.path, "rb") as f:
                for _, payload, _ in self._scan_file(f):
                    out.append(payload)
        except OSError:
            pass
        return out

    def rewrite(self, payloads) -> None:
        """Atomically replace the log's contents with ``payloads``
        (compaction). Crash-safe: temp + fsync + rename + dir fsync."""
        with self._lock:
            tmp = self.path + ".compact"
            nbytes = 0
            with open(tmp, "wb") as f:
                for p in payloads:
                    rec = self._HDR.pack(len(p), zlib.crc32(p)) + p
                    f.write(rec)
                    nbytes += len(rec)
                f.flush()
                os.fsync(f.fileno())
            if self._f is not None:
                self._f.close()
            os.replace(tmp, self.path)
            _fsync_dir(self.path)
            self._f = open(self.path, "ab")
            self.nbytes = nbytes

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class SqliteDatastore(Datastore):
    """Durable default backend on sqlite in WAL mode — the pebble-class
    engine (ref: src/vizier/utils/datastore/pebbledb/ is the reference
    default). Crash safety comes from sqlite's own journal; every write is
    a committed transaction."""

    def __init__(self, path: str):
        super().__init__()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=FULL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v BLOB NOT NULL)"
        )
        self._db.commit()
        # Warm the in-memory mirror so reads never touch the DB and the
        # base-class interface (get/get_prefix under one lock) holds.
        for k, v in self._db.execute("SELECT k, v FROM kv"):
            self._data[k] = bytes(v)

    def _on_write(self, key: str, value: Optional[bytes]) -> None:
        if self._db is None:
            return
        if value is None:
            self._db.execute("DELETE FROM kv WHERE k = ?", (key,))
        else:
            self._db.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) "
                "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (key, bytes(value)),
            )
        self._db.commit()

    def close(self) -> None:
        with self._lock:
            if self._db is not None:
                self._db.close()
                self._db = None
