"""Relation = ordered column schema of a table / row batch.

Ref: src/table_store/schema/relation.h:41 (Relation), row descriptors in
src/table_store/schema/row_descriptor.h. Ours carries semantic types inline
(the reference splits them across Relation + planner annotations).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Iterator

from pixie_tpu.types.dtypes import DataType, PatternType, SemanticType


@dataclasses.dataclass(frozen=True)
class ColumnSchema:
    name: str
    data_type: DataType
    semantic_type: SemanticType = SemanticType.ST_NONE
    pattern_type: PatternType = PatternType.UNSPECIFIED
    desc: str = ""

    def with_name(self, name: str) -> "ColumnSchema":
        return dataclasses.replace(self, name=name)


class Relation:
    """An ordered, named, typed column list with O(1) name lookup."""

    def __init__(self, columns: Iterable[ColumnSchema] = ()):  # noqa: D401
        self._columns: list[ColumnSchema] = list(columns)
        self._index: dict[str, int] = {c.name: i for i, c in enumerate(self._columns)}
        if len(self._index) != len(self._columns):
            names = [c.name for c in self._columns]
            dupes = {n for n in names if names.count(n) > 1}
            raise ValueError(f"duplicate column names in relation: {sorted(dupes)}")

    @classmethod
    def of(cls, *cols: tuple) -> "Relation":
        """Relation.of(("time_", DataType.TIME64NS, SemanticType.ST_TIME_NS), ...)."""
        schemas = []
        for c in cols:
            if isinstance(c, ColumnSchema):
                schemas.append(c)
            else:
                schemas.append(ColumnSchema(*c))
        return cls(schemas)

    # -- queries ----------------------------------------------------------
    def num_columns(self) -> int:
        return len(self._columns)

    def has_column(self, name: str) -> bool:
        return name in self._index

    def col_idx(self, name: str) -> int:
        try:
            return self._index[name]
        except KeyError:
            raise KeyError(
                f"column {name!r} not in relation {self.col_names()}"
            ) from None

    def col(self, name_or_idx) -> ColumnSchema:
        if isinstance(name_or_idx, str):
            return self._columns[self.col_idx(name_or_idx)]
        return self._columns[name_or_idx]

    def col_names(self) -> list[str]:
        return [c.name for c in self._columns]

    def col_types(self) -> list[DataType]:
        return [c.data_type for c in self._columns]

    # -- construction -----------------------------------------------------
    def add_column(self, schema: ColumnSchema) -> "Relation":
        return Relation(self._columns + [schema])

    def select(self, names: Iterable[str]) -> "Relation":
        return Relation([self.col(n) for n in names])

    def rename(self, mapping: dict[str, str]) -> "Relation":
        return Relation(
            [c.with_name(mapping.get(c.name, c.name)) for c in self._columns]
        )

    # -- dunder -----------------------------------------------------------
    def __iter__(self) -> Iterator[ColumnSchema]:
        return iter(self._columns)

    def __len__(self) -> int:
        return len(self._columns)

    def __eq__(self, other) -> bool:
        if not isinstance(other, Relation):
            return NotImplemented
        return [
            (c.name, c.data_type) for c in self._columns
        ] == [(c.name, c.data_type) for c in other._columns]

    def __hash__(self):
        return hash(tuple((c.name, c.data_type) for c in self._columns))

    def __repr__(self) -> str:
        cols = ", ".join(f"{c.name}:{c.data_type.name}" for c in self._columns)
        return f"Relation[{cols}]"

    def to_dict(self) -> list[dict]:
        return [
            {
                "name": c.name,
                "data_type": int(c.data_type),
                "semantic_type": int(c.semantic_type),
            }
            for c in self._columns
        ]

    @classmethod
    def from_dict(cls, cols: list[dict]) -> "Relation":
        return cls(
            [
                ColumnSchema(
                    c["name"],
                    DataType(c["data_type"]),
                    SemanticType(c.get("semantic_type", SemanticType.ST_NONE)),
                )
                for c in cols
            ]
        )
