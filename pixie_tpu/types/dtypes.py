"""Data / semantic / pattern type enums and their host/device dtypes.

Ref: src/shared/types/typespb/types.proto (enum values kept identical so plan
dumps remain comparable), src/shared/types/types.h:1 (value widths).
"""

from __future__ import annotations

import enum

import numpy as np


class DataType(enum.IntEnum):
    """Physical column types (ref: types.proto:26)."""

    DATA_TYPE_UNKNOWN = 0
    BOOLEAN = 1
    INT64 = 2
    UINT128 = 3
    FLOAT64 = 4
    STRING = 5
    TIME64NS = 6


class PatternType(enum.IntEnum):
    """Value-pattern classification used by the compiler/UI (ref: types.proto:47)."""

    UNSPECIFIED = 0
    GENERAL = 100
    STRUCTURED = 200
    GENERAL_ENUM = 101


class SemanticType(enum.IntEnum):
    """Semantic annotations driving UDF inference + UI rendering (ref: types.proto:63)."""

    ST_UNSPECIFIED = 0
    ST_NONE = 1
    ST_TIME_NS = 2
    ST_AGENT_UID = 100
    ST_ASID = 101
    ST_UPID = 200
    ST_SERVICE_NAME = 300
    ST_POD_NAME = 400
    ST_POD_PHASE = 401
    ST_POD_STATUS = 402
    ST_NODE_NAME = 500
    ST_CONTAINER_NAME = 600
    ST_CONTAINER_STATE = 601
    ST_CONTAINER_STATUS = 602
    ST_NAMESPACE_NAME = 700
    ST_BYTES = 800
    ST_PERCENT = 900
    ST_DURATION_NS = 901
    ST_THROUGHPUT_PER_NS = 902
    ST_THROUGHPUT_BYTES_PER_NS = 903
    ST_QUANTILES = 1000
    ST_DURATION_NS_QUANTILES = 1001
    ST_IP_ADDRESS = 1100
    ST_PORT = 1200
    ST_HTTP_REQ_METHOD = 1300
    ST_HTTP_RESP_STATUS = 1400
    ST_HTTP_RESP_MESSAGE = 1500
    ST_SCRIPT_REFERENCE = 1600


# Host (numpy) representation per physical type. UINT128 is a structured pair
# of uint64 halves (ref: types.h UInt128Value {high, low}); STRING is a numpy
# object array pre-encoding, int32 codes post-encoding.
_HOST_DTYPES = {
    DataType.BOOLEAN: np.dtype(np.bool_),
    DataType.INT64: np.dtype(np.int64),
    DataType.UINT128: np.dtype([("high", np.uint64), ("low", np.uint64)]),
    DataType.FLOAT64: np.dtype(np.float64),
    DataType.STRING: np.dtype(object),
    DataType.TIME64NS: np.dtype(np.int64),
}

# Device (jnp-stageable) representation. STRING stages as its dictionary codes;
# UINT128 stages as two int64 lanes. BOOLEAN stages as bool_.
_DEVICE_DTYPES = {
    DataType.BOOLEAN: np.dtype(np.bool_),
    DataType.INT64: np.dtype(np.int64),
    DataType.FLOAT64: np.dtype(np.float64),
    DataType.TIME64NS: np.dtype(np.int64),
    DataType.STRING: np.dtype(np.int32),  # dictionary codes
    DataType.UINT128: np.dtype(np.int64),  # staged as [..., 2] hi/lo lanes
}

_NULL_VALUES = {
    DataType.BOOLEAN: False,
    DataType.INT64: 0,
    DataType.FLOAT64: float("nan"),
    DataType.TIME64NS: 0,
    DataType.STRING: "",
}


def host_dtype(dt: DataType) -> np.dtype:
    return _HOST_DTYPES[dt]


def device_dtype(dt: DataType) -> np.dtype:
    return _DEVICE_DTYPES[dt]


def is_device_stageable(dt: DataType) -> bool:
    """Whether a column of this type ships to HBM directly (STRING ships codes)."""
    return dt in _DEVICE_DTYPES


def null_value(dt: DataType):
    return _NULL_VALUES[dt]


def from_numpy_dtype(dtype: np.dtype) -> DataType:
    """Best-effort mapping for ingesting raw numpy columns."""
    if dtype == np.bool_:
        return DataType.BOOLEAN
    if np.issubdtype(dtype, np.integer):
        return DataType.INT64
    if np.issubdtype(dtype, np.floating):
        return DataType.FLOAT64
    if dtype == object or dtype.kind in ("U", "S"):
        return DataType.STRING
    raise TypeError(f"no DataType mapping for numpy dtype {dtype}")
