"""Shared type system.

Reference parity: src/shared/types/typespb/types.proto:26,47,63 (DataType /
PatternType / SemanticType enums), src/shared/types/types.h (value types),
src/shared/types/column_wrapper.h (batch column abstraction — ours lives in
pixie_tpu.table.column). Re-designed for TPU: every DataType knows its host
(numpy) and device (jnp) representation; STRING columns are dictionary-encoded
on host and only their int32 codes are device-stageable.
"""

from pixie_tpu.types.dtypes import (  # noqa: F401
    DataType,
    PatternType,
    SemanticType,
    device_dtype,
    host_dtype,
    is_device_stageable,
    null_value,
)
from pixie_tpu.types.relation import ColumnSchema, Relation  # noqa: F401
