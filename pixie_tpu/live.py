"""`px live`: interactive terminal view of a running script.

Ref: src/pixie_cli/pkg/live/ (the reference's tview-based live TUI:
re-executes the script on an interval, renders its vis tables with
sortable columns, scrolling, and table cycling) + pkg/components/
(sortable table widget). Re-implemented on stdlib curses:

  keys: q quit · TAB next table · arrows/PgUp/PgDn scroll ·
        </> move sort column · s toggle sort direction · p pause

The rendering core (LiveModel) is decoupled from curses so tests drive
it headlessly: feed results, sort, scroll, snapshot visible lines.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    s = str(v)
    return s.replace("\n", "\\n")


@dataclasses.dataclass
class _TableView:
    name: str
    columns: list
    rows: list  # list of row tuples
    sort_col: int = 0
    sort_desc: bool = True
    scroll: int = 0

    def sorted_rows(self) -> list:
        if not self.rows or not (0 <= self.sort_col < len(self.columns)):
            return self.rows

        def key(row):
            v = row[self.sort_col]
            # Mixed types sort by (type class, value) to stay total.
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                return (0, v, "")
            return (1, 0, str(v))

        return sorted(self.rows, key=key, reverse=self.sort_desc)


class LiveModel:
    """State of the live view: tables, selection, sort, scroll."""

    def __init__(self):
        self.tables: list[_TableView] = []
        self.selected = 0
        self.paused = False
        self.last_refresh_s = 0.0
        self.refresh_count = 0

    # -- data ---------------------------------------------------------------
    def update(self, result) -> None:
        """Fold a new execution result in, preserving view state per
        table name (the reference keeps sort/scroll across refreshes)."""
        if self.paused:
            return
        old = {t.name: t for t in self.tables}
        from pixie_tpu.table.row_batch import RowBatch

        tables = []
        for name in sorted(result.tables):
            batches = [b for b in result.tables[name] if b.num_rows]
            if batches:
                data = RowBatch.concat(batches).to_pydict()
                cols = list(data.keys())
                n = len(next(iter(data.values()))) if data else 0
                rows = [
                    tuple(data[c][i] for c in cols) for i in range(n)
                ]
            else:
                cols, rows = [], []
            tv = _TableView(name=name, columns=cols, rows=rows)
            prev = old.get(name)
            if prev is not None and prev.columns == cols:
                tv.sort_col = prev.sort_col
                tv.sort_desc = prev.sort_desc
                tv.scroll = prev.scroll
            tables.append(tv)
        self.tables = tables
        self.selected = min(self.selected, max(len(tables) - 1, 0))
        self.refresh_count += 1

    @property
    def current(self) -> Optional[_TableView]:
        return self.tables[self.selected] if self.tables else None

    # -- key handling (the reference's live-view bindings) ------------------
    def handle_key(self, key: str) -> bool:
        """Returns False when the view should exit."""
        t = self.current
        if key in ("q", "Q"):
            return False
        if key == "\t" and self.tables:
            self.selected = (self.selected + 1) % len(self.tables)
        elif key == "p":
            self.paused = not self.paused
        elif t is None:
            return True
        elif key == "KEY_DOWN":
            t.scroll += 1
        elif key == "KEY_UP":
            t.scroll = max(t.scroll - 1, 0)
        elif key == "KEY_NPAGE":
            t.scroll += 20
        elif key == "KEY_PPAGE":
            t.scroll = max(t.scroll - 20, 0)
        elif key == "<":
            t.sort_col = max(t.sort_col - 1, 0)
        elif key == ">":
            t.sort_col = min(t.sort_col + 1, len(t.columns) - 1)
        elif key == "s":
            t.sort_desc = not t.sort_desc
        return True

    # -- rendering (curses-independent) -------------------------------------
    def render_lines(self, width: int = 120, height: int = 30) -> list[str]:
        """Visible lines for the current table; the curses frontend blits
        these verbatim, tests assert on them."""
        t = self.current
        lines = []
        tabs = " ".join(
            (f"[{tv.name}]" if i == self.selected else f" {tv.name} ")
            for i, tv in enumerate(self.tables)
        )
        state = "PAUSED" if self.paused else "LIVE"
        lines.append(f"{state} #{self.refresh_count} {tabs}"[:width])
        if t is None:
            lines.append("(no tables)")
            return lines
        rows = t.sorted_rows()
        t.scroll = max(min(t.scroll, max(len(rows) - 1, 0)), 0)
        ncols = max(len(t.columns), 1)
        colw = max(min(24, (width - ncols) // ncols), 6)

        def cells(vals):
            return "|".join(_fmt(v)[:colw].ljust(colw) for v in vals)

        hdr = []
        for i, c in enumerate(t.columns):
            mark = (" ▼" if t.sort_desc else " ▲") if i == t.sort_col else ""
            hdr.append((c + mark)[:colw].ljust(colw))
        lines.append("|".join(hdr)[:width])
        body = rows[t.scroll : t.scroll + max(height - 3, 1)]
        for row in body:
            lines.append(cells(row)[:width])
        lines.append(
            f"rows {t.scroll + 1}-{t.scroll + len(body)}/{len(rows)} "
            f"sort={t.columns[t.sort_col] if t.columns else '-'} "
            f"{'desc' if t.sort_desc else 'asc'}"[:width]
        )
        return lines


def run_live(
    execute_fn,
    interval_s: float = 2.0,
    max_refreshes: Optional[int] = None,
) -> None:
    """Curses frontend: execute_fn() -> result, re-run every interval."""
    import curses

    model = LiveModel()

    def loop(stdscr):
        curses.curs_set(0)
        stdscr.nodelay(True)
        stdscr.timeout(100)
        last = 0.0
        while True:
            now = time.monotonic()
            if not model.paused and (
                now - last >= interval_s or model.refresh_count == 0
            ):
                t0 = time.perf_counter()
                model.update(execute_fn())
                model.last_refresh_s = time.perf_counter() - t0
                last = now
                if (
                    max_refreshes is not None
                    and model.refresh_count >= max_refreshes
                ):
                    return
            h, w = stdscr.getmaxyx()
            stdscr.erase()
            for y, line in enumerate(model.render_lines(w - 1, h)):
                if y >= h:
                    break
                try:
                    stdscr.addstr(y, 0, line)
                except curses.error:
                    pass
            stdscr.refresh()
            try:
                ch = stdscr.getkey()
            except curses.error:
                continue
            if not model.handle_key(ch):
                return

    curses.wrapper(loop)
