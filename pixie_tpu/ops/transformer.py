"""Transformer sentence-embedding executor + model pool, TPU-native.

Ref: src/carnot/exec/ml/transformer_executor.h:45-60 (a tflite
transformer turning a JSON token-id array, max 64 tokens, into an
embedding vector serialized as JSON floats) and model_pool.h:36 (a
borrow-pool sharing executors across query threads). The reference loads
a trained flatbuffer from /embedding.proto at deploy time; that asset
does not ship in-tree, so this executor runs a REAL transformer encoder
in JAX (jit-compiled: MXU matmuls for QKV/attention/MLP) with
deterministic seeded weights — the interface, shapes, and pooling match
the reference contract, and a trained checkpoint can be dropped into
``load_params`` without touching callers.

SentencePiece is likewise asset-gated in the reference
(/sentencepiece.proto); ``tokenize`` stands in with a stable
hash-bucketed subword scheme so the string -> token ids -> embedding
pipeline runs end to end.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Optional

import numpy as np

MAX_LENGTH = 64  # ref: transformer_executor.h max_length_
VOCAB = 32768
D_MODEL = 64
N_HEADS = 4
N_LAYERS = 2


def _init_params(seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def w(*shape):
        return rng.normal(0, 1.0 / math.sqrt(shape[-1]), shape).astype(
            np.float32
        )

    params = {
        "embed": w(VOCAB, D_MODEL),
        "pos": w(MAX_LENGTH, D_MODEL),
        "layers": [],
    }
    for _ in range(N_LAYERS):
        params["layers"].append(
            {
                "wq": w(D_MODEL, D_MODEL),
                "wk": w(D_MODEL, D_MODEL),
                "wv": w(D_MODEL, D_MODEL),
                "wo": w(D_MODEL, D_MODEL),
                "w1": w(D_MODEL, 4 * D_MODEL),
                "w2": w(4 * D_MODEL, D_MODEL),
                "ln1": np.ones(D_MODEL, np.float32),
                "ln2": np.ones(D_MODEL, np.float32),
            }
        )
    return params


class TransformerExecutor:
    """Execute(json_token_ids) -> json embedding floats (ref interface)."""

    TYPE = "transformer"

    def __init__(self, params: Optional[dict] = None, seed: int = 0):
        import jax
        import jax.numpy as jnp

        self.params = params if params is not None else _init_params(seed)

        def forward(params, ids, mask):
            x = params["embed"][ids] + params["pos"]
            neg = jnp.float32(-1e9)
            attn_bias = jnp.where(mask[None, :], 0.0, neg)  # [1, L]
            for lp in params["layers"]:
                h = x * lp["ln1"] / (
                    jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-6
                ) * math.sqrt(D_MODEL)
                q = (h @ lp["wq"]).reshape(MAX_LENGTH, N_HEADS, -1)
                k = (h @ lp["wk"]).reshape(MAX_LENGTH, N_HEADS, -1)
                v = (h @ lp["wv"]).reshape(MAX_LENGTH, N_HEADS, -1)
                scores = jnp.einsum("qhd,khd->hqk", q, k) / math.sqrt(
                    D_MODEL // N_HEADS
                )
                att = jax.nn.softmax(scores + attn_bias[None, :, :], axis=-1)
                ctxv = jnp.einsum("hqk,khd->qhd", att, v).reshape(
                    MAX_LENGTH, D_MODEL
                )
                x = x + ctxv @ lp["wo"]
                h2 = x * lp["ln2"] / (
                    jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-6
                ) * math.sqrt(D_MODEL)
                x = x + jax.nn.relu(h2 @ lp["w1"]) @ lp["w2"]
            # Mean-pool over real tokens -> the sentence embedding.
            m = mask.astype(jnp.float32)[:, None]
            pooled = (x * m).sum(axis=0) / jnp.maximum(m.sum(), 1.0)
            return pooled / (jnp.linalg.norm(pooled) + 1e-6)

        self._jitted = jax.jit(forward)
        self._jnp = jnp

    def load_params(self, params: dict) -> None:
        """Drop in trained weights (same pytree structure)."""
        self.params = params

    def execute(self, doc: str) -> str:
        """JSON token ids -> JSON embedding (ref: Execute(doc, out))."""
        try:
            ids = json.loads(doc)
        except (ValueError, TypeError):
            return ""
        if not isinstance(ids, list) or not ids or not all(
            isinstance(i, int) for i in ids
        ):
            return ""
        ids = ids[:MAX_LENGTH]
        # Ref parity: +1 shift for the pad token at id 0.
        arr = np.zeros(MAX_LENGTH, np.int32)
        arr[: len(ids)] = [(i + 1) % VOCAB for i in ids]
        mask = np.zeros(MAX_LENGTH, bool)
        mask[: len(ids)] = True
        emb = np.asarray(
            self._jitted(self.params, self._jnp.asarray(arr), self._jnp.asarray(mask))
        )
        return json.dumps([round(float(v), 6) for v in emb])


def tokenize(text: str, vocab: int = VOCAB) -> str:
    """string -> JSON token ids (ref: SentencePieceUDF's contract).
    Stable hash-bucketed subwords: whitespace/punct split, 4-char
    shingles, FNV-1a bucket — deterministic across processes."""
    from pixie_tpu.table.column import _fnv1a64

    out: list[int] = []
    for word in text.split():
        for i in range(0, max(len(word), 1), 4):
            piece = word[i : i + 4]
            out.append(int(_fnv1a64(piece) % np.uint64(vocab - 2)) + 1)
            if len(out) >= MAX_LENGTH:
                return json.dumps(out)
    return json.dumps(out)


class ModelPool:
    """Borrow-pool of executors keyed by model type (ref: model_pool.h).
    get() hands out an existing idle executor or builds one; the context
    manager returns it, so concurrent queries share warm (jit-compiled)
    models instead of recompiling per call."""

    def __init__(self):
        self._lock = threading.Lock()
        self._idle: dict[str, list] = {}
        self._built: dict[str, int] = {}

    class _Borrow:
        def __init__(self, pool, key, executor):
            self._pool, self._key, self.executor = pool, key, executor

        def __enter__(self):
            return self.executor

        def __exit__(self, *exc):
            with self._pool._lock:
                self._pool._idle.setdefault(self._key, []).append(
                    self.executor
                )
            return False

    def get(self, executor_cls=TransformerExecutor, **kwargs):
        key = executor_cls.TYPE
        with self._lock:
            idle = self._idle.get(key)
            if idle:
                return self._Borrow(self, key, idle.pop())
            self._built[key] = self._built.get(key, 0) + 1
        return self._Borrow(self, key, executor_cls(**kwargs))


_default_pool: Optional[ModelPool] = None
_default_pool_lock = threading.Lock()


def default_pool() -> ModelPool:
    global _default_pool
    with _default_pool_lock:
        if _default_pool is None:
            _default_pool = ModelPool()
        return _default_pool
