"""ML runtime ops: static-shape reservoir sampling + k-means.

Ref: src/carnot/exec/ml/{kmeans,coreset}.{h,cc} and
src/carnot/funcs/builtins/ml_ops.h:88 (KMeansUDA: streaming coreset →
Lloyd's at finalize), :145 (ReservoirSampleUDA). TPU re-design: the
pointer-based coreset tree becomes a fixed-size priority reservoir — each
item gets a deterministic hash priority, a reservoir is the top-K
priorities per group, and merge is concat + top-K again, which is
associative and static-shape (so it vectorizes over groups and
all-gathers across shards). Uniform sampling by max-priority is the
classic A-Res construction. K-means itself is a vmapped Lloyd iteration
over [G, S, d] sample tensors at finalize time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu.ops import hashing

DEFAULT_RESERVOIR = 64


# -- priority reservoir (device: jnp; also exact under np on host) ----------
def reservoir_init(num_groups: int, k: int = DEFAULT_RESERVOIR, dtype=jnp.float64):
    # dtype follows the argument column: int64 samples must round-trip
    # exactly (timestamps/ids exceed f64's 2^53 integer range).
    return {
        "values": jnp.zeros((num_groups, k), dtype),
        "priority": jnp.full((num_groups, k), -jnp.inf, jnp.float64),
        "count": jnp.zeros((num_groups,), jnp.int64),
    }


def _priorities(values, count_salt):
    """Deterministic uniform (0,1) priority per row: hash of the value bits
    mixed with a per-call salt (row position within the stream), so repeated
    values get distinct priorities."""
    n = values.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64) + count_salt
    h = hashing.combine(hashing.hash64(values), hashing.hash64(idx))
    return (h >> np.uint64(11)).astype(jnp.float64) / float(1 << 53)


def reservoir_update(state, gids, values, mask=None):
    """Fold a batch into per-group top-K-by-priority reservoirs."""
    num_groups, k = state["values"].shape
    v = values.astype(state["values"].dtype)
    n = v.shape[0]
    if mask is None:
        mask = jnp.ones((n,), jnp.bool_)
    pri = jnp.where(mask, _priorities(v, state["count"].sum()), -jnp.inf)
    g = jnp.where(mask, gids.astype(jnp.int32), num_groups)
    # Rank rows within each group by priority (desc): sort by (g, -pri),
    # rank = position - group start; rows with rank >= k can never enter.
    g_s, negp_s, v_s = jax.lax.sort((g, -pri, v), num_keys=2)
    counts = jnp.bincount(g_s, length=num_groups + 1).astype(jnp.int32)
    starts = jnp.cumsum(counts) - counts
    rank = jnp.arange(n, dtype=jnp.int32) - starts[g_s]
    keep = (g_s < num_groups) & (rank < k)
    slot = jnp.where(keep, g_s * k + rank, num_groups * k)
    # Scatter the block's per-group top-k into a [G, k] candidate buffer.
    cand_v = jnp.zeros((num_groups * k + 1,), v.dtype).at[slot].set(v_s)
    cand_p = (
        jnp.full((num_groups * k + 1,), -jnp.inf, jnp.float64)
        .at[slot]
        .set(-negp_s)
    )
    cand = {
        "values": cand_v[:-1].reshape(num_groups, k),
        "priority": cand_p[:-1].reshape(num_groups, k),
        # counts already tallies the same masked multiset (sorted copy).
        "count": counts[:-1].astype(jnp.int64),
    }
    return reservoir_merge(state, cand)


def topk_by_priority(vals_a, vals_b, pri_a, pri_b, k):
    """Per-group top-k selection over concatenated candidates — the shared
    reservoir-merge core. vals may carry trailing dims ([G, S] or
    [G, S, d]); priorities are [G, S]."""
    vals = jnp.concatenate([vals_a, vals_b], axis=1)
    pris = jnp.concatenate([pri_a, pri_b], axis=1)
    order = jnp.argsort(-pris, axis=1)[:, :k]
    vorder = order.reshape(order.shape + (1,) * (vals.ndim - 2))
    return (
        jnp.take_along_axis(vals, vorder, axis=1),
        jnp.take_along_axis(pris, order, axis=1),
    )


def reservoir_merge(a, b):
    """Concat candidates and keep the top-K priorities per group."""
    k = a["values"].shape[1]
    vals, pris = topk_by_priority(
        a["values"], b["values"], a["priority"], b["priority"], k
    )
    return {
        "values": vals,
        "priority": pris,
        "count": a["count"] + b["count"],
    }


def reservoir_finalize(state) -> np.ndarray:
    """[G] JSON strings: {"count": N, "sample": [..]} (live slots only)."""
    vals = np.asarray(state["values"])
    pris = np.asarray(state["priority"])
    counts = np.asarray(state["count"])
    import json

    is_int = np.issubdtype(vals.dtype, np.integer)
    out = np.empty(vals.shape[0], dtype=object)
    for gid in range(vals.shape[0]):
        live = vals[gid][np.isfinite(pris[gid])]
        if not is_int:
            live = live[np.isfinite(live)]  # NaN/inf render invalid JSON
        sample = [int(x) if is_int else float(x) for x in live]
        out[gid] = json.dumps(
            {"count": int(counts[gid]), "sample": sample}
        )
    return out


# -- k-means (vmapped Lloyd's over per-group samples) -----------------------
def kmeans_fit(points, weights, k: int, iters: int = 10):
    """points [S, d], weights [S] (0 = empty slot) -> centers [k, d].
    Greedy farthest-point init then Lloyd iterations; empty clusters stay
    on their seed."""
    S, d = points.shape
    live = weights > 0

    # Farthest-point seeding (deterministic): start from the first live
    # point, repeatedly take the point farthest from chosen centers.
    first = jnp.argmax(live)
    centers0 = jnp.zeros((k, d), points.dtype).at[0].set(points[first])

    def seed_step(i, centers):
        d2 = jnp.sum(
            (points[:, None, :] - centers[None, :, :]) ** 2, axis=-1
        )  # [S, k]
        masked = jnp.where(
            jnp.arange(k)[None, :] < i, d2, jnp.inf
        )
        mind = jnp.min(masked, axis=1)
        mind = jnp.where(live, mind, -jnp.inf)
        nxt = jnp.argmax(mind)
        return centers.at[i].set(points[nxt])

    centers = jax.lax.fori_loop(1, min(k, S), seed_step, centers0)

    def lloyd(_, centers):
        d2 = jnp.sum(
            (points[:, None, :] - centers[None, :, :]) ** 2, axis=-1
        )
        assign = jnp.argmin(d2, axis=1)  # [S]
        onehot = jax.nn.one_hot(assign, k, dtype=points.dtype) * weights[:, None]
        sums = onehot.T @ points  # [k, d]
        wsum = onehot.sum(axis=0)  # [k]
        return jnp.where(
            (wsum > 0)[:, None], sums / jnp.maximum(wsum, 1e-9)[:, None], centers
        )

    return jax.lax.fori_loop(0, iters, lloyd, centers)


def kmeans_assign(point, centers):
    """Nearest-center index for one point [d] against centers [k, d]."""
    return int(np.argmin(np.sum((centers - point[None, :]) ** 2, axis=1)))
