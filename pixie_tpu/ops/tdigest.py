"""Static-shape merging t-digest, vectorized over groups.

Parity counterpart of the reference's QuantilesUDA
(src/carnot/funcs/builtins/math_sketches.h:34-82, which wraps a
pointer-based tdigest). Re-designed for XLA: a digest is a fixed
[num_groups, capacity] pair of (means, weights) tensors; batch updates and
merges are sort + segment-reduce recompressions in k-space (the "merging
t-digest" construction), so everything is static-shape and jit/vmap/shard_map
compatible. Cross-shard merge = concat + recompress (not elementwise), so the
distributed layer all-gathers digest states instead of psumming them.

float32 note: means/weights are f32 for TPU sort/reduce speed; at 1e9 rows
the ~1e-7 relative weight error is far below the digest's own approximation
error.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from pixie_tpu.ops import segment

DEFAULT_CAPACITY = 256


def init(num_groups: int, capacity: int = DEFAULT_CAPACITY):
    return {
        "means": jnp.zeros((num_groups, capacity), jnp.float32),
        "weights": jnp.zeros((num_groups, capacity), jnp.float32),
    }


def _k_scale(q):
    """The t-digest k1 scale function, normalized to [0, 1]."""
    q = jnp.clip(q, 1e-7, 1 - 1e-7)
    return jnp.arcsin(2.0 * q - 1.0) / math.pi + 0.5


def _cluster_ids(q, capacity):
    return jnp.clip(
        jnp.floor(_k_scale(q) * capacity).astype(jnp.int32), 0, capacity - 1
    )


# Packed-key sort applies while the group id (plus its sentinel) fits in
# this many key bits; the same number of value-mantissa LOW bits is
# dropped (≤2^-15 relative perturbation at 8 bits — far below the
# digest's own ~1% error). One u32 single-key sort costs ~2.5ns/row on a
# v5e vs ~2x for the (group, value) 2-key sort it replaces (r5 measured).
_PACK_MAX_GROUP_BITS = 8


def _packed_sort(gids, v32, mask, num_groups: int, bits_g: int):
    """Sort (group, value) as ONE u32 key: order-preserving float bits in
    the low lanes, group (sentinel = num_groups for masked rows) in the
    high lanes. Returns (sorted gids, values reconstructed from the key —
    low ``bits_g`` mantissa bits zeroed)."""
    u = jax.lax.bitcast_convert_type(v32, jnp.uint32)
    # Standard order-preserving map: flip all bits of negatives, set the
    # sign bit of non-negatives.
    mapped = jnp.where(
        (u >> jnp.uint32(31)) > 0, ~u, u | jnp.uint32(0x80000000)
    )
    g = jnp.where(mask, gids.astype(jnp.uint32), jnp.uint32(num_groups))
    key = (g << jnp.uint32(32 - bits_g)) | (mapped >> jnp.uint32(bits_g))
    ks = jnp.sort(key)
    g_s = (ks >> jnp.uint32(32 - bits_g)).astype(jnp.int32)
    mp = ks << jnp.uint32(bits_g)
    uu = jnp.where(
        (mp >> jnp.uint32(31)) > 0, mp & jnp.uint32(0x7FFFFFFF), ~mp
    )
    return g_s, jax.lax.bitcast_convert_type(uu, jnp.float32)


def update(state, gids, values, mask=None):
    """Fold a batch of (group, value) rows into the digests."""
    num_groups, capacity = state["means"].shape
    n = values.shape[0]
    v = values.astype(jnp.float32)
    if mask is None:
        mask = jnp.ones((n,), jnp.bool_)
    bits_g = max((num_groups + 1).bit_length(), 1)
    if bits_g <= _PACK_MAX_GROUP_BITS:
        g_s, v_s = _packed_sort(gids, v, mask, num_groups, bits_g)
    else:
        # Masked rows sort to a sentinel group: never touch real segments.
        g = jnp.where(mask, gids.astype(jnp.int32), num_groups)
        g_s, v_s = jax.lax.sort((g, v), num_keys=2)
    w_s = (g_s < num_groups).astype(jnp.float32)
    # Group boundaries by binary search over the SORTED gids — a handful
    # of log(n) probes instead of a segment reduction (r5).
    qs = jnp.arange(num_groups + 1, dtype=g_s.dtype)
    starts_i = jnp.searchsorted(g_s, qs, side="left").astype(jnp.int32)
    ends_i = jnp.searchsorted(g_s, qs, side="right").astype(jnp.int32)
    counts_i = ends_i - starts_i  # [G+1]; exact int32 ranks
    rank = (jnp.arange(n, dtype=jnp.int32) - starts_i[g_s]).astype(
        jnp.float32
    )
    counts = counts_i.astype(jnp.float32)
    qmid = (rank + 0.5) / jnp.maximum(counts[g_s], 1.0)
    cl = _cluster_ids(qmid, capacity)
    flat = jnp.where(
        g_s < num_groups, g_s * capacity + cl, num_groups * capacity
    )
    nseg = num_groups * capacity + 1
    if segment.matmul_strategy(nseg):
        # Both reductions share ONE one-hot on the MXU (the one-hot
        # generation dominates; a second einsum row is nearly free).
        totals = segment.f32_rows_einsum([w_s, v_s * w_s], flat, nseg)
        w_new = totals[0][:-1].astype(jnp.float32).reshape(
            num_groups, capacity
        )
        m_sum = totals[1][:-1].astype(jnp.float32).reshape(
            num_groups, capacity
        )
    else:
        w_new = segment.seg_sum(w_s, flat, nseg)[:-1].reshape(
            num_groups, capacity
        )
        m_sum = segment.seg_sum(v_s * w_s, flat, nseg)[:-1].reshape(
            num_groups, capacity
        )
    batch = {
        "means": jnp.where(w_new > 0, m_sum / jnp.maximum(w_new, 1.0), 0.0),
        "weights": w_new,
    }
    return merge(state, batch)


def merge(a, b):
    """Merge two digest states: concat centroids, sort by mean, recompress."""
    num_groups, capacity = a["means"].shape
    means = jnp.concatenate([a["means"], b["means"]], axis=1)  # [G, 2C]
    weights = jnp.concatenate([a["weights"], b["weights"]], axis=1)
    # Sort centroids by mean within each group; empty (w=0) centroids go last.
    sort_key = jnp.where(weights > 0, means, jnp.inf)
    order = jnp.argsort(sort_key, axis=1)
    means = jnp.take_along_axis(means, order, axis=1)
    weights = jnp.take_along_axis(weights, order, axis=1)
    # Recompress in k-space using cumulative weight midpoints.
    total = weights.sum(axis=1, keepdims=True)
    cum = jnp.cumsum(weights, axis=1)
    qmid = (cum - 0.5 * weights) / jnp.maximum(total, 1.0)
    cl = _cluster_ids(qmid, capacity)  # [G, 2C]
    g_idx = jnp.broadcast_to(
        jnp.arange(num_groups, dtype=jnp.int32)[:, None], cl.shape
    )
    flat = (g_idx * capacity + cl).reshape(-1)
    w_flat = weights.reshape(-1)
    m_flat = (means * weights).reshape(-1)
    nseg = num_groups * capacity
    w_new = segment.seg_sum(w_flat, flat, nseg).reshape(num_groups, capacity)
    m_sum = segment.seg_sum(m_flat, flat, nseg).reshape(num_groups, capacity)
    return {
        "means": jnp.where(w_new > 0, m_sum / jnp.maximum(w_new, 1e-9), 0.0),
        "weights": w_new,
    }


def quantile_values(state, qs):
    """Per-group quantiles [num_groups, len(qs)] by centroid interpolation."""
    means, weights = state["means"], state["weights"]
    total = weights.sum(axis=1, keepdims=True)  # [G,1]
    cum = jnp.cumsum(weights, axis=1) - 0.5 * weights  # centroid midpoints
    qs_arr = jnp.asarray(qs, jnp.float32)
    target = qs_arr[None, :] * total  # [G, Q]
    # Index of first centroid whose midpoint >= target.
    reached = cum[:, :, None] >= target[:, None, :]  # [G, C, Q]
    # Only consider non-empty centroids.
    reached = reached & (weights > 0)[:, :, None]
    idx_hi = jnp.argmax(reached, axis=1)  # [G, Q]
    any_reached = reached.any(axis=1)
    last_valid = jnp.maximum((weights > 0).sum(axis=1) - 1, 0)  # [G]
    idx_hi = jnp.where(any_reached, idx_hi, last_valid[:, None])
    idx_lo = jnp.maximum(idx_hi - 1, 0)
    take = lambda arr, idx: jnp.take_along_axis(arr, idx, axis=1)
    m_lo, m_hi = take(means, idx_lo), take(means, idx_hi)
    c_lo, c_hi = take(cum, idx_lo), take(cum, idx_hi)
    frac = jnp.where(
        c_hi > c_lo, (target - c_lo) / jnp.maximum(c_hi - c_lo, 1e-9), 1.0
    )
    frac = jnp.clip(frac, 0.0, 1.0)
    out = m_lo + frac * (m_hi - m_lo)
    out = jnp.where(idx_hi == idx_lo, m_hi, out)
    return jnp.where(total > 0, out, 0.0).astype(jnp.float64)
