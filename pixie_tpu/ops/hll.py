"""HyperLogLog distinct-count sketch, vectorized over groups.

Net-new UDA (the reference ships no HLL — SURVEY.md §6): state is a dense
[num_groups, m] int32 register tensor (m = 2^precision), update is a
scatter-max of leading-zero counts, merge is elementwise max — so the
cross-device merge lowers to a single `lax.pmax` over ICI.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from pixie_tpu.ops import hashing, segment

DEFAULT_PRECISION = 11  # m=2048 registers -> ~2.3% standard error


def init(num_groups: int, precision: int = DEFAULT_PRECISION):
    return jnp.zeros((num_groups, 1 << precision), jnp.int32)


def update(state, gids, values, mask=None):
    num_groups, m = state.shape
    precision = int(m).bit_length() - 1  # derived: m == 2**precision
    h = hashing.hash64(values)
    reg = (h >> np.uint64(64 - precision)).astype(jnp.int32)
    rest = h << np.uint64(precision)
    # int32 ranks: registers are int32 and TPU s64 scatter-max is ~3x the
    # cost of s32.
    rho = jnp.minimum(hashing.clz64(rest) + 1, 64 - precision + 1).astype(
        jnp.int32
    )
    flat = segment.flat_segment_ids(gids, reg, m)
    if mask is not None:
        rho = jnp.where(mask, rho, 0)
    maxes = segment.seg_max(
        rho, flat, num_groups * m, mask=None
    )  # rho already masked to 0
    return jnp.maximum(state, maxes.reshape(num_groups, m))


def merge(a, b):
    return jnp.maximum(a, b)


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1 + 1.079 / m)


def estimate(state):
    """Per-group cardinality estimates [num_groups] float64 with the standard
    small-range (linear counting) correction."""
    g, m = state.shape
    regs = state.astype(jnp.float64)
    raw = _alpha(m) * m * m / jnp.sum(jnp.power(2.0, -regs), axis=1)
    zeros = jnp.sum(state == 0, axis=1).astype(jnp.float64)
    linear = m * jnp.log(jnp.maximum(m / jnp.maximum(zeros, 1e-9), 1.0))
    use_linear = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_linear, linear, raw)
