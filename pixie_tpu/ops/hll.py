"""HyperLogLog distinct-count sketch, vectorized over groups.

Net-new UDA (the reference ships no HLL — SURVEY.md §6): state is a dense
[num_groups, m] int32 register tensor (m = 2^precision), merge is
elementwise max — so the cross-device merge lowers to a single `lax.pmax`
over ICI.

Update strategy: hashing rides the native-u32 pipeline (TPU has no
64-bit multiplier; a u64 splitmix costs ~5x more per block). The
register update is max-reduction over a small packed domain
(rho < 2^5), which r8 expresses as the sort–COMPACT lane
(segment.sorted_segment_reduce_compact): pack (register, rho) into one
i32 key, sort so each register's winning rho sorts first, compact the
≤ nseg winners to the front with a second sort, and finish with an
O(nseg) scatter — the full-length ~7ns/row scalar scatter the r5
sort-DEDUP attempt still paid (and lost to, 12.6 vs 10.6 ns/row) is
gone from the lane entirely. Below segment.SORTED_MIN_ROWS (or past the
i32 packing boundary, or on CPU) the direct scatter-max remains the
lane of record; small-domain columns keep the r7 MXU cell lane
(cell_update).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu.ops import hashing, segment

DEFAULT_PRECISION = 11  # m=2048 registers -> ~2.3% standard error
_RHO_BITS = 5  # rho <= 32 - precision + 1 <= 29 for precision >= 4


def init(num_groups: int, precision: int = DEFAULT_PRECISION):
    if precision < 4:
        raise ValueError(f"HLL precision must be >= 4 (got {precision})")
    return jnp.zeros((num_groups, 1 << precision), jnp.int32)


def _reg_rho(values, precision: int):
    """(register index, rho) from a 32-bit hash stream."""
    h = hashing.hash32(values)
    reg = (h >> jnp.uint32(32 - precision)).astype(jnp.int32)
    rest = h << jnp.uint32(precision)
    rho = jnp.minimum(
        hashing.clz32(rest) + 1, jnp.int32(32 - precision + 1)
    ).astype(jnp.int32)
    return reg, rho


def update(state, gids, values, mask=None):
    num_groups, m = state.shape
    precision = int(m).bit_length() - 1  # derived: m == 2**precision
    reg, rho = _reg_rho(values, precision)
    flat = segment.flat_segment_ids(gids, reg, m)
    nseg = num_groups * m
    if segment.sorted_strategy(flat.shape[0], nseg) and (
        segment.compact_fits_i32(nseg, _RHO_BITS)
    ):
        # Sort–compact register update (r8): rho packs into the key so
        # each register's largest rho sorts first; the winners compact to
        # the front and the final scatter operand is O(nseg), not O(n).
        # The i32 packing boundary falls back to the scatter below — a
        # wrapped key would silently corrupt register ids.
        segment.lane_count("hll_sorted_compact")
        maxes = segment.sorted_segment_reduce_compact(
            flat, rho, _RHO_BITS, nseg, mask, mode="max"
        )
        return jnp.maximum(state, maxes.reshape(num_groups, m))
    segment.lane_count("hll_scatter")
    if mask is not None:
        rho = jnp.where(mask, rho, 0)
    # Direct scatter-max regardless of the generic minmax lane: this IS
    # the fallback for rows/boundaries the compact lane rejected.
    maxes = jax.ops.segment_max(rho, flat, num_segments=nseg)
    return jnp.maximum(state, maxes.reshape(num_groups, m))


def cell_update(state, hist, lut):
    """Fold a per-(group, value-code) histogram into the registers.

    ``hist``: [num_groups, C] int64 row counts per cell; ``lut``: [C] the
    int64 value each code stands for. Every row of a cell carries the
    same (register, rho) pair, so maxing rho over PRESENT cells
    (hist > 0 — cardinality ignores multiplicity) reproduces the row-wise
    scatter exactly while touching num_groups*C elements instead of n
    rows: approx_count_distinct on small-domain int columns rides the
    pipeline's MXU cell lane like count-min does.
    """
    num_groups, m = state.shape
    precision = int(m).bit_length() - 1
    reg, rho = _reg_rho(lut, precision)  # [C] each
    rho_gc = jnp.where(hist > 0, rho[None, :], 0).astype(jnp.int32)
    flat = (
        jnp.arange(num_groups, dtype=jnp.int32)[:, None] * m + reg[None, :]
    ).reshape(-1)
    maxes = segment.seg_max(rho_gc.reshape(-1), flat, num_groups * m)
    return jnp.maximum(state, maxes.reshape(num_groups, m))


def merge(a, b):
    return jnp.maximum(a, b)


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1 + 1.079 / m)


def estimate(state):
    """Per-group cardinality estimates [num_groups] float64 with the
    standard small-range (linear counting) and 32-bit large-range
    corrections. The large-range term compensates hash collisions as raw
    estimates approach the 2^32 hash space (registers derive from 32-bit
    hashes since r4; without it, estimates undercount past ~2^32/30)."""
    g, m = state.shape
    regs = state.astype(jnp.float64)
    raw = _alpha(m) * m * m / jnp.sum(jnp.power(2.0, -regs), axis=1)
    zeros = jnp.sum(state == 0, axis=1).astype(jnp.float64)
    linear = m * jnp.log(jnp.maximum(m / jnp.maximum(zeros, 1e-9), 1.0))
    use_linear = (raw <= 2.5 * m) & (zeros > 0)
    two32 = float(1 << 32)
    large = -two32 * jnp.log(
        jnp.maximum(1.0 - jnp.minimum(raw, two32 * 0.9999) / two32, 1e-12)
    )
    corrected = jnp.where(raw > two32 / 30.0, large, raw)
    return jnp.where(use_linear, linear, corrected)
