"""Masked segment reductions — the group-by aggregation primitive.

The reference aggregates row-at-a-time into an absl hash map of per-group UDA
objects (src/carnot/exec/agg_node.cc: HashRowBatch -> AggHashValue ->
UDA::Update). On TPU there are no dynamic hash maps inside a compiled
program; instead group keys are dense int32 segment ids (strings arrive
dictionary-encoded; other key types are densified host-side by
pixie_tpu.exec's GroupDictionary) and aggregation is an XLA segment
reduction over a static number of segments. Padding rows carry mask=False.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Strategy selection: XLA's scatter-add lowers to the TPU's scalar scatter
# unit (~160M rows/s measured on v5e — and int64 scatter is ~12x worse at
# ~13M rows/s, the dominant cost of integer group-by sums in round 3); a
# one-hot matvec/einsum rides the MXU at ~240M rows/s up to a few thousand
# segments, with cost scaling ~n*num_segments beyond. Non-sum reductions
# (max/min) have no einsum form; above SORTED_MIN_ROWS they ride the r8
# sort–COMPACT lane instead (two i32-class sorts + an O(num_segments)
# scatter; see sorted_segment_reduce_compact). CPU prefers scatter
# everywhere. Tests can pin a strategy via set_strategy() /
# set_sorted_strategy().
import threading

from pixie_tpu.utils import flags

# r22 cost model, resolved lazily: serving's package init transitively
# imports this module (controller -> vizier -> engine -> pipeline), so a
# top-level import here would deadlock the import graph. sorted_strategy
# runs at trace time only, so the one-time resolution cost is free.
_COST_MODEL = None


def _cost_model():
    global _COST_MODEL
    if _COST_MODEL is None:
        from pixie_tpu.serving import cost_model

        _COST_MODEL = cost_model
    return _COST_MODEL

_FORCE: Optional[str] = None
_TLS = threading.local()  # per-thread platform hint: agents run in threads
MATMUL_MAX_SEGMENTS = 8192


def set_strategy(s: Optional[str]) -> None:
    """Force 'matmul' or 'scatter' (None = auto by platform)."""
    global _FORCE
    assert s in (None, "matmul", "scatter")
    _FORCE = s


class platform_hint:
    """Context manager: pin the platform these kernels will execute on for
    the CURRENT THREAD. jax.default_backend() is a process-wide default
    that can differ from the mesh/device a program is traced for (e.g. CPU
    exec graph on a TPU-attached host); concurrent agent threads each carry
    their own hint."""

    def __init__(self, platform: Optional[str]):
        self.platform = platform

    def __enter__(self):
        self._old = getattr(_TLS, "hint", None)
        _TLS.hint = self.platform
        return self

    def __exit__(self, *exc):
        _TLS.hint = self._old
        return False


def _use_matmul(num_segments: int) -> bool:
    if _FORCE is not None:
        return _FORCE == "matmul"
    platform = getattr(_TLS, "hint", None) or jax.default_backend()
    return platform != "cpu" and num_segments <= MATMUL_MAX_SEGMENTS


def matmul_strategy(num_segments: int) -> bool:
    """Public strategy probe for composite sketches (histogram)."""
    return _use_matmul(num_segments)


_FORCE_SORTED: Optional[bool] = None


def set_sorted_strategy(v: Optional[bool]) -> None:
    """Force the sort-based reduction lane on (True) / off (False);
    None = auto (sorted_strategy below). History: the r4 sort-DEDUP
    design issued a FULL-LENGTH scatter (dropped duplicates are not free
    — the scalar unit walks every index) and lost to the direct scatter
    everywhere (r5: count-min 43 vs 27 ns/row, HLL 12.6 vs 10.6). The r8
    sort–COMPACT lane removes that full-length scatter entirely
    (sorted_segment_reduce_compact: the ≤ nseg winners are compacted to
    the front by a second sort and the final scatter operand has STATIC
    length nseg), so the lane is back on by default on TPU above
    SORTED_MIN_ROWS, behind the ``sorted_compact`` flag."""
    global _FORCE_SORTED
    _FORCE_SORTED = v


def sorted_strategy(n_rows: Optional[int] = None, nseg: Optional[int] = None) -> bool:
    """Should this reduction ride the sort–compact lane?

    Auto policy (no force): TPU-class platforms only (CPU scatters are
    cheap and its sorts are not), ``sorted_compact`` flag on, at least
    SORTED_MIN_ROWS rows, and — when the caller knows its segment count —
    nseg small enough relative to n that the compacted O(nseg) scatter
    tail is actually negligible (≥4x shorter than the direct scatter)."""
    if _FORCE_SORTED is not None:
        return _FORCE_SORTED
    if not flags.sorted_compact:
        return False
    default = True
    if n_rows is not None and n_rows < SORTED_MIN_ROWS:
        default = False
    if n_rows is not None and nseg is not None and nseg * 4 > n_rows:
        default = False
    if default:
        platform = getattr(_TLS, "hint", None) or jax.default_backend()
        default = platform != "cpu"
    # r22: with measured wall times for BOTH lane families at this row
    # bucket, the cost model may overrule the platform/row heuristic —
    # within rails (never sorted far below SORTED_MIN_ROWS, never when
    # nseg*4 > n_rows). Cold or disabled, `default` passes through
    # untouched. Trace-time only: this never runs inside a compiled
    # program.
    cm = _cost_model()
    if cm.ACTIVE and n_rows is not None:
        return cm.choose_sorted_lane(n_rows, nseg, default, SORTED_MIN_ROWS)
    return default


# -- reduction-lane telemetry: which lane each traced program chose.
# Incremented at TRACE time (once per compiled program, not per run) so
# bench.py can record the chosen lane per config next to rows/s.
LANE_COUNTS: dict[str, int] = {}


def lane_count(name: str) -> None:
    LANE_COUNTS[name] = LANE_COUNTS.get(name, 0) + 1


def reduce_lanes(reset: bool = False) -> dict:
    snap = dict(LANE_COUNTS)
    if reset:
        LANE_COUNTS.clear()
    return snap


def _matvec_sum(values_f32, seg_ids, num_segments: int):
    """sum per segment as [1,n]@[n,S] — MXU path, f32 accumulate."""
    oh = jax.nn.one_hot(seg_ids, num_segments, dtype=jnp.float32)
    return values_f32 @ oh


_F64_CHUNK = 256  # bounds f32 in-chunk accumulation error (~chunk*eps relative)


def _matvec_sum_f64(values, seg_ids, num_segments: int):
    """f64 segment sums that still ride the MXU: split each value into
    hi/lo float32 parts (exact to ~2^-48 relative), matmul each part in
    per-chunk batches, and accumulate the chunk partials in float64 — so
    representation error is ~f64-level and f32 accumulation is bounded to
    _F64_CHUNK elements, keeping device sums consistent with the f64
    scatter/host path (they diverged before; ADVICE r1)."""
    n = values.shape[0]
    if n == 0:
        return jnp.zeros((num_segments,), jnp.float64)
    chunk = min(_F64_CHUNK, n)
    pad = (-n) % chunk
    if pad:
        values = jnp.pad(values, (0, pad))  # pad value 0: no-op in a sum
        seg_ids = jnp.pad(seg_ids, (0, pad))
    c = values.shape[0] // chunk
    hi = values.astype(jnp.float32)
    lo = (values - hi.astype(jnp.float64)).astype(jnp.float32)
    oh = jax.nn.one_hot(
        seg_ids.reshape(c, chunk), num_segments, dtype=jnp.float32
    )
    parts_hi = jnp.einsum("ck,cks->cs", hi.reshape(c, chunk), oh)
    parts_lo = jnp.einsum("ck,cks->cs", lo.reshape(c, chunk), oh)
    return jnp.sum(
        parts_hi.astype(jnp.float64) + parts_lo.astype(jnp.float64), axis=0
    )


_LIMB_CHUNK = 1 << 16  # 8-bit limbs: in-chunk f32 sums <= 2^16*255 < 2^24


def _chunked_onehot_sums(V, seg_ids, num_segments: int, chunk: int):
    """[R, n] f32 rows -> [R, S] f64 per-segment sums sharing ONE one-hot,
    accumulating f32 within ``chunk``-sized pieces and f64 across them.
    The precision contract is the CALLER's: limb_einsum_sums feeds exact
    small ints (error-free), f32_rows_einsum feeds arbitrary f32
    (~chunk*eps relative in-chunk error)."""
    n = V.shape[1]
    chunk = min(chunk, max(n, 1))
    pad = (-n) % chunk
    if pad:
        V = jnp.pad(V, ((0, 0), (0, pad)))
        seg_ids = jnp.pad(seg_ids, (0, pad))  # pad rows are 0: no-op
    c = V.shape[1] // chunk
    oh = jax.nn.one_hot(
        seg_ids.reshape(c, chunk), num_segments, dtype=jnp.float32
    )
    parts = jnp.einsum("vck,cks->vcs", V.reshape(-1, c, chunk), oh)
    return jnp.sum(parts.astype(jnp.float64), axis=1)  # [R, S]


def limb_rows_i64(values) -> list:
    """Decompose int64 (two's-complement bit pattern) into eight 8-bit
    limbs as f32 rows. Reconstruction mod 2^64 reproduces exact wrapped
    int64 sums. Only native 32-bit ALU ops (bitcast + shifts/masks)."""
    w = jax.lax.bitcast_convert_type(values.astype(jnp.int64), jnp.uint32)
    rows = []
    for word in (w[..., 0], w[..., 1]):
        for sh in (0, 8, 16, 24):
            rows.append(
                ((word >> jnp.uint32(sh)) & jnp.uint32(0xFF)).astype(
                    jnp.float32
                )
            )
    return rows


def limb_einsum_sums(rows, seg_ids, num_segments: int):
    """Exact per-segment sums of non-negative f32 integer rows — each
    value MUST be an integer in [0, 255] — sharing ONE one-hot:
    [L, n] -> [L, S] float64.

    Exactness: within a chunk every f32 partial sum is an integer
    <= chunk (2^16) * 255 < 2^24, so each add is exact; chunk partials
    are accumulated in f64 (integers < 2^52, exact). Values above 255
    would overflow the 2^24 exact-integer range of f32 mid-chunk — wider
    values must be limb-decomposed first (limb_rows_i64). The MXU does
    the heavy lifting — this replaces the s64 scalar scatter (12x
    slower)."""
    return _chunked_onehot_sums(
        jnp.stack(rows), seg_ids, num_segments, _LIMB_CHUNK
    )


_F32_CHUNK = 1 << 16


def f32_rows_einsum(rows, seg_ids, num_segments: int):
    """Per-segment sums of several f32 rows sharing ONE one-hot:
    [R, n] -> [R, S] float64. Unlike limb_einsum_sums the row values are
    arbitrary f32 (not exact small ints): in-chunk accumulation is f32
    (relative error ~chunk*eps of the chunk partial), chunk partials
    accumulate in f64. Right for f32-grained sketch states (t-digest
    weights/means); exact integer sums must use limb_einsum_sums. The
    one-hot generation dominates, so batching all rows into one einsum
    costs the same as one row (r5 measured: 2 rows 3.76ns vs 9 rows
    4.05ns at 4096 segments)."""
    V = jnp.stack([r.astype(jnp.float32) for r in rows])  # [R, n]
    return _chunked_onehot_sums(V, seg_ids, num_segments, _F32_CHUNK)


def reconstruct_i64(limb_totals):
    """[8, S] f64 limb sums -> exact int64 sums (mod 2^64)."""
    acc = limb_totals[0].astype(jnp.int64)
    for i in range(1, 8):
        acc = acc + (limb_totals[i].astype(jnp.int64) << (8 * i))
    return acc


# -- sort–compact reduction lane (r8, TPU fast path) -------------------------
# TPU's scalar unit serializes scatters: ~7 ns/element at ANY segment
# count, and the cost scales with the scatter OPERAND LENGTH, not the
# unique count — the r4/r5 sort-dedup design still paid a full-length
# scatter and lost. The r8 lane removes it: sort so each segment's
# winning value sorts first, mask the first occurrences, then COMPACT
# the ≤ nseg winners to the front with a second sort keyed
# (winner ? packed_key : SENTINEL) and finish with a scatter whose
# operand has STATIC length nseg (~16K registers) instead of n (64M
# rows). Expected TPU cost: two i32 sorts (0.6–2.4 ns/row measured on a
# v5e at 2M–32M rows, STATUS r5) + an O(nseg) tail, vs ~7 ns/row for the
# direct scatter. tools/microbench_sort_reduce.py sweeps rows x segments
# for all three designs (direct scatter / sort+full-scatter /
# sort–compact). CPU-measured (this container, 1M–4M rows x 2^10–2^16
# segs): scatter 39–46 ns/row, sort+full-scatter 119–125, sort–compact
# 109–120 — compaction beats the full scatter at every shape, but CPU
# sorts are so slow the direct scatter wins outright, which is why the
# lane is TPU-gated (re-run the microbench on hardware to refresh the
# v5e column). Shared by HLL register maxes, count-min bucket counts, and
# (via the generic two-operand variant) high-cardinality min/max
# group-bys; the sentinel segment `nseg` collects masked/losing rows and
# lands on a dropped slot.

# Lane threshold: below this the direct scatter wins. r4 measured 1<<22
# for the sort+FULL-scatter design; the compact lane's scatter tail is
# O(nseg), so the crossover is just where two sorts beat ~7 ns/row —
# readjusted to 1<<20 (provisional: re-measure with
# tools/microbench_sort_reduce.py on hardware).
SORTED_MIN_ROWS = 1 << 20


def compact_fits_i32(nseg: int, value_bits: int) -> bool:
    """Can (segment, value) pack into one non-negative int32 key with a
    sentinel segment? Shared overflow gate: callers must fall back to the
    direct scatter past it (sorted_segment_reduce_compact raises)."""
    return (nseg + 1) << value_bits < (1 << 31)


def sorted_segment_reduce_compact(
    flat, values, value_bits: int, nseg: int, mask=None, mode: str = "max"
):
    """Segment reduction via sort → first-occurrence → COMPACT → O(nseg)
    scatter. The compaction is the r8 algorithmic idea: XLA scatter cost
    scales with operand length, so the winners are compacted to the
    front (second sort keyed ``winner ? packed_key : SENTINEL`` — the
    packed key already orders by segment) and statically sliced to
    ``nseg`` before the final scatter, which therefore touches nseg
    elements instead of n.

    Modes over int32 results:
      'max' / 'min' — reduce ``values`` (small non-negative ints
        < 2^value_bits, e.g. HLL rho) per segment. Empty segments hold 0
        for max (matching sorted_segment_max_small) and
        (2^value_bits - 1) for min.
      'count' — rows per segment; ``values``/``value_bits`` ignored.

    Raises ValueError when (nseg+1) << value_bits overflows int32 — the
    caller must take the direct-scatter lane instead (silent wraparound
    would corrupt every segment id past the boundary)."""
    if mode not in ("max", "min", "count"):
        raise ValueError(f"unknown sort–compact mode {mode!r}")
    if mode == "count":
        value_bits = 0
    if not compact_fits_i32(nseg, value_bits):
        raise ValueError(
            "sorted_segment_reduce_compact: (nseg+1) << value_bits "
            f"overflows int32 (nseg={nseg}, value_bits={value_bits}); "
            "use the direct-scatter lane"
        )
    n = flat.shape[0]
    vmax = jnp.int32((1 << value_bits) - 1)
    if n == 0:
        fill = vmax if mode == "min" else jnp.int32(0)
        return jnp.full(nseg, fill, jnp.int32)
    sentinel = jnp.int32(nseg << value_bits)
    if mode == "count":
        key = flat.astype(jnp.int32)
        if mask is not None:
            key = jnp.where(mask, key, jnp.int32(nseg))
        ks = jnp.sort(key)
        idx = jnp.arange(n, dtype=jnp.int32)
        first = jnp.concatenate([jnp.ones(1, jnp.bool_), ks[1:] != ks[:-1]])
        # Index of the next run start AFTER each position: reverse cummin
        # of start positions (n where not a start).
        start_at = jnp.where(first, idx, jnp.int32(n))
        nxt = jnp.flip(
            jax.lax.cummin(
                jnp.flip(
                    jnp.concatenate([start_at[1:], jnp.full(1, n, jnp.int32)])
                )
            )
        )
        runlen = jnp.where(first, nxt - idx, 0)
        keep = first & (ks < nseg)
        ckey, ccnt = jax.lax.sort(
            (jnp.where(keep, ks, jnp.int32(nseg)), runlen), num_keys=1
        )
        k = min(nseg, n)
        seg, cnt = ckey[:k], ccnt[:k]
        live = seg < nseg
        return (
            jnp.zeros(nseg, jnp.int32)
            .at[jnp.where(live, seg, nseg)]
            .add(jnp.where(live, cnt, 0), mode="drop")
        )
    # max/min: pack (segment, value) into one key so each segment's
    # winning value sorts FIRST within its run.
    vkey = (vmax - values) if mode == "max" else values
    key = (flat.astype(jnp.int32) << value_bits) | vkey.astype(jnp.int32)
    if mask is not None:
        key = jnp.where(mask, key, sentinel)
    ks = jnp.sort(key)
    flat_s = ks >> value_bits
    first = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), flat_s[1:] != flat_s[:-1]]
    )
    keep = first & (flat_s < nseg)
    # Compact: the winners' packed keys already order by segment, so one
    # more sort with losers collapsed onto the sentinel brings the ≤ nseg
    # winners to the front; the slice length is STATIC.
    cks = jnp.sort(jnp.where(keep, ks, sentinel))[: min(nseg, n)]
    seg = cks >> value_bits
    val = cks & vmax
    if mode == "max":
        val = vmax - val
    live = seg < nseg
    fill = jnp.int32(0) if mode == "max" else vmax
    return (
        jnp.full(nseg, fill, jnp.int32)
        .at[jnp.where(live, seg, nseg)]
        .set(jnp.where(live, val, fill), mode="drop")
    )


def sorted_segment_minmax_compact(
    values, seg_ids, num_segments: int, mask=None, is_min: bool = False
):
    """Per-segment min/max of ARBITRARY-dtype values (int64/float64
    group-by args) via a two-operand lexicographic sort + the same
    compaction: sort (segment, value) ascending, take the first (min) or
    last (max) row of each segment's run, compact the winners with a
    second sort, and scatter nseg elements. Empty segments hold the same
    identity fill seg_min/seg_max produce, so elementwise state merges
    are unchanged."""
    ident = _identity_for(values.dtype, is_min=is_min)
    n = values.shape[0]
    if n == 0:
        return jnp.full(num_segments, ident, values.dtype)
    seg = seg_ids.astype(jnp.int32)
    if mask is not None:
        seg = jnp.where(mask, seg, jnp.int32(num_segments))
    seg_s, val_s = jax.lax.sort((seg, values), num_keys=2)
    if is_min:
        winner = jnp.concatenate(
            [jnp.ones(1, jnp.bool_), seg_s[1:] != seg_s[:-1]]
        )
    else:
        winner = jnp.concatenate(
            [seg_s[1:] != seg_s[:-1], jnp.ones(1, jnp.bool_)]
        )
    winner = winner & (seg_s < num_segments)
    ckey, cval = jax.lax.sort(
        (jnp.where(winner, seg_s, jnp.int32(num_segments)), val_s),
        num_keys=1,
    )
    k = min(num_segments, n)
    seg_c, val_c = ckey[:k], cval[:k]
    live = seg_c < num_segments
    return (
        jnp.full(num_segments, ident, values.dtype)
        .at[jnp.where(live, seg_c, num_segments)]
        .set(jnp.where(live, val_c, ident), mode="drop")
    )


def sorted_segment_counts(flat, nseg: int, mask=None):
    """Per-segment counts via sort + run-length + compaction (r8: the
    r4 unique-index scatter was still FULL-length — XLA walks every
    index — so it lost; the compacted scatter touches nseg elements).
    Exact; int32 result (callers widen)."""
    return sorted_segment_reduce_compact(
        flat, None, 0, nseg, mask, mode="count"
    )


def sorted_segment_max_small(flat, values, value_bits: int, nseg: int, mask=None):
    """Per-segment max of small non-negative ints (< 2^value_bits) via a
    single packed-key sort: key = flat << bits | (max_value - value), so
    each segment's LARGEST value sorts first and the first-occurrence mask
    yields unique scatter indices. Requires (nseg+1) << value_bits < 2^31.
    Returns int32 maxes (0 for empty segments).

    NOTE (r8): the scatter here is still FULL-LENGTH (unique indices are
    not cheaper — XLA scatter cost scales with operand length), which is
    why this design lost to the direct scatter in r5. Kept as the
    sort+full-scatter comparand for tools/microbench_sort_reduce.py;
    production consumers use sorted_segment_reduce_compact."""
    n = flat.shape[0]
    if n == 0:
        return jnp.zeros(nseg, jnp.int32)
    vmax = jnp.int32((1 << value_bits) - 1)
    key = (flat << value_bits) | (vmax - values)
    if mask is not None:
        key = jnp.where(mask, key, jnp.int32(nseg << value_bits))
    ks = jnp.sort(key)
    flat_s = ks >> value_bits
    val_s = vmax - (ks & vmax)
    first = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), flat_s[1:] != flat_s[:-1]]
    )
    keep = first & (flat_s < nseg)
    idx = jnp.where(keep, flat_s, nseg)
    out = (
        jnp.zeros(nseg + 1, jnp.int32)
        .at[idx]
        .max(jnp.where(keep, val_s, 0), mode="drop")
    )
    return out[:-1]


# -- r19: sort-merge join primitives ----------------------------------------
# The join lane reuses the r8 idioms directly: a stable packed-key sort
# orders the build side (reproducing the host JoinNode's per-key original
# row order), searchsorted runs the merge, and the sentinel-sort
# compaction brings unmatched rows to the front for the outer variants.
# Output is bounded by host-computed caps (exact match/unmatched counts
# from bincount, padded to a power of two) so every shape is static.


def merge_join_pairs(sorted_build_keys, build_order, probe_keys, pair_cap: int):
    """Emit up to ``pair_cap`` (build_row, probe_row) match pairs of an
    equijoin between a SORTED build side and an unsorted probe side.

    ``sorted_build_keys``/``build_order`` come from one stable sort of the
    build keys (order = original row index), so within each key the build
    rows appear in original order — matching the host JoinNode's stable
    ``_build_order``. Pairs are probe-row-major: for probe row p with
    fanout f, its f pairs occupy slots [prefix[p]-f, prefix[p]).

    Returns ``(build_rows, probe_rows, valid, fanout)`` — all int32 except
    the bool ``valid`` mask; slots past the true match count are invalid
    (clipped gathers; callers mask or slice them away). ``fanout`` is the
    per-probe-row match count (0 for masked/padded rows whose key is a
    sentinel absent from the build side). Callers guarantee the true match
    total fits ``pair_cap`` and int32."""
    nb = sorted_build_keys.shape[0]
    np_ = probe_keys.shape[0]
    lo = jnp.searchsorted(
        sorted_build_keys, probe_keys, side="left"
    ).astype(jnp.int32)
    hi = jnp.searchsorted(
        sorted_build_keys, probe_keys, side="right"
    ).astype(jnp.int32)
    fanout = hi - lo
    prefix = jnp.cumsum(fanout)
    t = jnp.arange(pair_cap, dtype=jnp.int32)
    # Slot t belongs to the first probe row whose prefix exceeds t.
    probe_rows = jnp.minimum(
        jnp.searchsorted(prefix, t, side="right").astype(jnp.int32),
        jnp.int32(np_ - 1),
    )
    base = prefix[probe_rows] - fanout[probe_rows]
    build_pos = jnp.clip(lo[probe_rows] + (t - base), 0, nb - 1)
    return build_order[build_pos], probe_rows, t < prefix[-1], fanout


def local_sort_merge(lkey, rkey, lmask, rmask, cap_m: int, cap_r: int, cap_l: int):
    """The sort→merge→compact core shared by the replicated (v1) and
    partitioned (r21 mesh) join lanes, over whatever key slice the
    caller holds — the whole table when replicated, one hosts-axis
    shard when partitioned.

    ``lkey``/``rkey`` are sentinel-applied (padded build rows carry a
    key above every real id, padded probe rows one higher still, so
    neither can pair). One stable sort orders the build side by
    (key, original row), reproducing the host JoinNode's per-key
    original row order; ``merge_join_pairs`` emits probe-row-major
    match pairs; the sentinel-sort compaction fronts unmatched rows
    for the outer variants (cap 0 skips a section).

    Returns ``(build_rows, probe_rows, fanout, ur, ul)`` — int32 row
    indices into the caller's key slices; ``ur``/``ul`` are None when
    their cap is 0."""
    sl_key, sl_idx = jax.lax.sort(
        (lkey, jnp.arange(lkey.shape[0], dtype=jnp.int32)),
        num_keys=1,
        is_stable=True,
    )
    build_rows, probe_rows, _pv, fanout = merge_join_pairs(
        sl_key, sl_idx, rkey, cap_m
    )
    ur = ul = None
    if cap_r:
        ur = compact_unmatched_rows(rmask & (fanout == 0), cap_r)
    if cap_l:
        sr_key = jnp.sort(rkey)
        l_matched = jnp.searchsorted(
            sr_key, lkey, side="right"
        ) > jnp.searchsorted(sr_key, lkey, side="left")
        ul = compact_unmatched_rows(lmask & ~l_matched, cap_l)
    return build_rows, probe_rows, fanout, ur, ul


def compact_unmatched_rows(unmatched, cap: int):
    """Compact the indices of ``unmatched`` rows to the front, preserving
    original row order — the r8 sentinel-sort idiom (losers collapse onto
    sentinel ``n``, one sort, static slice). Returns int32[cap]; entries
    >= n are padding."""
    n = unmatched.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    out = jnp.sort(jnp.where(unmatched, idx, jnp.int32(n)))[: min(cap, n)]
    if cap > n:
        out = jnp.concatenate([out, jnp.full(cap - n, n, jnp.int32)])
    return out


def seg_sum(values, seg_ids, num_segments: int, mask=None):
    if _use_matmul(num_segments) and jnp.issubdtype(
        values.dtype, jnp.floating
    ):
        if values.dtype == jnp.float64:
            v = values if mask is None else jnp.where(mask, values, 0.0)
            return _matvec_sum_f64(v, seg_ids, num_segments)
        v = values.astype(jnp.float32)
        if mask is not None:
            v = jnp.where(mask, v, 0.0)
        return _matvec_sum(v, seg_ids, num_segments).astype(values.dtype)
    if _use_matmul(num_segments) and values.dtype == jnp.int64:
        # int32 stays on the (fast) s32 scatter; int64 scatter is ~12x
        # slower than s32, so exact limb sums on the MXU win decisively.
        v = values if mask is None else jnp.where(mask, values, 0)
        totals = limb_einsum_sums(
            limb_rows_i64(v), seg_ids.astype(jnp.int32), num_segments
        )
        return reconstruct_i64(totals)
    v = values if mask is None else jnp.where(mask, values, 0)
    return jax.ops.segment_sum(v, seg_ids, num_segments=num_segments)


def seg_count(seg_ids, num_segments: int, mask=None):
    if _use_matmul(num_segments):
        ones = (
            jnp.ones(seg_ids.shape, jnp.float32)
            if mask is None
            else mask.astype(jnp.float32)
        )
        # Chunk-exact at any n: in-chunk f32 sums are integers <= 2^16.
        totals = limb_einsum_sums(
            [ones], seg_ids.astype(jnp.int32), num_segments
        )
        return totals[0].astype(jnp.int64)
    # Scatter-add in int32 — TPU emulates s64 scatters at ~3x the cost —
    # and widen after: a single call covers one block (< 2^31 rows), so the
    # int32 partial is exact; the int64 accumulation across blocks happens
    # in the caller's state.
    ones = (
        jnp.ones(seg_ids.shape, jnp.int32)
        if mask is None
        else mask.astype(jnp.int32)
    )
    return jax.ops.segment_sum(
        ones, seg_ids, num_segments=num_segments
    ).astype(jnp.int64)


def seg_min(values, seg_ids, num_segments: int, mask=None):
    # min has no MXU einsum form; the sort–compact lane replaces the
    # ~7 ns/row scalar scatter above SORTED_MIN_ROWS (r8).
    if sorted_strategy(values.shape[0], num_segments):
        lane_count("minmax_sorted_compact")
        return sorted_segment_minmax_compact(
            values, seg_ids, num_segments, mask, is_min=True
        )
    lane_count("minmax_scatter")
    if mask is not None:
        fill = _identity_for(values.dtype, is_min=True)
        values = jnp.where(mask, values, fill)
    return jax.ops.segment_min(values, seg_ids, num_segments=num_segments)


def seg_max(values, seg_ids, num_segments: int, mask=None):
    if sorted_strategy(values.shape[0], num_segments):
        lane_count("minmax_sorted_compact")
        return sorted_segment_minmax_compact(
            values, seg_ids, num_segments, mask, is_min=False
        )
    lane_count("minmax_scatter")
    if mask is not None:
        fill = _identity_for(values.dtype, is_min=False)
        values = jnp.where(mask, values, fill)
    return jax.ops.segment_max(values, seg_ids, num_segments=num_segments)


def seg_any(values, seg_ids, num_segments: int, mask=None):
    v = values.astype(jnp.int32)
    if mask is not None:
        v = jnp.where(mask, v, 0)
    return jax.ops.segment_max(v, seg_ids, num_segments=num_segments).astype(jnp.bool_)


def seg_mean_state(values, seg_ids, num_segments: int, mask=None):
    """(sum, count) pair — mergeable across shards before the divide."""
    return (
        seg_sum(values, seg_ids, num_segments, mask),
        seg_count(seg_ids, num_segments, mask),
    )


def _identity_for(dtype, is_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if is_min else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if is_min else info.min, dtype)


def flat_segment_ids(gids, inner_ids, inner_size: int):
    """Compose (group, bucket) -> flat segment id for 2-D scatter-free
    histogram updates: segment-reduce over gids*inner_size+inner then reshape."""
    return gids.astype(jnp.int32) * inner_size + inner_ids.astype(jnp.int32)
