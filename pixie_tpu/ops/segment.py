"""Masked segment reductions — the group-by aggregation primitive.

The reference aggregates row-at-a-time into an absl hash map of per-group UDA
objects (src/carnot/exec/agg_node.cc: HashRowBatch -> AggHashValue ->
UDA::Update). On TPU there are no dynamic hash maps inside a compiled
program; instead group keys are dense int32 segment ids (strings arrive
dictionary-encoded; other key types are densified host-side by
pixie_tpu.exec's GroupDictionary) and aggregation is an XLA segment
reduction over a static number of segments. Padding rows carry mask=False.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

# Strategy selection: XLA's scatter-add lowers to the TPU's scalar scatter
# units (~150M rows/s measured on v5e); a one-hot matvec rides the MXU at
# >2B rows/s for small segment counts. CPU prefers scatter. Tests can pin a
# strategy via set_strategy().
import threading

_FORCE: Optional[str] = None
_TLS = threading.local()  # per-thread platform hint: agents run in threads
MATMUL_MAX_SEGMENTS = 128


def set_strategy(s: Optional[str]) -> None:
    """Force 'matmul' or 'scatter' (None = auto by platform)."""
    global _FORCE
    assert s in (None, "matmul", "scatter")
    _FORCE = s


class platform_hint:
    """Context manager: pin the platform these kernels will execute on for
    the CURRENT THREAD. jax.default_backend() is a process-wide default
    that can differ from the mesh/device a program is traced for (e.g. CPU
    exec graph on a TPU-attached host); concurrent agent threads each carry
    their own hint."""

    def __init__(self, platform: Optional[str]):
        self.platform = platform

    def __enter__(self):
        self._old = getattr(_TLS, "hint", None)
        _TLS.hint = self.platform
        return self

    def __exit__(self, *exc):
        _TLS.hint = self._old
        return False


def _use_matmul(num_segments: int) -> bool:
    if _FORCE is not None:
        return _FORCE == "matmul"
    platform = getattr(_TLS, "hint", None) or jax.default_backend()
    return platform != "cpu" and num_segments <= MATMUL_MAX_SEGMENTS


def matmul_strategy(num_segments: int) -> bool:
    """Public strategy probe for composite sketches (histogram)."""
    return _use_matmul(num_segments)


def _matvec_sum(values_f32, seg_ids, num_segments: int):
    """sum per segment as [1,n]@[n,S] — MXU path, f32 accumulate."""
    oh = jax.nn.one_hot(seg_ids, num_segments, dtype=jnp.float32)
    return values_f32 @ oh


_F64_CHUNK = 256  # bounds f32 in-chunk accumulation error (~chunk*eps relative)


def _matvec_sum_f64(values, seg_ids, num_segments: int):
    """f64 segment sums that still ride the MXU: split each value into
    hi/lo float32 parts (exact to ~2^-48 relative), matmul each part in
    per-chunk batches, and accumulate the chunk partials in float64 — so
    representation error is ~f64-level and f32 accumulation is bounded to
    _F64_CHUNK elements, keeping device sums consistent with the f64
    scatter/host path (they diverged before; ADVICE r1)."""
    n = values.shape[0]
    if n == 0:
        return jnp.zeros((num_segments,), jnp.float64)
    chunk = min(_F64_CHUNK, n)
    pad = (-n) % chunk
    if pad:
        values = jnp.pad(values, (0, pad))  # pad value 0: no-op in a sum
        seg_ids = jnp.pad(seg_ids, (0, pad))
    c = values.shape[0] // chunk
    hi = values.astype(jnp.float32)
    lo = (values - hi.astype(jnp.float64)).astype(jnp.float32)
    oh = jax.nn.one_hot(
        seg_ids.reshape(c, chunk), num_segments, dtype=jnp.float32
    )
    parts_hi = jnp.einsum("ck,cks->cs", hi.reshape(c, chunk), oh)
    parts_lo = jnp.einsum("ck,cks->cs", lo.reshape(c, chunk), oh)
    return jnp.sum(
        parts_hi.astype(jnp.float64) + parts_lo.astype(jnp.float64), axis=0
    )


def seg_sum(values, seg_ids, num_segments: int, mask=None):
    if _use_matmul(num_segments) and jnp.issubdtype(
        values.dtype, jnp.floating
    ):
        if values.dtype == jnp.float64:
            v = values if mask is None else jnp.where(mask, values, 0.0)
            return _matvec_sum_f64(v, seg_ids, num_segments)
        v = values.astype(jnp.float32)
        if mask is not None:
            v = jnp.where(mask, v, 0.0)
        return _matvec_sum(v, seg_ids, num_segments).astype(values.dtype)
    v = values if mask is None else jnp.where(mask, values, 0)
    return jax.ops.segment_sum(v, seg_ids, num_segments=num_segments)


def seg_count(seg_ids, num_segments: int, mask=None):
    if _use_matmul(num_segments):
        ones = (
            jnp.ones(seg_ids.shape, jnp.float32)
            if mask is None
            else mask.astype(jnp.float32)
        )
        # Exact while each call covers < 2^24 rows (blocks are 2^17); the
        # int accumulation across blocks happens in the UDA state.
        return jnp.round(
            _matvec_sum(ones, seg_ids, num_segments)
        ).astype(jnp.int64)
    # Scatter-add in int32 — TPU emulates s64 scatters at ~3x the cost —
    # and widen after: a single call covers one block (< 2^31 rows), so the
    # int32 partial is exact; the int64 accumulation across blocks happens
    # in the caller's state.
    ones = (
        jnp.ones(seg_ids.shape, jnp.int32)
        if mask is None
        else mask.astype(jnp.int32)
    )
    return jax.ops.segment_sum(
        ones, seg_ids, num_segments=num_segments
    ).astype(jnp.int64)


def seg_min(values, seg_ids, num_segments: int, mask=None):
    if mask is not None:
        fill = _identity_for(values.dtype, is_min=True)
        values = jnp.where(mask, values, fill)
    return jax.ops.segment_min(values, seg_ids, num_segments=num_segments)


def seg_max(values, seg_ids, num_segments: int, mask=None):
    if mask is not None:
        fill = _identity_for(values.dtype, is_min=False)
        values = jnp.where(mask, values, fill)
    return jax.ops.segment_max(values, seg_ids, num_segments=num_segments)


def seg_any(values, seg_ids, num_segments: int, mask=None):
    v = values.astype(jnp.int32)
    if mask is not None:
        v = jnp.where(mask, v, 0)
    return jax.ops.segment_max(v, seg_ids, num_segments=num_segments).astype(jnp.bool_)


def seg_mean_state(values, seg_ids, num_segments: int, mask=None):
    """(sum, count) pair — mergeable across shards before the divide."""
    return (
        seg_sum(values, seg_ids, num_segments, mask),
        seg_count(seg_ids, num_segments, mask),
    )


def _identity_for(dtype, is_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf if is_min else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max if is_min else info.min, dtype)


def flat_segment_ids(gids, inner_ids, inner_size: int):
    """Compose (group, bucket) -> flat segment id for 2-D scatter-free
    histogram updates: segment-reduce over gids*inner_size+inner then reshape."""
    return gids.astype(jnp.int32) * inner_size + inner_ids.astype(jnp.int32)
