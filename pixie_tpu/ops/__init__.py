"""Device kernels: the JAX/XLA compute layer under the exec engine.

This package is the TPU replacement for the reference's per-row C++ hot path
(RowTuple hashing + absl hash maps + per-group UDA virtual calls,
src/carnot/exec/agg_node.cc / row_tuple.h): group keys become dense int32
segment ids, aggregation becomes masked segment reductions, and sketch UDAs
(t-digest / HLL / count-min / log-histogram) are fixed-shape tensors whose
merge is an elementwise or sort-based op — so the cross-device "Kelvin merge"
is a psum/pmax collective over ICI instead of a gRPC stream.

All functions here are jit-compatible, static-shape, and take explicit masks
(padded batches are first-class: XLA wants fixed shapes).
"""

from pixie_tpu.ops import hashing, segment, tdigest, hll, countmin, histogram  # noqa: F401
