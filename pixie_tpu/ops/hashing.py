"""Device hash functions.

Replaces the reference's RowTuple hashing (src/carnot/exec/row_tuple.h:
absl-hash of packed variable-type tuples) with vectorized integer mixing that
runs on the VPU. Strings are already dictionary codes by the time they reach
the device, so every key column is an integer lane.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_U64 = jnp.uint64


def _u64(c: int):
    return np.uint64(c)


def splitmix64(x: jax.Array) -> jax.Array:
    """SplitMix64 finalizer — a full-avalanche 64-bit mix."""
    z = x.astype(_U64) + _u64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> _u64(30))) * _u64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> _u64(27))) * _u64(0x94D049BB133111EB)
    return z ^ (z >> _u64(31))


def hash64(x: jax.Array, seed: int = 0) -> jax.Array:
    """Hash any integer/float column to uint64."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        # Bit-cast so +/-0.0 collapse and NaNs hash stably enough for keys.
        x = jax.lax.bitcast_convert_type(x.astype(jnp.float64), jnp.uint64)
    elif x.dtype == jnp.bool_:
        x = x.astype(jnp.uint64)
    return splitmix64(x.astype(_U64) ^ _u64((seed * 0x9E3779B97F4A7C15) & (2**64 - 1)))


def combine(h1: jax.Array, h2: jax.Array) -> jax.Array:
    """Order-dependent hash combine (boost-style) for multi-column keys."""
    h1 = h1.astype(_U64)
    return splitmix64(
        h1 ^ (h2.astype(_U64) + _u64(0x9E3779B97F4A7C15) + (h1 << _u64(6)) + (h1 >> _u64(2)))
    )


def hash_columns(cols: list[jax.Array], seed: int = 0) -> jax.Array:
    """Hash a multi-column key row-wise into uint64."""
    h = hash64(cols[0], seed)
    for c in cols[1:]:
        h = combine(h, hash64(c, seed))
    return h


def clz64(x: jax.Array) -> jax.Array:
    """Count leading zeros of uint64 (used by HLL rho)."""
    x = x.astype(_U64)
    # Smear the highest set bit downward, then popcount.
    for s in (1, 2, 4, 8, 16, 32):
        x = x | (x >> _u64(s))
    return (64 - jax.lax.population_count(x).astype(jnp.int32)).astype(jnp.int32)


# -- 32-bit path -------------------------------------------------------------
# TPU has no native 64-bit integer multiply: every u64 mix above is emulated
# as several u32 multiplies/adds (~3x). Sketch updates (HLL registers, CM
# buckets) only need 32 bits of well-mixed entropy per use, so they ride
# this native-u32 pipeline instead (measured ~5x cheaper per block).

_U32 = jnp.uint32


def _u32(c: int):
    return np.uint32(c)


def u32_words(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(lo, hi) uint32 words of any column, via bitcast (no 64-bit ALU)."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(jnp.float64)
        w = jax.lax.bitcast_convert_type(x, _U32)  # [..., 2]
        return w[..., 0], w[..., 1]
    if x.dtype in (jnp.int64, jnp.uint64):
        w = jax.lax.bitcast_convert_type(x, _U32)
        return w[..., 0], w[..., 1]
    if x.dtype == jnp.bool_:
        x = x.astype(_U32)
    return x.astype(_U32), jnp.zeros_like(x, _U32)


def mix32(x: jax.Array, seed: int = 0) -> jax.Array:
    """murmur3 fmix32 — full-avalanche 32-bit mix on the VPU."""
    x = x.astype(_U32) ^ _u32(seed & 0xFFFFFFFF)
    x = (x ^ (x >> _u32(16))) * _u32(0x85EBCA6B)
    x = (x ^ (x >> _u32(13))) * _u32(0xC2B2AE35)
    return x ^ (x >> _u32(16))


def hash32(x: jax.Array, seed: int = 0) -> jax.Array:
    """Hash any column to uint32 using only native 32-bit ops."""
    lo, hi = u32_words(x)
    return mix32(lo ^ mix32(hi, 0x9E3779B9 ^ seed), 0x85EBCA77 ^ seed)


def hash32_pair(x: jax.Array, seed: int = 0) -> tuple[jax.Array, jax.Array]:
    """Two independent uint32 hashes (Kirsch–Mitzenmacher base pair)."""
    lo, hi = u32_words(x)
    a = mix32(lo ^ mix32(hi, 0x9E3779B9 ^ seed), 0x85EBCA77 ^ seed)
    b = mix32(hi ^ mix32(lo, 0xC2B2AE35 ^ seed), 0x27D4EB2F ^ seed)
    return a, b


def clz32(x: jax.Array) -> jax.Array:
    """Count leading zeros of uint32."""
    x = x.astype(_U32)
    for s in (1, 2, 4, 8, 16):
        x = x | (x >> _u32(s))
    return (32 - jax.lax.population_count(x).astype(jnp.int32)).astype(jnp.int32)
