"""Device hash functions.

Replaces the reference's RowTuple hashing (src/carnot/exec/row_tuple.h:
absl-hash of packed variable-type tuples) with vectorized integer mixing that
runs on the VPU. Strings are already dictionary codes by the time they reach
the device, so every key column is an integer lane.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_U64 = jnp.uint64


def _u64(c: int):
    return np.uint64(c)


def splitmix64(x: jax.Array) -> jax.Array:
    """SplitMix64 finalizer — a full-avalanche 64-bit mix."""
    z = x.astype(_U64) + _u64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> _u64(30))) * _u64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> _u64(27))) * _u64(0x94D049BB133111EB)
    return z ^ (z >> _u64(31))


def hash64(x: jax.Array, seed: int = 0) -> jax.Array:
    """Hash any integer/float column to uint64."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        # Bit-cast so +/-0.0 collapse and NaNs hash stably enough for keys.
        x = jax.lax.bitcast_convert_type(x.astype(jnp.float64), jnp.uint64)
    elif x.dtype == jnp.bool_:
        x = x.astype(jnp.uint64)
    return splitmix64(x.astype(_U64) ^ _u64((seed * 0x9E3779B97F4A7C15) & (2**64 - 1)))


def combine(h1: jax.Array, h2: jax.Array) -> jax.Array:
    """Order-dependent hash combine (boost-style) for multi-column keys."""
    h1 = h1.astype(_U64)
    return splitmix64(
        h1 ^ (h2.astype(_U64) + _u64(0x9E3779B97F4A7C15) + (h1 << _u64(6)) + (h1 >> _u64(2)))
    )


def hash_columns(cols: list[jax.Array], seed: int = 0) -> jax.Array:
    """Hash a multi-column key row-wise into uint64."""
    h = hash64(cols[0], seed)
    for c in cols[1:]:
        h = combine(h, hash64(c, seed))
    return h


def clz64(x: jax.Array) -> jax.Array:
    """Count leading zeros of uint64 (used by HLL rho)."""
    x = x.astype(_U64)
    # Smear the highest set bit downward, then popcount.
    for s in (1, 2, 4, 8, 16, 32):
        x = x | (x >> _u64(s))
    return (64 - jax.lax.population_count(x).astype(jnp.int32)).astype(jnp.int32)
