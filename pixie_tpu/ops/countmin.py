"""Count-min frequency sketch, vectorized over groups.

Net-new UDA (not in the reference — SURVEY.md §6): state is a dense
[num_groups, depth, width] int64 tensor; update adds per-depth bucket
counts; merge is elementwise add — cross-device merge is a single
`lax.psum`. Point queries take the min over depth rows (classic CM upper
bound).

Update strategies (r5 re-measured with state-carrying scans):
- ``cell_update`` — when the value column arrives as small-dictionary
  codes (the pipeline's int-dictionary staging), the per-(group, value)
  HISTOGRAM is computed by ONE MXU one-hot einsum and the sketch is
  updated per CELL, not per row: depth x |cells| scatter elements
  instead of depth x n (4.3 vs 27+ ns/row at 16 groups on a v5e).
  Exact — identical buckets to the row path, since all rows of a cell
  share their hash pair.
- ``update`` — per-row fallback: two native-u32 hashes
  (Kirsch–Mitzenmacher double hashing; a u64 multiply path is ~5x
  dearer on TPU) and a per-depth bucket count. Above
  segment.SORTED_MIN_ROWS the counts ride the r8 sort–COMPACT lane
  (run-length counts compacted to an O(nseg) scatter); the r4 sorted
  path — whose dedup sort still paid a FULL-length scatter and lost 43
  vs 27 ns/row (r5) — is what the compaction fixes. Below the threshold
  (or on CPU) the direct scatter-add remains.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu.ops import hashing, segment

DEFAULT_DEPTH = 4
DEFAULT_WIDTH = 8192  # eps ~ e/width ~ 3.3e-4 of total count


def init(num_groups: int, depth: int = DEFAULT_DEPTH, width: int = DEFAULT_WIDTH):
    if width & (width - 1):
        raise ValueError(
            f"count-min width must be a power of two (got {width}): "
            "bucketing masks with width-1"
        )
    return jnp.zeros((num_groups, depth, width), jnp.int64)


def _buckets(values, depth: int, width: int):
    """Kirsch–Mitzenmacher double hashing from two u32 hashes:
    bucket_d = (h1 + d*h2) & (width-1), all in native 32-bit VPU ops."""
    h1, h2 = hashing.hash32_pair(values, seed=1)
    return [
        ((h1 + jnp.uint32(d) * h2) & jnp.uint32(width - 1)).astype(jnp.int32)
        for d in range(depth)
    ]


def update(state, gids, values, mask=None):
    num_groups, depth, width = state.shape
    nseg = num_groups * width
    outs = []
    # r8: sorted_segment_counts now COMPACTS (run lengths ride a second
    # sort so the final scatter operand is O(nseg), not O(n)) — the
    # full-length unique-index scatter that made the r4 sorted path lose
    # is gone, so the lane re-enables above segment.SORTED_MIN_ROWS.
    use_sorted = segment.sorted_strategy(
        gids.shape[0], nseg
    ) and segment.compact_fits_i32(nseg, 0)
    segment.lane_count(
        "countmin_sorted_compact" if use_sorted else "countmin_scatter"
    )
    for bucket in _buckets(values, depth, width):
        flat = segment.flat_segment_ids(gids, bucket, width)
        if use_sorted:
            counts = segment.sorted_segment_counts(flat, nseg, mask)
        else:
            counts = segment.seg_count(flat, nseg, mask)
        outs.append(counts.reshape(num_groups, width))
    return state + jnp.stack(outs, axis=1)


def cell_update(state, hist, lut):
    """Fold a per-(group, value-code) histogram into the sketch.

    ``hist``: [num_groups, C] int64 row counts per cell; ``lut``: [C]
    the int64 value each code stands for. Every row of a cell hashes
    identically, so adding the cell COUNT to the cell value's buckets
    reproduces the row-wise update exactly while the scalar unit only
    touches num_groups*C*depth elements."""
    num_groups, depth, width = state.shape
    C = lut.shape[0]
    h1, h2 = hashing.hash32_pair(lut, seed=1)  # [C]
    cg = jnp.arange(num_groups * C, dtype=jnp.int32) // C
    counts = hist.reshape(-1)  # [G*C]
    outs = []
    for d in range(depth):
        b = ((h1 + jnp.uint32(d) * h2) & jnp.uint32(width - 1)).astype(
            jnp.int32
        )  # [C]
        flat = cg * width + jnp.tile(b, num_groups)
        outs.append(
            jnp.zeros(num_groups * width, jnp.int64)
            .at[flat]
            .add(counts)
            .reshape(num_groups, width)
        )
    return state + jnp.stack(outs, axis=1)


def merge(a, b):
    return a + b


def query(state, gids, values):
    """Estimated counts for (group, value) pairs: min over depth rows."""
    num_groups, depth, width = state.shape
    ests = []
    for d, b in enumerate(_buckets(values, depth, width)):
        ests.append(state[gids, d, b])
    return jnp.min(jnp.stack(ests, axis=0), axis=0)
