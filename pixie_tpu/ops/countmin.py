"""Count-min frequency sketch, vectorized over groups.

Net-new UDA (not in the reference — SURVEY.md §6): state is a dense
[num_groups, depth, width] int64 tensor; update is depth masked segment-sums;
merge is elementwise add — cross-device merge is a single `lax.psum`.
Point queries take the min over depth rows (classic CM upper bound).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pixie_tpu.ops import hashing, segment

DEFAULT_DEPTH = 4
DEFAULT_WIDTH = 8192  # eps ~ e/width ~ 3.3e-4 of total count


def init(num_groups: int, depth: int = DEFAULT_DEPTH, width: int = DEFAULT_WIDTH):
    if width & (width - 1):
        raise ValueError(
            f"count-min width must be a power of two (got {width}): "
            "bucketing masks with width-1"
        )
    return jnp.zeros((num_groups, depth, width), jnp.int64)


def _buckets(values, depth: int, width: int):
    """Kirsch–Mitzenmacher double hashing: ONE u64 hash (u64 multiplies are
    ~3x-emulated on TPU), then bucket_d = (h_lo + d*h_hi) & (width-1) in
    cheap 32-bit VPU arithmetic. Preserves the CM guarantees to within the
    usual double-hashing analysis."""
    h = hashing.hash64(values, seed=1)
    lo = (h & np.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (h >> np.uint64(32)).astype(jnp.uint32)
    return [
        ((lo + jnp.uint32(d) * hi) & jnp.uint32(width - 1)).astype(jnp.int32)
        for d in range(depth)
    ]


def update(state, gids, values, mask=None):
    num_groups, depth, width = state.shape
    outs = []
    for bucket in _buckets(values, depth, width):
        flat = segment.flat_segment_ids(gids, bucket, width)
        outs.append(
            segment.seg_count(flat, num_groups * width, mask).reshape(
                num_groups, width
            )
        )
    return state + jnp.stack(outs, axis=1)


def merge(a, b):
    return a + b


def query(state, gids, values):
    """Estimated counts for (group, value) pairs: min over depth rows."""
    num_groups, depth, width = state.shape
    ests = []
    for d, b in enumerate(_buckets(values, depth, width)):
        ests.append(state[gids, d, b])
    return jnp.min(jnp.stack(ests, axis=0), axis=0)
