"""Count-min frequency sketch, vectorized over groups.

Net-new UDA (not in the reference — SURVEY.md §6): state is a dense
[num_groups, depth, width] int64 tensor; update is depth masked segment-sums;
merge is elementwise add — cross-device merge is a single `lax.psum`.
Point queries take the min over depth rows (classic CM upper bound).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pixie_tpu.ops import hashing, segment

DEFAULT_DEPTH = 4
DEFAULT_WIDTH = 8192  # eps ~ e/width ~ 3.3e-4 of total count


def init(num_groups: int, depth: int = DEFAULT_DEPTH, width: int = DEFAULT_WIDTH):
    return jnp.zeros((num_groups, depth, width), jnp.int64)


def _bucket(values, seed: int, width: int):
    return (hashing.hash64(values, seed=seed + 1) % np.uint64(width)).astype(
        jnp.int32
    )


def update(state, gids, values, mask=None):
    num_groups, depth, width = state.shape
    outs = []
    for d in range(depth):
        flat = segment.flat_segment_ids(gids, _bucket(values, d, width), width)
        outs.append(
            segment.seg_count(flat, num_groups * width, mask).reshape(
                num_groups, width
            )
        )
    return state + jnp.stack(outs, axis=1)


def merge(a, b):
    return a + b


def query(state, gids, values):
    """Estimated counts for (group, value) pairs: min over depth rows."""
    num_groups, depth, width = state.shape
    ests = []
    for d in range(depth):
        b = _bucket(values, d, width)
        ests.append(state[gids, d, b])
    return jnp.min(jnp.stack(ests, axis=0), axis=0)
