"""Count-min frequency sketch, vectorized over groups.

Net-new UDA (not in the reference — SURVEY.md §6): state is a dense
[num_groups, depth, width] int64 tensor; update adds per-depth bucket
counts; merge is elementwise add — cross-device merge is a single
`lax.psum`. Point queries take the min over depth rows (classic CM upper
bound).

Update strategy (r4 redesign): bucket pairs come from two native-u32
hashes (Kirsch–Mitzenmacher double hashing; the old u64 multiply path was
~5x dearer on TPU), and on TPU each depth's counts are computed
SORT-BASED — radix-sort the flat (group, bucket) ids, run-length count
via a reverse cumulative min of run-start indices, and scatter only the
unique run starts. The scalar unit then touches ~min(n, cells) elements
instead of n. CPU keeps the direct scatter.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu.ops import hashing, segment

DEFAULT_DEPTH = 4
DEFAULT_WIDTH = 8192  # eps ~ e/width ~ 3.3e-4 of total count


def init(num_groups: int, depth: int = DEFAULT_DEPTH, width: int = DEFAULT_WIDTH):
    if width & (width - 1):
        raise ValueError(
            f"count-min width must be a power of two (got {width}): "
            "bucketing masks with width-1"
        )
    return jnp.zeros((num_groups, depth, width), jnp.int64)


def _buckets(values, depth: int, width: int):
    """Kirsch–Mitzenmacher double hashing from two u32 hashes:
    bucket_d = (h1 + d*h2) & (width-1), all in native 32-bit VPU ops."""
    h1, h2 = hashing.hash32_pair(values, seed=1)
    return [
        ((h1 + jnp.uint32(d) * h2) & jnp.uint32(width - 1)).astype(jnp.int32)
        for d in range(depth)
    ]


def update(state, gids, values, mask=None):
    num_groups, depth, width = state.shape
    nseg = num_groups * width
    outs = []
    # The sort amortizes only on big blocks: below SORTED_MIN_ROWS the
    # direct scatter's ~7ns/element beats sort+run-length (r4 measured the
    # crossover between 2M and 8M rows).
    use_sorted = (
        segment.sorted_strategy()
        and nseg < (1 << 31) - 1
        and values.shape[0] >= segment.SORTED_MIN_ROWS
    )
    for bucket in _buckets(values, depth, width):
        flat = segment.flat_segment_ids(gids, bucket, width)
        if use_sorted:
            counts = segment.sorted_segment_counts(flat, nseg, mask)
        else:
            counts = segment.seg_count(flat, nseg, mask)
        outs.append(counts.reshape(num_groups, width))
    return state + jnp.stack(outs, axis=1)


def merge(a, b):
    return a + b


def query(state, gids, values):
    """Estimated counts for (group, value) pairs: min over depth rows."""
    num_groups, depth, width = state.shape
    ests = []
    for d, b in enumerate(_buckets(values, depth, width)):
        ests.append(state[gids, d, b])
    return jnp.min(jnp.stack(ests, axis=0), axis=0)
