"""Staging codec: lightweight per-column compression with DEVICE-side decode.

After r6–r8 overlapped pack/transfer/compute, the wire itself is the cold
path (bench config 1: 572s of 613s in ``stage_transfer`` through a
~100MB/s host→HBM tunnel). The classic column-store result applies
directly: lightweight compression pays off most when the decoder runs
where the data lands (Abadi et al., SIGMOD 2006) — so the host packs
ENCODED shards, the wire/DMA carries the compressed representation, and a
small jitted program expands it in HBM ahead of the fold. The decoded
blocks are BIT-IDENTICAL to what the uncompressed pack would have
transferred, so everything downstream (fold programs, staged-cache
entries, shared scans) is untouched.

Encoders (chosen per column at plan time, passthrough when none pays):

- **RLE** (``rle``): per device-shard run values + cumulative run ends.
  Decode = ``searchsorted(ends, iota, 'right')`` + gather — a pure
  VPU-gather expansion, bit-exact for every dtype including NaN floats
  (run detection compares BIT PATTERNS via an unsigned view, so NaN runs
  compress instead of fragmenting). Wins on sorted/low-churn columns:
  gids of time-ordered group keys, status codes, enum-ish ints.
- **Delta** (``delta``): per-shard base + frame-of-reference-shifted
  deltas in the narrowest unsigned dtype that fits the column's global
  delta range. Decode = masked ``cumsum`` in int64 (exact) + cast.
  Wins on timestamps and monotone-ish ids whose VALUE range defeats
  plain frame-of-reference narrowing (a 64M-row time_ column spans
  >2^31 ns so ships as raw int64, but its deltas are ~constant: 8x).
  A non-monotone "monotone guess" simply has a wide delta range and
  falls back to passthrough at plan time; a pathological window that
  still overflows raises ``CodecOverflow`` and ships raw (per window).
  r16: a column whose delta RANGE fits 4 bits (a fixed-cadence
  timestamp has ~1 distinct delta) ships sub-byte — two deltas packed
  per byte (``delta_dtype="nib"``), halving the dominant column's wire
  bytes again vs u8. Decode unpacks nibbles (shift+mask, VPU-cheap)
  then runs the identical exact-int64 cumsum.

Both operate on the PACKED representation (after frame-of-reference
narrowing / f32-for-sketch / int-dictionary encoding, before the
[D, nblk, B] reshape), so the codec composes with — never replaces —
the r5 narrowing stack, and decode output == packed block by
construction. Decode programs are cached per (kind, dtypes, geometry,
run capacity) with bucketed capacities, so they share executables and
.jax_cache entries exactly like the fold units they feed.

This module also owns the raw→plan block CONVERTERS used by
device-resident ingest (serving/resident.py): ring tables hold
raw-dtype blocks; a query's plan-dtype view (narrow/f32/intdict) is
computed ON DEVICE from them, trading cheap TPU cycles for zero wire
bytes on the hot tail.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class CodecOverflow(Exception):
    """A window's data exceeded the plan's encoded capacity (more runs
    than runs_cap, or a delta outside the planned range). Callers ship
    that window raw — correctness never depends on the plan's guess."""


# Unsigned views for bit-pattern run detection: floats compare by bits so
# NaN == NaN (payload-exact) and -0.0 != +0.0 — both are what a LOSSLESS
# codec needs (decode is a gather of the original bit patterns).
_BITVIEW = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def _bits(a: np.ndarray) -> np.ndarray:
    if a.dtype.kind == "f":
        return a.view(_BITVIEW[a.dtype.itemsize])
    return a


def bucket_cap(n: int) -> int:
    """Round an encoded capacity up to its signature bucket (same
    quarter-octave pow2-scaled buckets as staging.bucket_block_count),
    bounding decode-program shape variety to O(log) distinct
    capacities."""
    if n <= 8:
        return max(n, 1)
    step = 1 << ((n - 1).bit_length() - 3)
    return ((n + step - 1) // step) * step


@dataclasses.dataclass(frozen=True)
class CodecPlan:
    """Per-column encode/decode recipe, fixed across a staging's windows
    (stream) or for its single monolithic window."""

    kind: str  # "rle" | "delta"
    dtype: str  # decoded (packed block) dtype str
    d: int  # device shards per window
    shard_len: int  # nblk * b elements per shard
    runs_cap: int = 0  # rle: padded runs per shard (bucketed)
    delta_dtype: str = ""  # delta: encoded delta dtype str ("nib" = u4x2)
    delta_off: int = 0  # delta: frame-of-reference offset on deltas

    def wire_nbytes(self) -> int:
        """Encoded bytes per window (static; what the wire carries)."""
        if self.kind == "rle":
            per = np.dtype(self.dtype).itemsize + 4  # values + i32 ends
            return self.d * self.runs_cap * per
        if self.delta_dtype == "nib":
            # Two 4-bit deltas per byte (+base+rows per shard).
            return self.d * ((self.shard_len + 1) // 2 + 8 + 4)
        per = np.dtype(self.delta_dtype).itemsize
        return self.d * (self.shard_len * per + 8 + 4)  # deltas+base+rows

    def block_nbytes(self) -> int:
        """Decoded bytes per window (what lands in HBM)."""
        return self.d * self.shard_len * np.dtype(self.dtype).itemsize

    def sig(self) -> str:
        """Decode-program identity (offset/base ride as traced args, so
        every staging sharing kind+dtype+geometry shares one
        executable and one .jax_cache entry)."""
        if self.kind == "rle":
            return (
                f"rle:{self.dtype}:d{self.d}:l{self.shard_len}"
                f":r{self.runs_cap}"
            )
        return (
            f"delta:{self.dtype}:{self.delta_dtype}:d{self.d}"
            f":l{self.shard_len}"
        )


@dataclasses.dataclass
class CodecPayload:
    """One window's encoded column: the arrays the wire actually
    carries. ``arrays`` order matches the decoder's signature."""

    plan: CodecPlan
    arrays: tuple  # rle: (values, ends); delta: (bases, deltas, rows)

    @property
    def nbytes(self) -> int:
        return int(sum(a.nbytes for a in self.arrays))


# -- planning ----------------------------------------------------------------


def _shard_bounds(
    num_rows: int, window_rows: int, shard_len: int, d: int
) -> np.ndarray:
    """Start offsets of every (window, device-shard) slice of the row
    range, clipped to num_rows — the units encode operates on."""
    n_windows = max((num_rows + window_rows - 1) // window_rows, 1)
    starts = []
    for w in range(n_windows):
        base = w * window_rows
        for s in range(d):
            starts.append(min(base + s * shard_len, num_rows))
    return np.asarray(starts, np.int64)


def _max_runs_per_shard(arr: np.ndarray, starts: np.ndarray) -> int:
    """Largest run count any shard sees, from ONE pass over the column
    (change flags + add.reduceat per shard)."""
    if arr.size <= 1:
        return 1
    v = _bits(arr)
    chg = v[1:] != v[:-1]
    # Shard s covers [starts[s], starts[s+1]); runs <= changes in that
    # span + 1 (the span includes the shard's trailing boundary — a
    # cheap upper bound; runs_cap only needs to dominate).
    idx = np.minimum(starts, chg.size - 1)
    counts = np.add.reduceat(chg, idx).astype(np.int64)
    # reduceat quirk: a segment whose start equals the next start (an
    # empty/clipped shard) returns chg[idx] instead of 0 — zero it.
    width = np.diff(np.append(idx, chg.size))
    counts = np.where(width > 0, counts, 0)
    return int(counts.max()) + 1 if counts.size else 1


def _delta_range(arr: np.ndarray) -> tuple[int, int]:
    """(min, max) of consecutive diffs, chunked so the int64 temp stays
    bounded on gigarow columns."""
    lo, hi = 0, 0
    chunk = 1 << 24
    first = True
    for off in range(0, arr.size - 1, chunk):
        a = arr[off : min(off + chunk + 1, arr.size)].astype(np.int64)
        dd = np.diff(a)
        if dd.size == 0:
            continue
        dmin, dmax = int(dd.min()), int(dd.max())
        if first:
            lo, hi = dmin, dmax
            first = False
        else:
            lo, hi = min(lo, dmin), max(hi, dmax)
    return lo, hi


def _delta_dtype_for(rng: int) -> Optional[str]:
    """Narrowest encoded-delta representation for a frame-of-reference
    delta range: "nib" (two 4-bit deltas per byte, r16) below 16, else
    u8/u16 dtype strs. A range past 16 bits defeats delta entirely."""
    if rng <= 0xF:
        return "nib"
    if rng <= 0xFF:
        return np.dtype(np.uint8).str
    if rng <= 0xFFFF:
        return np.dtype(np.uint16).str
    return None


def plan_codec(
    arr: np.ndarray,
    block_dtype: np.dtype,
    d: int,
    nblk: int,
    b: int,
    window_rows: int,
    num_rows: int,
    min_ratio: float,
    affine: bool,
) -> Optional[CodecPlan]:
    """Pick the cheapest encoder for a column, or None (passthrough).

    ``arr`` is the RAW host column; stats that survive the pack
    transform are computed on it directly (run boundaries are invariant
    under the affine narrow / int-dict transforms, and diffs are
    invariant under affine shifts), so the full packed column never
    materializes at plan time. ``affine`` is True when the pack
    transform preserves diffs (raw / narrow), enabling delta;
    f32-cast and int-dict columns are RLE-only. A column whose best
    encoder saves less than ``min_ratio`` ships passthrough."""
    if arr.size == 0 or num_rows <= 0:
        return None
    block_dtype = np.dtype(block_dtype)
    shard_len = nblk * b
    block_bytes = block_dtype.itemsize * d * shard_len  # per window
    starts = _shard_bounds(num_rows, window_rows, shard_len, d)
    candidates: list[CodecPlan] = []
    # RLE: runs_cap = observed max + slack for the padding run and the
    # clip-to-n boundary; every later window is a slice the plan's pass
    # already covered, so encode can only see fewer runs.
    runs_cap = bucket_cap(
        min(_max_runs_per_shard(arr, starts) + 2, shard_len)
    )
    rle = CodecPlan(
        kind="rle",
        dtype=block_dtype.str,
        d=d,
        shard_len=shard_len,
        runs_cap=runs_cap,
    )
    if rle.wire_nbytes() * min_ratio <= block_bytes:
        candidates.append(rle)
    if affine and arr.dtype.kind in "iu" and arr.size > 1:
        lo, hi = _delta_range(arr)
        ddt = _delta_dtype_for(hi - lo)
        if ddt is not None:
            delta = CodecPlan(
                kind="delta",
                dtype=block_dtype.str,
                d=d,
                shard_len=shard_len,
                delta_dtype=ddt,
                delta_off=lo,
            )
            if delta.wire_nbytes() * min_ratio <= block_bytes:
                candidates.append(delta)
    if not candidates:
        return None
    return min(candidates, key=lambda p: p.wire_nbytes())


def plan_codec_local(
    packed: np.ndarray,
    d: int,
    nblk: int,
    b: int,
    rows: int,
    min_ratio: float,
) -> Optional[CodecPlan]:
    """Single-window plan from the PACKED (transformed, padded) flat
    array itself — the monolithic-staging and resident-ingest entry
    point, where cross-window stability is moot and exact stats are
    free."""
    shard_len = nblk * b
    return plan_codec(
        packed[: max(rows, 1)],
        packed.dtype,
        d,
        nblk,
        b,
        window_rows=d * shard_len,
        num_rows=max(rows, 1),
        min_ratio=min_ratio,
        affine=packed.dtype.kind in "iu",
    )


# -- host encode -------------------------------------------------------------


def encode_window(
    packed_flat: np.ndarray, plan: CodecPlan, rows: int
) -> CodecPayload:
    """Encode one window's packed flat array ([d * shard_len], padded
    with zeros past ``rows``) into its wire payload. Raises
    CodecOverflow when the window defeats the plan — the caller ships
    that window raw."""
    d, L = plan.d, plan.shard_len
    shards = packed_flat.reshape(d, L)
    if plan.kind == "rle":
        values = np.zeros((d, plan.runs_cap), dtype=packed_flat.dtype)
        ends = np.full((d, plan.runs_cap), L, dtype=np.int32)
        for s in range(d):
            v = shards[s]
            bitsv = _bits(v)
            chg = np.flatnonzero(bitsv[1:] != bitsv[:-1]) + 1
            if chg.size + 1 > plan.runs_cap:
                raise CodecOverflow(
                    f"{chg.size + 1} runs > cap {plan.runs_cap}"
                )
            starts = np.concatenate(([0], chg))
            values[s, : starts.size] = v[starts]
            ends[s, : starts.size] = np.append(chg, L).astype(np.int32)
        return CodecPayload(plan, (values, ends))
    # delta
    nib = plan.delta_dtype == "nib"
    ddt = np.dtype(np.uint8) if nib else np.dtype(plan.delta_dtype)
    dmax = 0xF if nib else (1 << (8 * ddt.itemsize)) - 1
    bases = np.zeros(d, np.int64)
    rows_v = np.clip(rows - np.arange(d) * L, 0, L).astype(np.int32)
    deltas = np.zeros((d, L), dtype=ddt)
    for s in range(d):
        r = int(rows_v[s])
        if r == 0:
            continue
        v = shards[s][:r].astype(np.int64)
        bases[s] = v[0]
        if r > 1:
            enc = np.diff(v) - plan.delta_off
            if enc.size and (
                int(enc.min()) < 0 or int(enc.max()) > dmax
            ):
                raise CodecOverflow("delta outside planned range")
            deltas[s, 1:r] = enc.astype(ddt)
    if nib:
        # Two 4-bit deltas per byte, even index in the low nibble. L is
        # padded to even below so the odd tail has a zero high nibble.
        half = (L + 1) // 2
        if L % 2:
            deltas = np.concatenate(
                [deltas, np.zeros((d, 1), np.uint8)], axis=1
            )
        deltas = (deltas[:, 0::2] | (deltas[:, 1::2] << 4))[:, :half]
    return CodecPayload(plan, (bases, deltas, rows_v))


# -- device decode -----------------------------------------------------------


@functools.lru_cache(maxsize=128)
def _decoder(mesh: Mesh, sig: str, nblk: int, b: int):
    """Jitted decode program per (mesh, plan signature, geometry).
    Payload inputs are device-sharded on the leading axis and every
    lane is device-local (vmap over shards, no collectives); the output
    is the [D, nblk, B] block the fold would have received from an
    uncompressed transfer, bit for bit."""
    axis_name = tuple(mesh.axis_names)  # dim0 over every mesh axis
    sharding = NamedSharding(mesh, P(axis_name))
    parts = sig.split(":")
    kind = parts[0]
    L = nblk * b
    if kind == "rle":
        vdtype = np.dtype(parts[1])
        R = int(parts[4][1:])

        def dec_rle(values, ends):
            iota = jnp.arange(L, dtype=jnp.int32)

            def one(v, e):
                j = jnp.searchsorted(e, iota, side="right")
                return v[jnp.minimum(j, R - 1)].reshape(nblk, b)

            return jax.vmap(one)(values, ends)

        return jax.jit(dec_rle, out_shardings=sharding)

    vdtype = np.dtype(parts[1])
    nib = parts[2] == "nib"

    def dec_delta(bases, deltas, rows, off):
        iota = jnp.arange(L, dtype=jnp.int32)

        def one(b0, dl, r):
            if nib:
                # Unpack two 4-bit deltas per byte (low nibble first).
                lo16 = dl & 0xF
                hi16 = dl >> 4
                dl = jnp.stack([lo16, hi16], axis=-1).reshape(-1)[:L]
            d64 = dl.astype(jnp.int64) + off
            d64 = jnp.where((iota > 0) & (iota < r), d64, 0)
            v = b0 + jnp.cumsum(d64)
            v = jnp.where(iota < r, v, 0)
            return v.astype(vdtype).reshape(nblk, b)

        return jax.vmap(one, in_axes=(0, 0, 0))(bases, deltas, rows)

    return jax.jit(dec_delta, out_shardings=sharding, static_argnums=())


def decoder(mesh: Mesh, plan: CodecPlan, nblk: int, b: int):
    """The jitted decode program for ``plan`` at this geometry. Call
    with ``put_payload(mesh, payload)``'s device args (delta appends
    the plan's offset as a traced scalar, so the executable is shared
    across offsets and tables)."""
    return _decoder(mesh, plan.sig(), nblk, b)


def put_payload(mesh: Mesh, payload: CodecPayload) -> list:
    """device_put a payload's host arrays for the decoder: arrays shard
    on the leading (device) axis — this is the only wire transfer the
    column pays — and the delta offset rides replicated."""
    axis_name = tuple(mesh.axis_names)  # dim0 over every mesh axis
    sharded = NamedSharding(mesh, P(axis_name))
    repl = NamedSharding(mesh, P())
    args = [jax.device_put(a, sharded) for a in payload.arrays]
    if payload.plan.kind == "delta":
        args.append(jax.device_put(np.int64(payload.plan.delta_off), repl))
    return args


def decode_avals(plan: CodecPlan, mesh: Mesh):
    """ShapeDtypeStructs of the decoder's args (for background AOT
    compilation on the staging worker)."""
    axis_name = tuple(mesh.axis_names)  # dim0 over every mesh axis
    sharding = NamedSharding(mesh, P(axis_name))
    repl = NamedSharding(mesh, P())
    d, L = plan.d, plan.shard_len
    if plan.kind == "rle":
        return (
            jax.ShapeDtypeStruct(
                (d, plan.runs_cap), np.dtype(plan.dtype), sharding=sharding
            ),
            jax.ShapeDtypeStruct(
                (d, plan.runs_cap), np.int32, sharding=sharding
            ),
        )
    nib = plan.delta_dtype == "nib"
    return (
        jax.ShapeDtypeStruct((d,), np.int64, sharding=sharding),
        jax.ShapeDtypeStruct(
            (d, (L + 1) // 2 if nib else L),
            np.uint8 if nib else np.dtype(plan.delta_dtype),
            sharding=sharding,
        ),
        jax.ShapeDtypeStruct((d,), np.int32, sharding=sharding),
        jax.ShapeDtypeStruct((), np.int64, sharding=repl),
    )


# -- raw→plan converters (device-resident ingest) ----------------------------
#
# Ring tables (serving/resident.py) hold RAW-dtype blocks — the pack
# recipe (narrow offsets, f32 cast, int-dict codes) is query/staging
# specific and can't be fixed at ingest time. These converters compute
# the plan-dtype view ON DEVICE, reproducing pack_stream_window's host
# transform bit for bit: identity, (x - off).astype(dt),
# x.astype(f32), and min(searchsorted(lut, x), C-1).astype(dt).


@functools.lru_cache(maxsize=128)
def _converter(
    mesh: Mesh,
    kind: str,
    src_dtype: str,
    dst_dtype: str,
    nblk: int,
    b: int,
    lut_len: int,
):
    axis_name = tuple(mesh.axis_names)  # dim0 over every mesh axis
    sharding = NamedSharding(mesh, P(axis_name))
    dst = np.dtype(dst_dtype)

    if kind == "raw":
        fn = lambda x: x.astype(dst)
    elif kind == "f32":
        fn = lambda x: x.astype(jnp.float32)
    elif kind == "narrow":

        def fn(x, off):
            return (x.astype(jnp.int64) - off).astype(dst)

    elif kind == "intdict":

        def fn(x, lut):
            c = jnp.searchsorted(lut, x)
            return jnp.minimum(c, lut_len - 1).astype(dst)

    else:  # pragma: no cover - plan kinds are closed
        raise ValueError(f"unknown convert kind {kind!r}")
    return jax.jit(fn, out_shardings=sharding)


def convert_block(mesh: Mesh, col_plan, raw_block, int_dtype=None):
    """Apply a StreamPlan col_plan ("raw"/"f32"/"narrow"/"intdict") to a
    raw-dtype device block, returning the plan-dtype block the fold
    expects. ``raw_block`` is [D, nblk, B]; scalars/LUTs ride as traced
    args so executables are shared across offsets and tables."""
    kind, info = col_plan
    d, nblk, b = raw_block.shape
    if kind == "raw":
        dst = np.dtype(raw_block.dtype) if int_dtype is None else int_dtype
        fn = _converter(
            mesh, "raw", str(raw_block.dtype), np.dtype(dst).str, nblk, b, 0
        )
        return fn(raw_block)
    if kind == "f32":
        fn = _converter(
            mesh, "f32", str(raw_block.dtype), "f4", nblk, b, 0
        )
        return fn(raw_block)
    if kind == "narrow":
        dt, off = info
        fn = _converter(
            mesh, "narrow", str(raw_block.dtype), np.dtype(dt).str, nblk, b, 0
        )
        return fn(raw_block, np.int64(off))
    if kind == "intdict":
        lut, dt = info
        lut = np.asarray(lut)
        fn = _converter(
            mesh,
            "intdict",
            str(raw_block.dtype),
            np.dtype(dt).str,
            nblk,
            b,
            int(lut.shape[0]),
        )
        return fn(raw_block, lut.astype(np.int64))
    raise ValueError(f"unknown col plan kind {kind!r}")
