"""Log-binned per-group histogram sketch (DDSketch-style).

TPU-native quantile path: fixed [num_groups, NBINS] int64 counts; update is
one masked segment-sum over flat (group, bin) ids; cross-shard merge is a
plain add — i.e. it rides `lax.psum` over ICI directly, which is why this is
the default device quantile sketch (the t-digest in pixie_tpu.ops.tdigest is
the parity implementation whose merge needs a sort).

Bins are logarithmic with ratio ``gamma``: bin(v) = floor(log(v)/log(gamma))
clamped to [0, nbins), giving relative-error quantiles of
(gamma-1)/(gamma+1). Values <= min_value land in bin 0; an extra overflow
bin catches the tail.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from pixie_tpu.ops import segment


@dataclasses.dataclass(frozen=True)
class LogHistogramSpec:
    nbins: int = 1024
    min_value: float = 1.0  # ns granularity for latency telemetry
    max_value: float = 1e12

    @property
    def gamma(self) -> float:
        return math.exp(math.log(self.max_value / self.min_value) / (self.nbins - 2))

    @property
    def relative_error(self) -> float:
        g = self.gamma
        return (g - 1) / (g + 1)


DEFAULT_SPEC = LogHistogramSpec()


def init(num_groups: int, spec: LogHistogramSpec = DEFAULT_SPEC):
    return jnp.zeros((num_groups, spec.nbins), jnp.int64)


def bin_index(values, spec: LogHistogramSpec = DEFAULT_SPEC):
    vf = values.astype(jnp.float32)
    v = jnp.maximum(vf, spec.min_value)
    idx = jnp.floor(
        jnp.log(v / spec.min_value) / math.log(spec.gamma)
    ).astype(jnp.int32) + 1
    idx = jnp.where(vf <= spec.min_value, 0, idx)
    return jnp.clip(idx, 0, spec.nbins - 1)


def update(state, gids, values, mask=None, spec: LogHistogramSpec = DEFAULT_SPEC):
    num_groups, nbins = state.shape
    bins = bin_index(values, spec)
    if segment.matmul_strategy(num_groups):
        # Two-level one-hot matmul: [n,G].T @ [n,NBINS] on the MXU — ~2.7x
        # the scatter path on v5e (bf16 one-hots are exact 0/1; f32
        # accumulation exact below 2^24 rows per call, blocks are 2^17).
        ohg = jax.nn.one_hot(gids, num_groups, dtype=jnp.bfloat16)
        if mask is not None:
            ohg = ohg * mask[:, None].astype(jnp.bfloat16)
        ohb = jax.nn.one_hot(bins, nbins, dtype=jnp.bfloat16)
        counts = jnp.matmul(
            ohg.T, ohb, preferred_element_type=jnp.float32
        )
        return state + jnp.round(counts).astype(state.dtype)
    flat = segment.flat_segment_ids(gids, bins, nbins)
    counts = segment.seg_count(flat, num_groups * nbins, mask)
    return state + counts.reshape(num_groups, nbins)


def merge(a, b):
    return a + b


def quantile_values(state, qs, spec: LogHistogramSpec = DEFAULT_SPEC):
    """Per-group quantile estimates: [num_groups, len(qs)] float64.

    Uses the geometric midpoint of the selected bin — the standard DDSketch
    estimator with relative error <= spec.relative_error.
    """
    counts = state.astype(jnp.float64)
    total = counts.sum(axis=1, keepdims=True)
    cum = jnp.cumsum(counts, axis=1)
    qs_arr = jnp.asarray(qs, jnp.float64)
    # rank per (group, q): smallest bin with cum >= q * total
    target = qs_arr[None, :] * total  # [G, Q]
    # searchsorted per group via comparison matrix (nbins is static & small)
    reached = cum[:, :, None] >= jnp.maximum(target[:, None, :], 1e-9)  # [G,B,Q]
    bin_idx = jnp.argmax(reached, axis=1)  # first True along bins
    # geometric midpoint of bin i (i>=1): min * gamma^(i-1) * sqrt(gamma)
    g = spec.gamma
    vals = spec.min_value * jnp.power(g, jnp.maximum(bin_idx - 1, 0)) * math.sqrt(g)
    vals = jnp.where(bin_idx == 0, spec.min_value, vals)
    return jnp.where(total > 0, vals, 0.0)
