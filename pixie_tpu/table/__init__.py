"""Columnar storage: RowBatch dataflow unit + hot/cold Table + TableStore.

Ref: src/table_store/ (Table, TableStore, RowBatch, schema). TPU-first
re-design: STRING columns are dictionary-encoded once at write time so the
query path only ever sees int32 codes (device-stageable); numeric columns are
contiguous numpy on host, staged to HBM in fixed-size padded blocks.
"""

from pixie_tpu.table.column import DictColumn, StringDictionary  # noqa: F401
from pixie_tpu.table.row_batch import RowBatch  # noqa: F401
from pixie_tpu.table.table import Table, Cursor  # noqa: F401
from pixie_tpu.table.table_store import TableStore  # noqa: F401
