"""RowBatch — the unit of dataflow between exec operators.

Ref: src/table_store/schema/row_batch.h:40 (vector of Arrow arrays +
RowDescriptor + eow/eos flags, proto (de)serialization for gRPC transfer).
Ours is numpy-columnar with dictionary-encoded strings; (de)serialization for
the inter-host data plane lives in ``to_bytes``/``from_bytes``.
"""

from __future__ import annotations

import io
from typing import Sequence

import numpy as np

from pixie_tpu.table.column import DictColumn, StringDictionary, concat_dict_columns
from pixie_tpu.types import DataType, Relation
from pixie_tpu.types.dtypes import host_dtype


ColumnData = "np.ndarray | DictColumn"


class RowBatch:
    """Columnar batch: relation + per-column data + end-of-window/stream flags.

    eow/eos semantics follow the reference (row_batch.h:40): ``eow`` marks the
    end of a streaming window (blocking aggregates emit on it), ``eos`` marks
    the end of the stream.
    """

    __slots__ = ("relation", "columns", "eow", "eos")

    def __init__(
        self,
        relation: Relation,
        columns: Sequence[ColumnData],
        eow: bool = False,
        eos: bool = False,
    ):
        if len(columns) != relation.num_columns():
            raise ValueError(
                f"{len(columns)} columns for relation with "
                f"{relation.num_columns()} fields"
            )
        self.relation = relation
        self.columns = list(columns)
        self.eow = eow
        self.eos = eos
        n = self.num_rows
        for i, c in enumerate(self.columns):
            if len(c) != n:
                raise ValueError(
                    f"column {relation.col(i).name!r} has {len(c)} rows, expected {n}"
                )

    # -- construction ------------------------------------------------------
    @classmethod
    def from_pydict(
        cls,
        relation: Relation,
        data: dict,
        dictionaries: dict[str, StringDictionary] | None = None,
        eow: bool = False,
        eos: bool = False,
    ) -> "RowBatch":
        """Build from a name->values dict; strings are dict-encoded."""
        cols: list[ColumnData] = []
        for schema in relation:
            values = data[schema.name]
            if schema.data_type == DataType.STRING and not isinstance(
                values, DictColumn
            ):
                d = (dictionaries or {}).get(schema.name) or StringDictionary()
                cols.append(DictColumn(d.encode(values), d))
            elif isinstance(values, DictColumn):
                cols.append(values)
            else:
                cols.append(
                    np.asarray(values, dtype=host_dtype(schema.data_type))
                )
        return cls(relation, cols, eow=eow, eos=eos)

    @classmethod
    def with_zero_rows(cls, relation: Relation, eow=False, eos=False) -> "RowBatch":
        """Ref: RowBatch::WithZeroRows — used to propagate bare eow/eos."""
        cols: list[ColumnData] = []
        for schema in relation:
            if schema.data_type == DataType.STRING:
                cols.append(
                    DictColumn(np.empty(0, np.int32), StringDictionary())
                )
            else:
                cols.append(np.empty(0, host_dtype(schema.data_type)))
        return cls(relation, cols, eow=eow, eos=eos)

    # -- accessors ---------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def col(self, name_or_idx) -> ColumnData:
        if isinstance(name_or_idx, str):
            return self.columns[self.relation.col_idx(name_or_idx)]
        return self.columns[name_or_idx]

    def num_bytes(self) -> int:
        total = 0
        for c in self.columns:
            arr = c.codes if isinstance(c, DictColumn) else c
            total += arr.nbytes if arr.dtype != object else sum(
                len(str(v)) for v in arr
            )
        return total

    # -- transforms --------------------------------------------------------
    def select(self, names: list[str]) -> "RowBatch":
        rel = self.relation.select(names)
        return RowBatch(
            rel, [self.col(n) for n in names], eow=self.eow, eos=self.eos
        )

    def take(self, indices) -> "RowBatch":
        cols = [
            c.take(indices) if isinstance(c, DictColumn) else c[indices]
            for c in self.columns
        ]
        return RowBatch(self.relation, cols, eow=self.eow, eos=self.eos)

    def slice(self, start: int, stop: int) -> "RowBatch":
        cols = [
            c.slice(start, stop) if isinstance(c, DictColumn) else c[start:stop]
            for c in self.columns
        ]
        return RowBatch(self.relation, cols, eow=self.eow, eos=self.eos)

    def with_flags(self, eow: bool, eos: bool) -> "RowBatch":
        return RowBatch(self.relation, self.columns, eow=eow, eos=eos)

    @classmethod
    def concat(cls, batches: list["RowBatch"]) -> "RowBatch":
        assert batches
        rel = batches[0].relation
        cols: list[ColumnData] = []
        for i in range(rel.num_columns()):
            parts = [b.columns[i] for b in batches]
            if isinstance(parts[0], DictColumn):
                cols.append(concat_dict_columns(parts))
            else:
                cols.append(np.concatenate(parts))
        return cls(rel, cols, eow=batches[-1].eow, eos=batches[-1].eos)

    # -- output ------------------------------------------------------------
    def to_pydict(self, decode_strings: bool = True) -> dict:
        out = {}
        for schema, c in zip(self.relation, self.columns):
            if isinstance(c, DictColumn):
                out[schema.name] = (
                    c.decode().tolist() if decode_strings else c.codes.tolist()
                )
            else:
                out[schema.name] = c.tolist()
        return out

    def to_pandas(self):  # pragma: no cover - convenience
        import pandas as pd

        return pd.DataFrame(self.to_pydict())

    # -- wire format (inter-host data plane; ref: row_batch proto serde) ----
    def to_bytes(self) -> bytes:
        """Serialize for DCN transfer. Strings ship as their decoded values so
        the receiving host can re-encode into its own dictionaries."""
        buf = io.BytesIO()
        arrays = {}
        meta = {"eow": self.eow, "eos": self.eos, "relation": self.relation.to_dict()}
        for i, (schema, c) in enumerate(zip(self.relation, self.columns)):
            if isinstance(c, DictColumn):
                arrays[f"c{i}"] = np.asarray(c.decode().tolist(), dtype="U")
            else:
                arrays[f"c{i}"] = c
        np.savez_compressed(buf, __meta__=np.frombuffer(
            repr(meta).encode(), dtype=np.uint8
        ), **arrays)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "RowBatch":
        import ast

        with np.load(io.BytesIO(data), allow_pickle=False) as npz:
            meta = ast.literal_eval(bytes(npz["__meta__"]).decode())
            rel = Relation.from_dict(meta["relation"])
            cols: list[ColumnData] = []
            for i, schema in enumerate(rel):
                arr = npz[f"c{i}"]
                if schema.data_type == DataType.STRING:
                    d = StringDictionary()
                    cols.append(DictColumn(d.encode(arr.astype(object)), d))
                else:
                    cols.append(arr.astype(host_dtype(schema.data_type)))
            return cls(rel, cols, eow=bool(meta["eow"]), eos=bool(meta["eos"]))

    def __reduce__(self):
        # Pickling rides the explicit wire format (to_bytes/from_bytes), so
        # cross-process transports move bytes, not live object graphs.
        return (RowBatch.from_bytes, (self.to_bytes(),))

    def __repr__(self) -> str:
        flags = (" eow" if self.eow else "") + (" eos" if self.eos else "")
        return f"RowBatch({self.num_rows} rows, {self.relation}{flags})"
