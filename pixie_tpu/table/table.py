"""Hot/cold in-memory table with ring-buffer expiry and time-sliced cursors.

Ref: src/table_store/table/table.h:51,72-160 — Table keeps a write-side "hot"
partition and a compacted "cold" partition, bounded by a size limit (oldest
data expires first); Cursors are time+row-id indexed and survive concurrent
compaction/expiry because row ids are global and monotonic
(internal/store_with_row_accounting.h).

TPU-first twist: compaction coalesces hot batches into cold batches of
``compacted_rows`` rows — chosen to match the exec engine's device block size
so cold reads stage to HBM with zero re-chunking.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

import numpy as np

from pixie_tpu.table.column import DictColumn, StringDictionary
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.types import DataType, Relation

DEFAULT_SIZE_LIMIT = 64 * 1024 * 1024  # ref: FLAGS_table_store_table_size_limit
DEFAULT_COMPACTED_ROWS = 1 << 17  # 131072 rows/cold batch == device block size
TIME_COLUMN = "time_"


@dataclasses.dataclass
class TableStats:
    """Ref: TableStats (table.h:58)."""

    batches_added: int = 0
    batches_expired: int = 0
    compacted_batches: int = 0
    bytes_added: int = 0
    num_batches: int = 0
    num_rows: int = 0
    bytes: int = 0
    max_table_size: int = 0
    min_time: int = -1


@dataclasses.dataclass
class _Segment:
    first_row_id: int
    batch: RowBatch
    min_time: int
    max_time: int
    hot: bool

    @property
    def num_rows(self) -> int:
        return self.batch.num_rows

    @property
    def end_row_id(self) -> int:
        return self.first_row_id + self.num_rows


class Table:
    """Append-only (write side) columnar table with bounded memory."""

    def __init__(
        self,
        relation: Relation,
        size_limit: int = DEFAULT_SIZE_LIMIT,
        compacted_rows: int = DEFAULT_COMPACTED_ROWS,
        name: str = "",
    ):
        self.name = name
        self.relation = relation
        self.size_limit = size_limit
        self.compacted_rows = compacted_rows
        self._lock = threading.RLock()
        self._metric_bytes = None  # bound lazily: name may be set later
        self._metric_batches = None
        self._segments: list[_Segment] = []
        self._next_row_id = 0
        self._bytes = 0
        self._stats = TableStats(max_table_size=size_limit)
        self._stopped = False  # stream end marker for streaming cursors
        # Table-level string dictionaries, shared by every batch written so
        # codes are comparable across the whole table (segment-id property).
        self.dictionaries: dict[str, StringDictionary] = {
            c.name: StringDictionary()
            for c in relation
            if c.data_type == DataType.STRING
        }
        self._time_idx = (
            relation.col_idx(TIME_COLUMN) if relation.has_column(TIME_COLUMN) else -1
        )
        # Append listeners (r13): fn(first_row_id, batch) fired inside
        # the write lock AFTER dictionary adoption, so device-resident
        # ingest rings see every row exactly once, in row-id order, with
        # table-dictionary codes. Keep listeners cheap-ish: they run on
        # the writer's thread.
        self._append_listeners: list = []

    # -- write side --------------------------------------------------------
    def write(self, batch: RowBatch) -> None:
        """Append a hot batch (ref: Table::WriteRowBatch / TransferRecordBatch)."""
        if batch.relation.col_names() != self.relation.col_names():
            raise ValueError(
                f"batch relation {batch.relation} != table relation {self.relation}"
            )
        batch = self._adopt_dictionaries(batch)
        with self._lock:
            if self._time_idx >= 0 and batch.num_rows:
                t = np.asarray(batch.columns[self._time_idx])
                mn, mx = int(t.min()), int(t.max())
            else:
                mn = mx = self._segments[-1].max_time if self._segments else 0
            seg = _Segment(self._next_row_id, batch, mn, mx, hot=True)
            self._segments.append(seg)
            first_row_id = self._next_row_id
            self._next_row_id += batch.num_rows
            for fn in self._append_listeners:
                try:
                    fn(first_row_id, batch)
                except Exception:
                    import logging

                    logging.getLogger("pixie_tpu.table").exception(
                        "append listener failed (ignored)"
                    )
            nbytes = batch.num_bytes()
            self._bytes += nbytes
            self._stats.batches_added += 1
            self._stats.bytes_added += nbytes
            self._expire_locked()
            if self.name:  # occupancy gauges (ref: table_metrics.h)
                if self._metric_bytes is None:
                    from pixie_tpu.utils import metrics_registry

                    m = metrics_registry()
                    self._metric_bytes = m.gauge(
                        "table_bytes", "Resident bytes per table."
                    ).labels(table=self.name)
                    self._metric_batches = m.gauge(
                        "table_batches", "Resident batches per table."
                    ).labels(table=self.name)
                self._metric_bytes.set(self._bytes)
                self._metric_batches.set(len(self._segments))

    def write_pydict(self, data: dict, eow=False, eos=False) -> None:
        self.write(
            RowBatch.from_pydict(
                self.relation, data, dictionaries=self.dictionaries, eow=eow, eos=eos
            )
        )

    def stop(self) -> None:
        """Mark the stream ended (streaming cursors will see eos)."""
        with self._lock:
            self._stopped = True

    def add_append_listener(self, fn) -> None:
        """Register fn(first_row_id, batch), fired under the write lock
        after every append (post dictionary adoption). The r13
        device-resident ingest hook."""
        with self._lock:
            self._append_listeners.append(fn)

    def remove_append_listener(self, fn) -> None:
        with self._lock:
            try:
                self._append_listeners.remove(fn)
            except ValueError:
                pass

    def _adopt_dictionaries(self, batch: RowBatch) -> RowBatch:
        """Re-encode any foreign-dictionary string columns into table dicts."""
        cols = []
        changed = False
        for schema, col in zip(batch.relation, batch.columns):
            if isinstance(col, DictColumn):
                table_dict = self.dictionaries[schema.name]
                if col.dictionary is not table_dict:
                    cols.append(DictColumn(table_dict.encode(col.decode()), table_dict))
                    changed = True
                    continue
            cols.append(col)
        if not changed:
            return batch
        return RowBatch(batch.relation, cols, eow=batch.eow, eos=batch.eos)

    # -- compaction / expiry ----------------------------------------------
    def compact(self) -> int:
        """Coalesce hot batches into cold batches of ``compacted_rows`` rows.

        Ref: Table::CompactHotToCold (kMaxBatchesPerCompactionCall,
        internal/arrow_array_compactor.*). Returns number of cold batches
        produced. Called periodically by the store's compaction thread or
        inline by tests.
        """
        with self._lock:
            hot = [s for s in self._segments if s.hot]
            if not hot:
                return 0
            hot_rows = sum(s.num_rows for s in hot)
            # Leave a partial tail hot unless the table is stopped.
            n_cold_rows = (
                hot_rows if self._stopped else (hot_rows // self.compacted_rows)
                * self.compacted_rows
            )
            if n_cold_rows == 0:
                return 0
            merged = RowBatch.concat([s.batch for s in hot])
            first_id = hot[0].first_row_id
            cold_part = merged.slice(0, n_cold_rows)
            produced = []
            for off in range(0, n_cold_rows, self.compacted_rows):
                chunk = cold_part.slice(off, min(off + self.compacted_rows, n_cold_rows))
                t = (
                    np.asarray(chunk.columns[self._time_idx])
                    if self._time_idx >= 0 and chunk.num_rows
                    else None
                )
                produced.append(
                    _Segment(
                        first_id + off,
                        chunk,
                        int(t.min()) if t is not None else 0,
                        int(t.max()) if t is not None else 0,
                        hot=False,
                    )
                )
            tail_segments = []
            if n_cold_rows < hot_rows:
                tail = merged.slice(n_cold_rows, hot_rows)
                t = (
                    np.asarray(tail.columns[self._time_idx])
                    if self._time_idx >= 0 and tail.num_rows
                    else None
                )
                tail_segments.append(
                    _Segment(
                        first_id + n_cold_rows,
                        tail,
                        int(t.min()) if t is not None else 0,
                        int(t.max()) if t is not None else 0,
                        hot=True,
                    )
                )
            cold_prefix = [s for s in self._segments if not s.hot]
            self._segments = cold_prefix + produced + tail_segments
            self._stats.compacted_batches += len(produced)
            return len(produced)

    def _expire_locked(self) -> None:
        while self._bytes > self.size_limit and len(self._segments) > 1:
            seg = self._segments.pop(0)
            self._bytes -= seg.batch.num_bytes()
            self._stats.batches_expired += 1

    # -- read side ---------------------------------------------------------
    def min_row_id(self) -> int:
        with self._lock:
            return self._segments[0].first_row_id if self._segments else 0

    def end_row_id(self) -> int:
        with self._lock:
            return self._next_row_id

    @property
    def stopped(self) -> bool:
        return self._stopped

    def stats(self) -> TableStats:
        with self._lock:
            s = dataclasses.replace(self._stats)
            s.num_batches = len(self._segments)
            s.num_rows = sum(seg.num_rows for seg in self._segments)
            s.bytes = self._bytes
            s.min_time = self._segments[0].min_time if self._segments else -1
            return s

    def time_bounds(self) -> tuple[Optional[int], Optional[int]]:
        """(min, max) time currently resident, or (None, None) if empty."""
        with self._lock:
            if not self._segments:
                return None, None
            return self._segments[0].min_time, self._segments[-1].max_time

    def cursor(
        self,
        start_time: Optional[int] = None,
        stop_time: Optional[int] = None,
        streaming: bool = False,
    ) -> "Cursor":
        return Cursor(self, start_time, stop_time, streaming)

    def _read_from(
        self, row_id: int, max_rows: int, start_time, stop_time
    ) -> tuple[Optional[RowBatch], int]:
        """Return (batch, next_row_id). Batch is None if nothing available yet."""
        with self._lock:
            for seg in self._segments:
                if seg.end_row_id <= row_id:
                    continue
                # Time-slice pruning on segment [min,max] bounds.
                if start_time is not None and seg.max_time < start_time:
                    row_id = seg.end_row_id
                    continue
                if stop_time is not None and seg.min_time > stop_time:
                    return None, row_id  # telemetry is time-ordered; done
                lo = max(0, row_id - seg.first_row_id)
                hi = min(seg.num_rows, lo + max_rows)
                chunk = seg.batch.slice(lo, hi)
                next_id = seg.first_row_id + hi
                if self._time_idx >= 0 and (
                    start_time is not None or stop_time is not None
                ):
                    t = np.asarray(chunk.columns[self._time_idx])
                    mask = np.ones(len(t), dtype=bool)
                    if start_time is not None:
                        mask &= t >= start_time
                    if stop_time is not None:
                        mask &= t <= stop_time
                    if not mask.all():
                        chunk = chunk.take(np.nonzero(mask)[0])
                return chunk, next_id
            return None, max(row_id, self._next_row_id)


class Cursor:
    """Time+row-id indexed iterator; survives concurrent compaction/expiry.

    Ref: Table::Cursor (table.h:127-160). If data the cursor points at has
    been expired from the ring buffer, the cursor silently skips forward (the
    reference logs a data-loss counter; we track ``rows_skipped``).
    """

    def __init__(self, table: Table, start_time, stop_time, streaming: bool):
        self.table = table
        self.start_time = start_time
        self.stop_time = stop_time
        self.streaming = streaming
        self._row_id = table.min_row_id()
        self.rows_skipped = 0
        self._done = False

    def done(self) -> bool:
        if self._done:
            return True
        if self.streaming and not self.table.stopped:
            return False
        return self._row_id >= self.table.end_row_id()

    def next_batch(self, max_rows: int = DEFAULT_COMPACTED_ROWS) -> Optional[RowBatch]:
        """Next row batch, or None if no data is currently available."""
        if self._done:
            return None
        min_id = self.table.min_row_id()
        if self._row_id < min_id:
            self.rows_skipped += min_id - self._row_id
            self._row_id = min_id
        batch, next_id = self.table._read_from(
            self._row_id, max_rows, self.start_time, self.stop_time
        )
        advanced = next_id > self._row_id
        self._row_id = next_id
        if batch is None and not advanced:
            if self.stop_time is not None and not self.streaming:
                self._done = True
            return None
        return batch
