"""Column representations.

Ref: src/shared/types/column_wrapper.h:49 (ColumnWrapper) — but where the
reference keeps strings as Arrow string arrays and hashes them row-at-a-time
in the engine, we dictionary-encode at ingest (write-side, off the query
critical path) so group-by keys and equality filters on strings become int32
ops on device. ``StringDictionary`` is append-only: codes are dense and stable
for the lifetime of a table, which makes them directly usable as segment ids
in TPU segment reductions (pixie_tpu.ops.segment).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

# Optional native fast path (pixie_tpu/native): C++ dictionary encoder.
try:  # pragma: no cover - exercised when the native lib is built
    from pixie_tpu.native import host_runtime as _native
except Exception:  # pragma: no cover
    _native = None


class StringDictionary:
    """Append-only string<->int32 dictionary.

    Thread-safe for concurrent encode (ingest) + read (query): the values list
    only ever grows, and lookups take the lock only on miss.
    """

    __slots__ = ("_values", "_index", "_lock", "_hashes", "_has_nul")

    def __init__(self, values: list[str] | None = None):
        self._values: list[str] = list(values) if values else []
        self._index: dict[str, int] = {v: i for i, v in enumerate(self._values)}
        self._lock = threading.Lock()
        self._hashes: np.ndarray = np.empty(0, dtype=np.uint64)
        # numpy's fixed-width U layout drops trailing NULs; once any such
        # value enters the dictionary, the native fast path would alias its
        # prefix — route around it permanently.
        self._has_nul = any("\x00" in v for v in self._values)

    def __len__(self) -> int:
        return len(self._values)

    def get_code(self, value: str) -> int:
        """Code for value, adding it if unseen."""
        code = self._index.get(value)
        if code is not None:
            return code
        with self._lock:
            code = self._index.get(value)
            if code is None:
                code = len(self._values)
                if "\x00" in value:
                    self._has_nul = True
                self._values.append(value)
                self._index[value] = code
            return code

    def lookup(self, value: str) -> int:
        """Code for value, -1 if unseen (used by equality filters on strings)."""
        return self._index.get(value, -1)

    def encode(self, values) -> np.ndarray:
        """Vectorized encode of an array/sequence of strings -> int32 codes."""
        arr = np.asarray(values, dtype=object)
        if _native is not None and len(arr) >= 1024 and not self._has_nul:
            # numpy's fixed-width U layout cannot represent trailing NULs;
            # such values (rare in telemetry) take the object-array path so
            # encode semantics never depend on batch size.
            u = arr.astype("U")
            total = int(np.fromiter(map(len, arr), np.int64, len(arr)).sum())
            u_ok = total == int(np.char.str_len(u).sum())
            if u_ok:
                # Native O(n) hash-map pass (the reference's write-side C++
                # analogue); appends unseen values under the lock so codes
                # stay dense + stable. _has_nul re-checked under the lock:
                # a concurrent get_code() may have admitted a NUL value
                # after the unlocked check above.
                with self._lock:
                    if self._has_nul:
                        u_ok = False
                    else:
                        codes, new_values = _native.encode_with_dict(
                            arr, self._values, u=u
                        )
                        for v in new_values:
                            # Append BEFORE indexing: lock-free readers
                            # must never see a code without its value.
                            self._values.append(v)
                            self._index[v] = len(self._values) - 1
                if u_ok:
                    return codes
        # Encode the unique values only, then broadcast back: telemetry string
        # columns (service/pod names, methods, paths) are extremely low-
        # cardinality relative to row count.
        uniq, inverse = np.unique(arr, return_inverse=True)
        uniq_codes = np.fromiter(
            (self.get_code(v) for v in uniq), dtype=np.int32, count=len(uniq)
        )
        return uniq_codes[inverse].astype(np.int32, copy=False)

    def decode(self, codes: np.ndarray) -> np.ndarray:
        values = np.asarray(self._values, dtype=object)
        out = np.empty(len(codes), dtype=object)
        valid = (codes >= 0) & (codes < len(values))
        out[valid] = values[codes[valid]]
        out[~valid] = ""
        return out

    def values(self) -> list[str]:
        return list(self._values)

    def content_hashes(self) -> np.ndarray:
        """Stable uint64 content hash per dictionary value (FNV-1a over
        utf-8), incrementally extended as the dictionary grows.

        Gathered through codes, this gives UDAs a dictionary-independent
        view of string identity — two agents (or two tables in a union)
        that encode the same string under different codes still agg into
        the same sketch bucket (ref: the reference hashes the string value
        itself via RowTuple/absl hash, src/carnot/exec/row_tuple.h)."""
        n = len(self._values)
        if len(self._hashes) < n:
            with self._lock:
                m = len(self._hashes)
                if m < n:
                    fresh = self._values[m:n]
                    if _native is not None:
                        new = _native.fnv1a64_batch(fresh)
                    else:
                        new = np.array(
                            [_fnv1a64(v) for v in fresh], dtype=np.uint64
                        )
                    self._hashes = np.concatenate([self._hashes, new])
        return self._hashes


def _fnv1a64(s: str) -> np.uint64:
    h = 0xCBF29CE484222325
    for b in s.encode("utf-8"):
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return np.uint64(h)


@dataclass
class DictColumn:
    """A dictionary-encoded string column: int32 codes + shared dictionary."""

    codes: np.ndarray  # int32[n]
    dictionary: StringDictionary

    def __post_init__(self):
        self.codes = np.asarray(self.codes, dtype=np.int32)

    def __len__(self) -> int:
        return len(self.codes)

    def decode(self) -> np.ndarray:
        return self.dictionary.decode(self.codes)

    def take(self, indices) -> "DictColumn":
        return DictColumn(self.codes[indices], self.dictionary)

    def slice(self, start: int, stop: int) -> "DictColumn":
        return DictColumn(self.codes[start:stop], self.dictionary)


def concat_dict_columns(cols: list[DictColumn]) -> DictColumn:
    dicts = {id(c.dictionary) for c in cols}
    if len(dicts) != 1:
        # Re-encode into the first column's dictionary (rare: cross-table
        # unions). Codes are remapped through the string values.
        base = cols[0].dictionary
        parts = [cols[0].codes]
        for c in cols[1:]:
            parts.append(base.encode(c.decode()))
        return DictColumn(np.concatenate(parts), base)
    return DictColumn(np.concatenate([c.codes for c in cols]), cols[0].dictionary)
