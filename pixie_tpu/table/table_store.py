"""TableStore: name/id -> Table registry with tablet support.

Ref: src/table_store/table/table_store.h:79 — maps table name and table id to
Table objects, with optional per-tablet addressing (tablet partitioning is the
reference's key-sharding mechanism; on TPU the analogous sharding happens at
the device-mesh layer, but tablets are kept for ingest-side partitioning).
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional

from pixie_tpu.table.table import Table
from pixie_tpu.types import Relation

DEFAULT_TABLET = ""


class TableStore:
    def __init__(self):
        self._lock = threading.RLock()
        # (name, tablet_id) -> Table
        self._tables: dict[tuple[str, str], Table] = {}
        self._relations: dict[str, Relation] = {}
        self._ids: dict[int, str] = {}
        self._next_id = 1
        # Registration hooks: fn(name, table), fired after a table
        # registers (r8: the engine attaches the device executor's
        # compile prewarm here). Best-effort — a failing listener must
        # never fail table creation.
        self._listeners: list = []

    def add_create_listener(self, fn) -> None:
        """Register fn(name, table) to run after every add_table."""
        with self._lock:
            self._listeners.append(fn)

    def add_table(
        self,
        name: str,
        table: Table,
        tablet_id: str = DEFAULT_TABLET,
        table_id: Optional[int] = None,
    ) -> int:
        with self._lock:
            table.name = table.name or name
            self._tables[(name, tablet_id)] = table
            self._relations[name] = table.relation
            tid = table_id if table_id is not None else self._next_id
            self._next_id = max(self._next_id, tid + 1)
            self._ids[tid] = name
            listeners = list(self._listeners)
        # Outside the lock: listeners may call back into the store.
        for fn in listeners:
            try:
                fn(name, table)
            except Exception:
                import logging

                logging.getLogger("pixie_tpu.table").warning(
                    "table-create listener failed for %r", name,
                    exc_info=True,
                )
        return tid

    def create_table(self, name: str, relation: Relation, **kwargs) -> Table:
        t = Table(relation, name=name, **kwargs)
        self.add_table(name, t)
        return t

    def get_table(
        self, name_or_id, tablet_id: str = DEFAULT_TABLET
    ) -> Optional[Table]:
        with self._lock:
            name = (
                self._ids.get(name_or_id)
                if isinstance(name_or_id, int)
                else name_or_id
            )
            return self._tables.get((name, tablet_id))

    def get_relation(self, name: str) -> Optional[Relation]:
        with self._lock:
            return self._relations.get(name)

    def has_table(self, name: str) -> bool:
        with self._lock:
            return name in self._relations

    def table_names(self) -> list[str]:
        with self._lock:
            return sorted(self._relations)

    def tablets(self, name: str) -> list[str]:
        with self._lock:
            return sorted(t for (n, t) in self._tables if n == name)

    def tables(self) -> Iterable[Table]:
        with self._lock:
            return list(self._tables.values())

    def compact_all(self) -> int:
        n = 0
        for t in self.tables():
            n += t.compact()
        return n

    def relation_map(self) -> dict[str, Relation]:
        """Schema map handed to the compiler (ref: schema::Schema)."""
        with self._lock:
            return dict(self._relations)
