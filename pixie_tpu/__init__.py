"""pixie_tpu — a TPU-native observability query framework.

A brand-new implementation of the capabilities of Pixie (reference:
``Emin3mU/pixie``): pluggable telemetry source connectors feeding an in-memory
hot/cold columnar table store, queried with PxL (a Pythonic, pandas-like DSL)
through a compiler, distributed planner, and a dataflow execution engine whose
heavy operators (map/filter/group-by aggregation/join and the sketch UDAs)
lower to jit-compiled JAX running on TPU.

Architecture (TPU-first, not a port — see SURVEY.md for the reference map):

- ``pixie_tpu.types``      value/relation type system (ref: src/shared/types)
- ``pixie_tpu.table``      columnar RowBatch + hot/cold Table store
                           (ref: src/table_store)
- ``pixie_tpu.udf``        typed UDF/UDA/UDTF registry + builtin funcs
                           (ref: src/carnot/udf, src/carnot/funcs)
- ``pixie_tpu.ops``        the JAX/TPU kernels: segment reductions, sketch
                           tensors (t-digest/log-histogram/HLL/count-min)
- ``pixie_tpu.compiler``   PxL front end -> operator IR -> logical plan
                           (ref: src/carnot/planner/compiler)
- ``pixie_tpu.plan``       plan representation (ref: src/carnot/plan)
- ``pixie_tpu.exec``       ExecNode dataflow engine (ref: src/carnot/exec)
- ``pixie_tpu.parallel``   distributed planner: blocking-op split, partial-agg
                           rewrite, device-mesh coordinator, shard_map/psum
                           merge over ICI (ref: src/carnot/planner/distributed
                           + the PEM->Kelvin gRPC bridge it replaces)
- ``pixie_tpu.ingest``     source-connector framework + synthetic telemetry
                           generators (ref: src/stirling, CPU-side by design)
- ``pixie_tpu.metadata``   k8s-entity metadata state for ctx[] resolution
                           (ref: src/shared/metadata)
- ``pixie_tpu.engine``     the Carnot-equivalent engine facade
- ``pixie_tpu.broker``     thin query broker (ref: src/vizier/services/query_broker)
- ``pixie_tpu.api``        client API (ref: src/api)

64-bit note: telemetry timestamps and counters are int64; we enable jax x64 so
device columns keep their width. Hot kernels cast explicitly to
float32/bfloat16 where precision allows, so this does not put f64 on the MXU.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from pixie_tpu.types import DataType, SemanticType, Relation  # noqa: E402,F401
