"""Structural program keys: one stable string per query *shape*.

Extracted from the device circuit breaker (r9, parallel/pipeline.py) so
the broker's health plane (r10) can compute the SAME key the agents trip
their breakers on: operator chain + table names + agg/map expressions,
NOT the table version — a poisoned fold shape stays recognizable across
data growth, while a different query shape keys independently. Agents
report per-key breaker state in their heartbeats; ``execute_script``
matches the planned per-agent fragments against those keys and routes
around agents whose breaker is open for this exact program shape.
"""

from __future__ import annotations


def fragment_program_key(fragment) -> str:
    """Stable structural key for one plan fragment (the unit both the
    device executor and the distributed planner hand around)."""
    parts = []
    for nid in fragment.topo_order():
        op = fragment.node(nid)
        parts.append(type(op).__name__)
        tn = getattr(op, "table_name", None)
        if tn:
            parts.append(tn)
        exprs = getattr(op, "values", None) or getattr(op, "exprs", None)
        if exprs:
            parts.append(repr(exprs))
        groups = getattr(op, "groups", None)
        if groups:
            parts.append(repr(groups))
    return "|".join(parts)
