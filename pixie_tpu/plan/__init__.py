"""Plan representation: scalar expressions, operators, fragments, plans.

Ref: src/carnot/plan/ (plan.{h,cc}, plan_fragment.{h,cc}, operators.{h,cc},
scalar_expression.{h,cc}) — the deserialized, walkable form of a compiled
query that the exec engine consumes.
"""

from pixie_tpu.plan.expressions import (
    AggregateExpression,
    ColumnRef,
    Constant,
    FuncCall,
    ScalarExpression,
    expr_data_type,
    expr_semantic_type,
    referenced_columns,
)
from pixie_tpu.plan.operators import (
    AggOp,
    AggStage,
    BridgeSinkOp,
    BridgeSourceOp,
    EmptySourceOp,
    FilterOp,
    JoinOp,
    LimitOp,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    Operator,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)
from pixie_tpu.plan.plan import Plan, PlanFragment

__all__ = [
    "AggOp",
    "AggStage",
    "AggregateExpression",
    "BridgeSinkOp",
    "BridgeSourceOp",
    "ColumnRef",
    "Constant",
    "EmptySourceOp",
    "FilterOp",
    "FuncCall",
    "JoinOp",
    "LimitOp",
    "MapOp",
    "MemorySinkOp",
    "MemorySourceOp",
    "Operator",
    "Plan",
    "PlanFragment",
    "ResultSinkOp",
    "ScalarExpression",
    "UDTFSourceOp",
    "UnionOp",
    "expr_data_type",
    "expr_semantic_type",
    "referenced_columns",
]
