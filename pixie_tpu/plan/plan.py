"""Plan & PlanFragment DAGs with walkers and relation propagation.

Ref: src/carnot/plan/plan.{h,cc}, plan_fragment.{h,cc} — a Plan is a DAG of
PlanFragments; a PlanFragment is a DAG of operators. PlanWalker /
PlanFragmentWalker do topological traversal (used by the engine at
carnot.cc:147-218,353).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable, Optional

from pixie_tpu.plan import dag
from pixie_tpu.plan.operators import (
    AggOp,
    AggStage,
    BridgeSourceOp,
    MemorySourceOp,
    Operator,
)
from pixie_tpu.types import Relation


@dataclasses.dataclass
class _Node:
    nid: int
    op: Operator
    parents: list[int]


class PlanFragment:
    """An operator DAG executed by one engine instance.

    Nodes are added in any order; ``topo_order`` yields parents before
    children. Edges run parent→child in dataflow direction (parent produces,
    child consumes).
    """

    def __init__(self, fragment_id: int = 0):
        self.fragment_id = fragment_id
        self._nodes: dict[int, _Node] = {}
        self._next_id = 0

    def add(self, op: Operator, parents: Iterable[int] = ()) -> int:
        nid = self._next_id
        self._next_id += 1
        parents = list(parents)
        for p in parents:
            if p not in self._nodes:
                raise KeyError(f"unknown parent node {p}")
        self._nodes[nid] = _Node(nid, op, parents)
        return nid

    # -- structure queries --------------------------------------------------
    def node(self, nid: int) -> Operator:
        return self._nodes[nid].op

    def parents(self, nid: int) -> list[int]:
        return list(self._nodes[nid].parents)

    def children(self, nid: int) -> list[int]:
        """Child node ids, with multiplicity (a self-join lists its single
        parent twice; each occurrence is a distinct dataflow edge)."""
        return dag.children_of(self._parents_map(), nid)

    def _parents_map(self) -> dict[int, list[int]]:
        return {n.nid: n.parents for n in self._nodes.values()}

    def nodes(self) -> list[int]:
        return list(self._nodes)

    def sources(self) -> list[int]:
        return [n.nid for n in self._nodes.values() if not n.parents]

    def sinks(self) -> list[int]:
        with_children = {p for n in self._nodes.values() for p in n.parents}
        return [nid for nid in self._nodes if nid not in with_children]

    def topo_order(self) -> list[int]:
        """Parents-before-children order (ref: PlanFragmentWalker)."""
        return dag.topo_order(self._parents_map())

    def walk(self, fn: Callable[[int, Operator], None]) -> None:
        for nid in self.topo_order():
            fn(nid, self._nodes[nid].op)

    # -- relation propagation ----------------------------------------------
    def resolve_relations(
        self,
        registry,
        table_relations: Optional[Callable[[MemorySourceOp], Relation]] = None,
    ) -> dict[int, Relation]:
        """Compute every node's output relation bottom-up."""
        rels: dict[int, Relation] = {}
        for nid in self.topo_order():
            op = self._nodes[nid].op
            inputs = [rels[p] for p in self._nodes[nid].parents]
            if isinstance(op, MemorySourceOp):
                if table_relations is None:
                    raise ValueError("need table_relations to resolve sources")
                rels[nid] = op.output_relation(
                    inputs, registry, table_relation=table_relations(op)
                )
            else:
                rels[nid] = op.output_relation(inputs, registry)
        return rels

    def has_blocking_agg(self) -> bool:
        return any(
            isinstance(n.op, AggOp) and not n.op.windowed
            for n in self._nodes.values()
        )

    def bridge_source_ids(self) -> list[str]:
        return [
            n.op.bridge_id
            for n in self._nodes.values()
            if isinstance(n.op, BridgeSourceOp)
        ]

    def __repr__(self):
        parts = []
        for nid in self.topo_order():
            n = self._nodes[nid]
            src = f"{n.parents}→" if n.parents else ""
            parts.append(f"{src}{nid}:{n.op.op_name}")
        return f"Fragment#{self.fragment_id}[{', '.join(parts)}]"


class Plan:
    """A DAG of fragments. ``executing_instance`` labels which engine
    instance (device shard / kelvin) runs each fragment — filled in by the
    distributed coordinator; single-instance plans leave it None."""

    def __init__(self, query_id: str = ""):
        self.query_id = query_id
        self.fragments: list[PlanFragment] = []
        self.executing_instance: dict[int, Optional[str]] = {}

    def add_fragment(self, instance: Optional[str] = None) -> PlanFragment:
        f = PlanFragment(fragment_id=len(self.fragments))
        self.fragments.append(f)
        self.executing_instance[f.fragment_id] = instance
        return f

    def fragment_topo_order(self) -> list[PlanFragment]:
        """Producer fragments before consumer fragments, inferred from
        bridge ids (a fragment consuming bridge B depends on the fragment
        producing B). Ref: PlanWalker over the fragment DAG."""
        from pixie_tpu.plan.operators import BridgeSinkOp

        producers: dict[str, int] = {}
        for f in self.fragments:
            for nid in f.nodes():
                op = f.node(nid)
                if isinstance(op, BridgeSinkOp):
                    producers[op.bridge_id] = f.fragment_id
        deps: dict[int, set[int]] = {f.fragment_id: set() for f in self.fragments}
        for f in self.fragments:
            for bid in f.bridge_source_ids():
                if bid in producers:
                    deps[f.fragment_id].add(producers[bid])
        out: list[PlanFragment] = []
        done: set[int] = set()
        while len(done) < len(self.fragments):
            progressed = False
            for f in self.fragments:
                if f.fragment_id in done:
                    continue
                if deps[f.fragment_id] <= done:
                    out.append(f)
                    done.add(f.fragment_id)
                    progressed = True
            if not progressed:
                raise ValueError("fragment DAG has a cycle")
        return out

    def __repr__(self):
        return f"Plan({self.query_id!r}, {self.fragments!r})"
