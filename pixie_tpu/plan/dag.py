"""Shared DAG traversal helpers used by PlanFragment and the compiler IR.

Edges are expressed as a parents map {node_id: [parent_ids]} — parent ids may
repeat (a self-join lists one parent twice; each occurrence is a distinct
dataflow edge).
"""

from __future__ import annotations


def children_of(parents: dict[int, list[int]], nid: int) -> list[int]:
    """Child ids with multiplicity (one entry per edge)."""
    out: list[int] = []
    for n, ps in parents.items():
        out.extend(n for p in ps if p == nid)
    return out


def topo_order(parents: dict[int, list[int]]) -> list[int]:
    """Parents-before-children order; raises on cycles."""
    indeg = {n: len(ps) for n, ps in parents.items()}
    ready = sorted(n for n, d in indeg.items() if d == 0)
    out: list[int] = []
    while ready:
        nid = ready.pop(0)
        out.append(nid)
        for c in children_of(parents, nid):  # duplicates decrement per edge
            indeg[c] -= 1
            if indeg[c] == 0:
                ready.append(c)
        ready.sort()
    if len(out) != len(parents):
        raise ValueError("operator graph has a cycle")
    return out
