"""Plan operators.

Ref: src/carnot/plan/operators.{h,cc} — MemorySourceOperator, MapOperator,
FilterOperator, AggregateOperator, JoinOperator, LimitOperator,
UnionOperator, MemorySinkOperator, GRPCSource/SinkOperator,
UDTFSourceOperator, EmptySourceOperator. Each knows how to compute its
output relation from its inputs' relations — the exec engine and the
distributed splitter both rely on that.

TPU-first notes: Agg carries an explicit ``stage`` (FULL / PARTIAL / MERGE)
instead of the reference's partial_agg/finalize_results bool pair
(planpb) so the splitter's partial-aggregate rewrite
(distributed/splitter/partial_op_mgr.h:94) is a one-field edit, and the
bridge operators are transport-agnostic (in-process queue on one host, DCN
stream across hosts) rather than gRPC-specific.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional

from pixie_tpu.plan.expressions import (
    AggregateExpression,
    ScalarExpression,
    expr_data_type,
    expr_semantic_type,
)
from pixie_tpu.types import ColumnSchema, DataType, Relation, SemanticType


class Operator:
    """Base plan operator. ``output_relation`` resolves schema bottom-up."""

    __slots__ = ()

    def output_relation(self, inputs: list[Relation], registry) -> Relation:
        raise NotImplementedError

    @property
    def op_name(self) -> str:
        return type(self).__name__.removesuffix("Op")

    def __repr__(self):
        return self.op_name


@dataclasses.dataclass(frozen=True, repr=False)
class MemorySourceOp(Operator):
    """Read a table via a time-bounded cursor (ref: memory_source_node.h:42)."""

    table_name: str
    column_names: Optional[tuple[str, ...]] = None  # None = all columns
    start_time: Optional[int] = None
    stop_time: Optional[int] = None
    streaming: bool = False
    tablet: Optional[str] = None

    def output_relation(self, inputs, registry, table_relation=None) -> Relation:
        if table_relation is None:
            raise ValueError("MemorySourceOp needs the table relation to resolve")
        if self.column_names is None:
            return table_relation
        return table_relation.select(list(self.column_names))


@dataclasses.dataclass(frozen=True, repr=False)
class UDTFSourceOp(Operator):
    """Run a user-defined table function (ref: udtf_source_node)."""

    udtf_name: str
    arg_values: tuple[tuple[str, Any], ...] = ()

    def output_relation(self, inputs, registry) -> Relation:
        udtf = registry.lookup_udtf(self.udtf_name)
        if udtf is None:
            raise ValueError(f"no UDTF named {self.udtf_name!r}")
        return udtf.output_relation


@dataclasses.dataclass(frozen=True, repr=False)
class EmptySourceOp(Operator):
    """Produces a single empty batch with a fixed relation."""

    relation: Relation

    def output_relation(self, inputs, registry) -> Relation:
        return self.relation


@dataclasses.dataclass(frozen=True, repr=False)
class InlineSourceOp(Operator):
    """Emits batches precomputed by another executor (the device pipeline
    substitutes its aggregate output here so the remaining host suffix —
    post-agg maps, limits, sinks — runs unchanged)."""

    key: str
    relation: Relation

    def output_relation(self, inputs, registry) -> Relation:
        return self.relation


@dataclasses.dataclass(frozen=True, repr=False)
class BridgeSourceOp(Operator):
    """Receive batches from another fragment (ref: grpc_source_node.h:39)."""

    bridge_id: str
    relation: Relation

    def output_relation(self, inputs, registry) -> Relation:
        return self.relation


@dataclasses.dataclass(frozen=True, repr=False)
class MapOp(Operator):
    """Project/compute columns (ref: MapOperator). ``exprs`` fully define the
    output — pass-through columns are explicit ColumnRefs."""

    exprs: tuple[tuple[str, ScalarExpression], ...]

    def output_relation(self, inputs, registry) -> Relation:
        (rel,) = inputs
        cols = []
        for name, e in self.exprs:
            cols.append(
                ColumnSchema(
                    name,
                    expr_data_type(e, rel, registry),
                    expr_semantic_type(e, rel, registry),
                )
            )
        return Relation(cols)


@dataclasses.dataclass(frozen=True, repr=False)
class FilterOp(Operator):
    expr: ScalarExpression

    def output_relation(self, inputs, registry) -> Relation:
        (rel,) = inputs
        if expr_data_type(self.expr, rel, registry) != DataType.BOOLEAN:
            raise ValueError("filter predicate must be BOOLEAN")
        return rel


class AggStage(enum.Enum):
    FULL = "full"        # update + finalize in one node
    PARTIAL = "partial"  # update only; emit serialized group states
    MERGE = "merge"      # consume states; merge + finalize

    # Ref: partial_op_mgr.h:36,77,94 — the reference expresses this as
    # (partial_agg, finalize_results) bools on AggregateOperator.


@dataclasses.dataclass(frozen=True, repr=False)
class AggOp(Operator):
    """Group-by aggregate (ref: AggregateOperator / exec agg_node.h:66).

    ``windowed`` emits per end-of-window instead of end-of-stream.
    """

    groups: tuple[str, ...]
    values: tuple[tuple[str, AggregateExpression], ...]
    windowed: bool = False
    stage: AggStage = AggStage.FULL
    # MERGE stages resolve UDA overloads against the relation the matching
    # PARTIAL stage consumed (set by the distributed splitter) — the merge
    # input itself carries opaque state columns (ref: the plan proto carries
    # resolved UDA ids across the PEM/Kelvin split instead).
    pre_agg_relation: Optional[Relation] = None

    def output_relation(self, inputs, registry) -> Relation:
        (rel,) = inputs
        value_rel = (
            self.pre_agg_relation
            if self.stage == AggStage.MERGE and self.pre_agg_relation is not None
            else rel
        )
        cols = [
            dataclasses.replace(rel.col(g), name=g) for g in self.groups
        ]
        for name, agg in self.values:
            if self.stage == AggStage.PARTIAL:
                # Serialized per-group UDA state travels as an opaque string
                # column (ref: partial aggs serialize into string columns).
                cols.append(ColumnSchema(name, DataType.STRING))
            else:
                cols.append(
                    ColumnSchema(
                        name,
                        expr_data_type(agg, value_rel, registry),
                        expr_semantic_type(agg, value_rel, registry),
                    )
                )
        return Relation(cols)

    def merge_input_relation(self, pre_agg_relation: Relation) -> Relation:
        """Relation a MERGE-stage agg expects from its PARTIAL upstreams."""
        cols = [pre_agg_relation.col(g) for g in self.groups]
        for name, _ in self.values:
            cols.append(ColumnSchema(name, DataType.STRING))
        return Relation(cols)


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


@dataclasses.dataclass(frozen=True, repr=False)
class JoinOp(Operator):
    """Hash equijoin (ref: equijoin_node.h:48). ``output_columns`` is a list
    of (side, input_col, output_name); side 0 = left/build, 1 = right/probe.
    """

    how: JoinType
    left_on: tuple[str, ...]
    right_on: tuple[str, ...]
    output_columns: tuple[tuple[int, str, str], ...]

    def output_relation(self, inputs, registry) -> Relation:
        left, right = inputs
        cols = []
        for side, in_name, out_name in self.output_columns:
            src = left if side == 0 else right
            cols.append(src.col(in_name).with_name(out_name))
        return Relation(cols)


@dataclasses.dataclass(frozen=True, repr=False)
class LimitOp(Operator):
    """Row limit; aborts upstream abortable sources when satisfied
    (ref: limit_node + annotate_abortable_sources_for_limits_rule)."""

    n: int

    def output_relation(self, inputs, registry) -> Relation:
        (rel,) = inputs
        return rel


@dataclasses.dataclass(frozen=True, repr=False)
class UnionOp(Operator):
    """k-way union; time-ordered merge when a time_ column exists
    (ref: union_node does ordered merge on time_)."""

    def output_relation(self, inputs, registry) -> Relation:
        first = inputs[0]
        for rel in inputs[1:]:
            # Relation.__eq__ compares (name, dtype) pairs only.
            if rel != first:
                raise ValueError(f"union inputs differ: {first} vs {rel}")
        # Semantic types may legitimately differ between branches (e.g.
        # dns_flow_graph unions a resolved-entity branch with a raw-IP
        # branch); keep a column's semantic only where ALL branches agree
        # (the reference planner unions on name+dtype).
        cols = []
        for i, c in enumerate(first):
            sem = c.semantic_type
            if any(rel.col(i).semantic_type != sem for rel in inputs[1:]):
                sem = SemanticType.ST_NONE
            cols.append(ColumnSchema(c.name, c.data_type, sem))
        return Relation(cols)


@dataclasses.dataclass(frozen=True, repr=False)
class MemorySinkOp(Operator):
    """Write result into the local table store (ref: memory_sink_node)."""

    name: str

    def output_relation(self, inputs, registry) -> Relation:
        (rel,) = inputs
        return rel


@dataclasses.dataclass(frozen=True, repr=False)
class ResultSinkOp(Operator):
    """Stream to the query result destination (ref: GRPCSink in
    external-result mode → query broker TransferResultChunk)."""

    table_name: str

    def output_relation(self, inputs, registry) -> Relation:
        (rel,) = inputs
        return rel


@dataclasses.dataclass(frozen=True, repr=False)
class OTelExportSinkOp(Operator):
    """Export row batches as OpenTelemetry metrics/spans
    (ref: src/carnot/exec/otel_export_sink_node.h:40 + the px.otel PxL
    module, planner/objects/otel.h). Column references are names into the
    input relation; ``metrics``/``spans`` are spec dicts built by the
    compiler's px.otel objects."""

    resource: tuple  # ((attr name, column-or-value, is_column), ...)
    metrics: tuple = ()  # Gauge/Summary spec dicts (frozen as tuples)
    spans: tuple = ()
    endpoint: Optional[str] = None

    def output_relation(self, inputs, registry) -> Relation:
        (rel,) = inputs
        return rel


@dataclasses.dataclass(frozen=True, repr=False)
class BridgeSinkOp(Operator):
    """Send batches to another fragment (ref: grpc_sink_node.h:54 in
    internal mode)."""

    bridge_id: str

    def output_relation(self, inputs, registry) -> Relation:
        (rel,) = inputs
        return rel
