"""Scalar expression trees.

Ref: src/carnot/plan/scalar_expression.{h,cc} — ScalarValue / Column /
ScalarFunc / AggregateExpression with an ExpressionWalker. Type resolution
happens against the UDF registry (the reference resolves during planner
analysis and carries resolved ids in the proto; we resolve lazily but
deterministically from (name, arg types)).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator

from pixie_tpu.types import DataType, Relation, SemanticType


class ScalarExpression:
    """Base class for scalar expression nodes (immutable)."""

    __slots__ = ()


@dataclasses.dataclass(frozen=True)
class ColumnRef(ScalarExpression):
    """A reference to an input column by name."""

    name: str

    def __repr__(self):
        return f"col({self.name})"


@dataclasses.dataclass(frozen=True)
class Constant(ScalarExpression):
    """A literal with an explicit data type (ref: ScalarValue)."""

    value: Any
    data_type: DataType

    def __repr__(self):
        return f"lit({self.value!r})"


@dataclasses.dataclass(frozen=True)
class FuncCall(ScalarExpression):
    """A scalar UDF call. ``init_args`` are non-column trailing arguments
    (ref: udf.h init args — e.g. the substring pattern)."""

    name: str
    args: tuple[ScalarExpression, ...]
    init_args: tuple[Any, ...] = ()

    def __repr__(self):
        parts = [repr(a) for a in self.args] + [repr(a) for a in self.init_args]
        return f"{self.name}({', '.join(parts)})"


@dataclasses.dataclass(frozen=True)
class AggregateExpression:
    """A UDA call inside an Agg operator (ref: plan AggregateExpression).

    Args are restricted to column refs / constants — the compiler hoists
    computed arguments into a preceding Map (same as the reference planner).
    """

    name: str
    args: tuple[ScalarExpression, ...]
    init_args: tuple[Any, ...] = ()

    def __repr__(self):
        return f"{self.name}({', '.join(repr(a) for a in self.args)})"


def walk(expr: ScalarExpression) -> Iterator[ScalarExpression]:
    """Post-order walk (ref: ExpressionWalker)."""
    if isinstance(expr, FuncCall):
        for a in expr.args:
            yield from walk(a)
    yield expr


def referenced_columns(expr) -> set[str]:
    """Column names an expression (or aggregate) reads."""
    if isinstance(expr, AggregateExpression):
        out: set[str] = set()
        for a in expr.args:
            out |= referenced_columns(a)
        return out
    return {e.name for e in walk(expr) if isinstance(e, ColumnRef)}


def expr_data_type(expr, relation: Relation, registry) -> DataType:
    """Resolve the output DataType of an expression against a relation.

    Raises KeyError for unknown columns and ValueError for unresolvable
    function overloads — the same failures the reference planner surfaces as
    compile errors.
    """
    if isinstance(expr, ColumnRef):
        return relation.col(expr.name).data_type
    if isinstance(expr, Constant):
        return expr.data_type
    if isinstance(expr, FuncCall):
        arg_types = [expr_data_type(a, relation, registry) for a in expr.args]
        udf = registry.lookup_scalar(expr.name, arg_types)
        if udf is None:
            raise ValueError(
                f"no scalar function {expr.name}"
                f"({', '.join(t.name for t in arg_types)})"
            )
        return udf.out_type
    if isinstance(expr, AggregateExpression):
        arg_types = [expr_data_type(a, relation, registry) for a in expr.args]
        uda = registry.lookup_uda(expr.name, arg_types)
        if uda is None:
            raise ValueError(
                f"no aggregate {expr.name}"
                f"({', '.join(t.name for t in arg_types)})"
            )
        return uda.out_type
    raise TypeError(f"not an expression: {expr!r}")


def expr_semantic_type(expr, relation: Relation, registry) -> SemanticType:
    """Resolve the output SemanticType (ref: udf/type_inference.h rules)."""
    if isinstance(expr, ColumnRef):
        return relation.col(expr.name).semantic_type
    if isinstance(expr, Constant):
        return SemanticType.ST_NONE
    if isinstance(expr, (FuncCall, AggregateExpression)):
        arg_types = [expr_data_type(a, relation, registry) for a in expr.args]
        arg_sems = [expr_semantic_type(a, relation, registry) for a in expr.args]
        if isinstance(expr, FuncCall):
            f = registry.lookup_scalar(expr.name, arg_types)
        else:
            f = registry.lookup_uda(expr.name, arg_types)
        return f.infer_semantic(arg_sems) if f is not None else SemanticType.ST_NONE
    raise TypeError(f"not an expression: {expr!r}")
