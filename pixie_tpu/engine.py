"""Carnot-equivalent engine facade.

Ref: src/carnot/carnot.{h,cc} — Carnot::Create (carnot.h:52),
ExecuteQuery (carnot.cc:122; compile then execute), ExecutePlan
(carnot.cc:319; walk fragments, build exec graphs, run, stream results +
per-operator stats to the result destination).
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Optional

from pixie_tpu.compiler import Compiler
from pixie_tpu.exec import BridgeRouter, ExecState, ExecutionGraph
from pixie_tpu.plan.operators import BridgeSinkOp, InlineSourceOp
from pixie_tpu.plan.plan import Plan, PlanFragment
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.table.table_store import TableStore
from pixie_tpu.utils import flags, trace


@dataclasses.dataclass
class QueryResult:
    """Streamed result tables + execution stats (ref: queryresultspb)."""

    query_id: str
    tables: dict[str, list[RowBatch]]
    exec_stats: dict[str, dict]  # node name -> stats dict (analyze mode)
    compile_time_ns: int = 0
    exec_time_ns: int = 0
    # Structured partial-result annotation (r9; ref: the forwarder's
    # per-agent timeout/cancel annotations, query_result_forwarder.go:395):
    # None = complete result. Otherwise a dict with keys ``partial``,
    # ``reasons``, ``agent_errors`` {agent: message}, ``lost_agents``
    # (heartbeat-expired mid-query), ``timed_out_agents`` (still pending at
    # the deadline), ``skipped_agents`` (planning never covered them),
    # ``skipped`` (r10: [{agent_id, reason}] with reason
    # ``heartbeat_expired`` or ``breaker_open``), ``forward_dropped``
    # (result messages lost in the broker's forwarder), ``trace_id``
    # (r11: joins the annotation to the query's span tree).
    degraded: Optional[dict] = None
    # Finished trace spans for this query (r11), merged across agents by
    # trace_id — wire-shaped dicts (utils/trace.py Span.to_dict). None
    # when query_tracing is off.
    trace_spans: Optional[list] = None
    # Transparent-failover annotation (r17, flag ``fragment_failover``):
    # set when the result is COMPLETE but one or more fragments had to be
    # retried onto a surviving agent or won by a hedged duplicate —
    # {"retried": [{slot, from, to, reason, epoch}], "hedged": [{slot,
    # winner, loser}], "trace_id"}. A recovered result is NOT degraded
    # (``ok`` stays True): the rows are bit-identical to an unfaulted
    # run; the annotation only says failover did work to get them.
    recovered: Optional[dict] = None
    # Materialized-view freshness stamp (r20, flag ``materialized_views``):
    # set when the result was served from a view's merged partial-agg
    # state instead of a fold — {"view", "view_id", "staleness_s"
    # (seconds since the view's last successful maintenance),
    # "watermark" (table row-id the carried state covers), "tail_rows"
    # (unflushed rows delta-folded at read time)}. A view-served result
    # is bit-identical to folding from scratch; the stamp only says how
    # the rows were produced and how fresh the carried state was.
    view: Optional[dict] = None

    @property
    def ok(self) -> bool:
        """True when the result is complete (no degraded annotation)."""
        return self.degraded is None

    @property
    def profile(self) -> Optional[dict]:
        """The assembled query trace (r11): a span forest covering
        broker, every participating agent, each exec node, and per-window
        device phases — with degraded agents marked. None when tracing
        was off for the query."""
        if self.trace_spans is None:
            return None
        roots = trace.build_tree(self.trace_spans)
        agents = sorted(
            {
                s["instance"]
                for s in self.trace_spans
                if s.get("name") == "agent.execute"
            }
        )
        out = {
            "trace_id": self.query_id,
            "span_count": len(self.trace_spans),
            "agents": agents,
            "roots": roots,
        }
        if self.degraded is not None:
            # Mark agents whose span subtree is missing or truncated.
            out["degraded"] = {
                "reasons": list(self.degraded.get("reasons", ())),
                "lost_agents": list(self.degraded.get("lost_agents", ())),
                "timed_out_agents": list(
                    self.degraded.get("timed_out_agents", ())
                ),
                "skipped_agents": list(
                    self.degraded.get("skipped_agents", ())
                ),
                "error_agents": sorted(
                    self.degraded.get("agent_errors", {})
                ),
            }
        return out

    def table(self, name: str = None) -> dict:
        if name is None:
            if len(self.tables) != 1:
                raise KeyError(f"result has tables {sorted(self.tables)}")
            name = next(iter(self.tables))
        batches = [b for b in self.tables[name] if b.num_rows]
        if not batches:
            return {}
        return RowBatch.concat(batches).to_pydict()


def _splice_inline_source(
    fragment: PlanFragment, agg_nid: int, key: str, relation
) -> PlanFragment:
    """Replace the device-executed prefix (agg + its ancestors) with an
    InlineSource emitting the computed aggregate, keeping the suffix."""
    ancestors = set()
    stack = list(fragment.parents(agg_nid))
    while stack:
        p = stack.pop()
        if p not in ancestors:
            ancestors.add(p)
            stack.extend(fragment.parents(p))
    new = PlanFragment(fragment.fragment_id)
    mapping: dict[int, int] = {}
    mapping[agg_nid] = new.add(InlineSourceOp(key=key, relation=relation))
    for nid in fragment.topo_order():
        if nid == agg_nid or nid in ancestors:
            continue
        mapping[nid] = new.add(
            fragment.node(nid), [mapping[p] for p in fragment.parents(nid)]
        )
    return new


class Carnot:
    """One engine instance (a PEM or Kelvin equivalent runs one of these)."""

    def __init__(
        self,
        table_store: Optional[TableStore] = None,
        registry=None,
        metadata_state=None,
        router: Optional[BridgeRouter] = None,
        instance: str = "local",
        device_executor=None,
        vizier_ctx=None,
        otel_exporter=None,
    ):
        self.table_store = table_store or TableStore()
        self.vizier_ctx = vizier_ctx
        # Default exporter: BOUNDED in-memory collector (zero-egress
        # default; long-lived engines with recurring exports must not leak
        # — swap in an OTLP/HTTP callable for a real collector).
        import collections

        self.otel_payloads: "collections.deque" = collections.deque(
            maxlen=1024
        )
        self.otel_exporter = otel_exporter or self.otel_payloads.append
        if registry is None:
            from pixie_tpu.udf.registry import default_registry

            registry = default_registry()
        self.registry = registry
        self.metadata_state = metadata_state
        self.router = router or BridgeRouter()
        self.instance = instance
        # Optional pixie_tpu.parallel.MeshExecutor: fragments matching the
        # hot source→map/filter→agg chain run as ONE compiled shard_map
        # program on the device mesh; the host exec graph runs the suffix.
        self.device_executor = device_executor
        # Self-telemetry tables (r11): every engine instance owns
        # query_spans/engine_metrics tables so PxL can query the engine
        # about itself (ref: stirling_error/probe_status dogfooding).
        # Created eagerly so the compiler sees their relations; rows land
        # on demand (execute_plan flush) or via the ingest connector.
        if flags.query_tracing or flags.resource_attribution:
            from pixie_tpu.ingest.self_telemetry import ensure_tables

            ensure_tables(self.table_store)
        if device_executor is not None and hasattr(
            device_executor, "prewarm_table"
        ):
            # r8 cold-path lever: table registration kicks the background
            # compile prewarm for the table's bucketed stream-window
            # geometry (flag ``prewarm_compile``; gated inside
            # prewarm_table so it can be flipped at runtime).
            self.table_store.add_create_listener(
                lambda name, table: device_executor.prewarm_table(
                    table, self.registry
                )
            )
        if device_executor is not None and hasattr(
            device_executor, "enable_resident_ingest"
        ):
            # r13 cold-path lever: with flag ``resident_ingest``, every
            # created table gets an HBM ring fed by its appends
            # (serving/resident.py), so hot tables never cold-stage
            # their in-window span — stage_transfer ≈ 0 for it.
            self.table_store.add_create_listener(
                lambda name, table: device_executor.enable_resident_ingest(
                    table
                )
            )
        self.compiler = Compiler(registry)
        # Live per-query exec states (r17): lets the broker's hedge path
        # cancel a losing duplicate mid-flight through the r9 abort
        # machinery (ExecState.cancel → keep_running False → sources
        # abort) instead of letting it run to completion. Cancellation
        # is ATTEMPT-scoped: one engine may host several attempts of
        # the same query (a hedged merge landing on the straggler's own
        # agent), and cancelling the loser must not touch its
        # co-resident siblings.
        self._active_lock = threading.Lock()
        self._active_states: dict[str, list] = {}
        import collections as _collections

        self._cancelled_attempts: set = set()
        self._cancelled_order: "_collections.deque" = _collections.deque()

    def cancel_query(self, query_id: str, token=None) -> None:
        """Cancel live exec states of ``query_id`` on this engine (r17
        hedge-loser cancellation; also usable by embedders). With
        ``token`` (a failover attempt's (slot, epoch)), only that
        attempt's states cancel. A query with no live state is a no-op
        — cancellation is advisory, exactly-once delivery never depends
        on it; the mark persists so an attempt cancelled between
        fragments stops (and withholds its output) too."""
        with self._active_lock:
            self._cancelled_attempts.add((query_id, token))
            self._cancelled_order.append((query_id, token))
            while len(self._cancelled_order) > 1024:
                self._cancelled_attempts.discard(
                    self._cancelled_order.popleft()
                )
            states = [
                st
                for st in self._active_states.get(query_id, ())
                if token is None or st.bridge_token == token
            ]
        for st in states:
            st.cancel("cancelled by broker (hedge loser / failover)")

    def attempt_cancelled(self, query_id: str, token) -> bool:
        """True when this (query, attempt) was cancelled by the broker:
        the attempt must WITHHOLD its output — another attempt won the
        slot, and partial rows from an aborted run must never look like
        a completed fragment."""
        with self._active_lock:
            return (query_id, token) in self._cancelled_attempts or (
                (query_id, None) in self._cancelled_attempts
            )

    def _track_state(self, query_id: str, state) -> None:
        with self._active_lock:
            self._active_states.setdefault(query_id, []).append(state)

    def _untrack_states(self, query_id: str, states: list) -> None:
        with self._active_lock:
            kept = [
                st
                for st in self._active_states.get(query_id, ())
                if st not in states
            ]
            if kept:
                self._active_states[query_id] = kept
            else:
                self._active_states.pop(query_id, None)

    # -- the two entry points (carnot.h:72-81) ------------------------------
    def execute_query(
        self,
        query: str,
        query_id: Optional[str] = None,
        analyze: bool = False,
        now_ns: Optional[int] = None,
        script_args: Optional[dict] = None,
        exec_funcs=None,
    ) -> QueryResult:
        qid = query_id or str(uuid.uuid4())
        # Local root span (r11): a standalone engine produces the same
        # trace shape the broker path does, rooted at the query_id. When
        # an ambient context exists (an agent executing a broker plan
        # calls execute_plan directly), this path is not taken.
        root = trace.begin(
            "query", trace_id=qid, parent_id="", instance=self.instance
        )
        t0 = time.perf_counter_ns()
        # r15: a standalone engine attributes its own CPU/device work to
        # the query (the broker/agent paths set their own attribution).
        with trace.attribution(qid, "default", "query"):
            with trace.context_of(root):
                with trace.span("compile", instance=self.instance):
                    plan = self.compiler.compile(
                        query,
                        self.table_store.relation_map(),
                        now_ns=now_ns,
                        script_args=script_args,
                        query_id=qid,
                        exec_funcs=exec_funcs,
                    )
                compile_ns = time.perf_counter_ns() - t0
                result = self.execute_plan(plan, analyze=analyze)
        result.compile_time_ns = compile_ns
        if root is not None:
            trace.finish(root)
            result.trace_spans = sorted(
                (s.to_dict() for s in trace.spans_for(qid)),
                key=lambda s: s["start_unix_ns"],
            )
        return result

    def execute_plan(
        self,
        plan: Plan,
        analyze: bool = False,
        manage_router: bool = True,
        deadline_s: Optional[float] = None,
        bridge_token: Optional[tuple] = None,
    ) -> QueryResult:
        """manage_router=False when a broker coordinates several engine
        instances over one shared router: producer registration and query
        cleanup then happen centrally (ref: the GRPCRouter is owned by the
        receiving agent, registration by connection).

        ``deadline_s`` is the propagated per-query hard deadline (r9): all
        fragments share one absolute deadline computed here, so a stalled
        fragment raises QueryDeadlineExceeded instead of holding the agent
        thread to the stall timeout."""
        qid = plan.query_id or str(uuid.uuid4())
        deadline = (
            time.monotonic() + deadline_s
            if deadline_s is not None and deadline_s > 0
            else None
        )
        tables: dict[str, list[RowBatch]] = {}

        def on_result(table_name: str, batch: RowBatch) -> None:
            tables.setdefault(table_name, []).append(batch)

        # Register bridge producers so consumers know their eos counts.
        if manage_router:
            for frag in plan.fragments:
                for nid in frag.nodes():
                    op = frag.node(nid)
                    if isinstance(op, BridgeSinkOp):
                        self.router.register_producer(qid, op.bridge_id)

        # Self-telemetry read path (r11): a plan reading the engine's own
        # query_spans/engine_metrics tables gets the freshest buffered
        # spans/metric samples flushed in before sources open — PxL can
        # profile a query that finished microseconds ago without waiting
        # for the periodic ingest connector.
        if flags.query_tracing or flags.resource_attribution:
            from pixie_tpu.ingest import self_telemetry

            if self_telemetry.plan_reads_telemetry(plan):
                self_telemetry.flush_into(self.table_store)

        exec_stats: dict[str, dict] = {}
        my_states: list = []
        t0 = time.perf_counter_ns()
        try:
            # Producer fragments run before consumers (the reference runs
            # them concurrently across agents; one engine instance runs its
            # own fragments in dependency order — bridge queues buffer).
            ambient = trace.current()
            for frag in plan.fragment_topo_order():
                if self.attempt_cancelled(qid, bridge_token):
                    # r17: the broker cancelled this attempt between
                    # fragments (another attempt won) — stop here; the
                    # caller withholds whatever was produced.
                    break
                fspan = trace.span(
                    "fragment",
                    # Without an ambient context (bare execute_plan), the
                    # fragment spans still join the query's trace: the
                    # query_id is the trace_id.
                    trace_id=None if ambient else qid,
                    instance=self.instance,
                    attrs={"fragment_id": frag.fragment_id},
                )
                with fspan:
                    state = ExecState(
                        qid,
                        self.table_store,
                        self.registry,
                        router=self.router,
                        metadata_state=self.metadata_state,
                        result_callback=on_result,
                        instance=self.instance,
                        vizier_ctx=self.vizier_ctx,
                        otel_exporter=self.otel_exporter,
                        deadline=deadline,
                        bridge_token=bridge_token,
                    )
                    my_states.append(state)
                    self._track_state(qid, state)
                    if self.device_executor is not None:
                        offloaded = self.device_executor.try_execute_fragment(
                            frag, self.table_store, self.registry,
                            state.func_ctx,
                        )
                        if offloaded is not None:
                            agg_nid, batch = offloaded
                            key = f"device:{frag.fragment_id}:{agg_nid}"
                            # Windowed device aggs return one batch PER
                            # WINDOW (eow-cadenced, like the host AggNode).
                            batches = (
                                batch if isinstance(batch, list) else [batch]
                            )
                            state.inline_batches[key] = batches
                            # StateBatches (PARTIAL offload) carry no
                            # relation; resolve the agg op's declared
                            # output instead.
                            rel = getattr(batches[0], "relation", None)
                            if rel is None:
                                rel = frag.resolve_relations(
                                    self.registry,
                                    lambda op: self.table_store.get_relation(
                                        op.table_name
                                    ),
                                )[agg_nid]
                            frag = _splice_inline_source(
                                frag, agg_nid, key, rel
                            )
                    graph = ExecutionGraph(frag, state)
                    graph.execute()
                    if analyze:
                        for name, s in graph.stats().items():
                            exec_stats[f"f{frag.fragment_id}/{name}"] = s
        finally:
            self._untrack_states(qid, my_states)
            if manage_router:
                self.router.cleanup_query(qid)
        exec_ns = time.perf_counter_ns() - t0
        return QueryResult(
            query_id=qid,
            tables=tables,
            exec_stats=exec_stats,
            exec_time_ns=exec_ns,
        )
