"""Carnot-equivalent engine facade.

Ref: src/carnot/carnot.{h,cc} — Carnot::Create (carnot.h:52),
ExecuteQuery (carnot.cc:122; compile then execute), ExecutePlan
(carnot.cc:319; walk fragments, build exec graphs, run, stream results +
per-operator stats to the result destination).
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Optional

from pixie_tpu.compiler import Compiler
from pixie_tpu.exec import BridgeRouter, ExecState, ExecutionGraph
from pixie_tpu.plan.operators import BridgeSinkOp
from pixie_tpu.plan.plan import Plan
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.table.table_store import TableStore


@dataclasses.dataclass
class QueryResult:
    """Streamed result tables + execution stats (ref: queryresultspb)."""

    query_id: str
    tables: dict[str, list[RowBatch]]
    exec_stats: dict[str, dict]  # node name -> stats dict (analyze mode)
    compile_time_ns: int = 0
    exec_time_ns: int = 0

    def table(self, name: str = None) -> dict:
        if name is None:
            if len(self.tables) != 1:
                raise KeyError(f"result has tables {sorted(self.tables)}")
            name = next(iter(self.tables))
        batches = [b for b in self.tables[name] if b.num_rows]
        if not batches:
            return {}
        return RowBatch.concat(batches).to_pydict()


class Carnot:
    """One engine instance (a PEM or Kelvin equivalent runs one of these)."""

    def __init__(
        self,
        table_store: Optional[TableStore] = None,
        registry=None,
        metadata_state=None,
        router: Optional[BridgeRouter] = None,
        instance: str = "local",
    ):
        self.table_store = table_store or TableStore()
        if registry is None:
            from pixie_tpu.udf.registry import default_registry

            registry = default_registry()
        self.registry = registry
        self.metadata_state = metadata_state
        self.router = router or BridgeRouter()
        self.instance = instance
        self.compiler = Compiler(registry)

    # -- the two entry points (carnot.h:72-81) ------------------------------
    def execute_query(
        self,
        query: str,
        query_id: Optional[str] = None,
        analyze: bool = False,
        now_ns: Optional[int] = None,
        script_args: Optional[dict] = None,
    ) -> QueryResult:
        qid = query_id or str(uuid.uuid4())
        t0 = time.perf_counter_ns()
        plan = self.compiler.compile(
            query,
            self.table_store.relation_map(),
            now_ns=now_ns,
            script_args=script_args,
            query_id=qid,
        )
        compile_ns = time.perf_counter_ns() - t0
        result = self.execute_plan(plan, analyze=analyze)
        result.compile_time_ns = compile_ns
        return result

    def execute_plan(self, plan: Plan, analyze: bool = False) -> QueryResult:
        qid = plan.query_id or str(uuid.uuid4())
        tables: dict[str, list[RowBatch]] = {}

        def on_result(table_name: str, batch: RowBatch) -> None:
            tables.setdefault(table_name, []).append(batch)

        # Register bridge producers so consumers know their eos counts.
        for frag in plan.fragments:
            for nid in frag.nodes():
                op = frag.node(nid)
                if isinstance(op, BridgeSinkOp):
                    self.router.register_producer(qid, op.bridge_id)

        exec_stats: dict[str, dict] = {}
        t0 = time.perf_counter_ns()
        try:
            # Producer fragments run before consumers (the reference runs
            # them concurrently across agents; one engine instance runs its
            # own fragments in dependency order — bridge queues buffer).
            for frag in plan.fragment_topo_order():
                state = ExecState(
                    qid,
                    self.table_store,
                    self.registry,
                    router=self.router,
                    metadata_state=self.metadata_state,
                    result_callback=on_result,
                    instance=self.instance,
                )
                graph = ExecutionGraph(frag, state)
                graph.execute()
                if analyze:
                    for name, s in graph.stats().items():
                        exec_stats[f"f{frag.fragment_id}/{name}"] = s
        finally:
            self.router.cleanup_query(qid)
        exec_ns = time.perf_counter_ns() - t0
        return QueryResult(
            query_id=qid,
            tables=tables,
            exec_stats=exec_stats,
            exec_time_ns=exec_ns,
        )
