"""`px` CLI: run PxL scripts against a live engine and print tables.

Ref: src/pixie_cli/px.go:44 + pkg/cmd/root.go:193 — the reference CLI's
core loop is `px run <script> [-- --arg val]` streaming rendered tables;
`px scripts list` lists the bundle. Cloud auth/deploy subcommands are
cloud-control-plane surface; here the cluster is in-process: by default
`run` boots a demo cluster (synthetic socket-tracer + profiler connectors
feeding the table store, synthetic k8s metadata) so every bundled script
has data to chew on.

Usage:
  python -m pixie_tpu.cli scripts list
  python -m pixie_tpu.cli run px/service_stats
  python -m pixie_tpu.cli run px/http_data --arg max_num_records=20
  python -m pixie_tpu.cli run my_query.pxl --warm 2.0
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _build_demo_cluster(warm_s: float):
    """A single-process 'cluster': engine + ingest + synthetic metadata."""
    from pixie_tpu.engine import Carnot
    from pixie_tpu.ingest.core import IngestCore
    from pixie_tpu.ingest.http_gen import HTTPEventsConnector
    from pixie_tpu.ingest.perf_profiler import PerfProfilerConnector
    from pixie_tpu.metadata.state import (
        MetadataState,
        PodInfo,
        ServiceInfo,
    )

    n = 8
    pods, services, upid_to_pod, ip_to_pod = {}, {}, {}, {}
    for i in range(n):
        sid = f"s{i}"
        services[sid] = ServiceInfo(sid, f"default/svc-{i}", "default")
        pid = f"p{i}"
        ip = f"10.0.{i // 256}.{i % 256}"
        pods[pid] = PodInfo(
            pid, f"default/svc-{i}-pod", "default", sid, f"node-{i % 2}", ip
        )
        ip_to_pod[ip] = pid
        upid_to_pod[f"1:{i}:{i * 7 + 1}"] = pid  # http_gen upids
        upid_to_pod[f"1:{100 + i}:{i * 13 + 5}"] = pid  # profiler upids
    md = MetadataState(
        pods=pods,
        services=services,
        upid_to_pod=upid_to_pod,
        ip_to_pod=ip_to_pod,
    )
    carnot = Carnot(metadata_state=md)
    core = IngestCore()
    core.register_source(HTTPEventsConnector(rows_per_sample=500))
    core.register_source(PerfProfilerConnector())
    core.wire_to_table_store(carnot.table_store)
    core.set_context(md)
    core.run_as_thread()
    time.sleep(warm_s)
    core.stop()
    return carnot


def _render_table(name: str, batches, limit: int = 50) -> None:
    from pixie_tpu.table.row_batch import RowBatch

    batches = [b for b in batches if b.num_rows]
    print(f"\n== {name} ==")
    if not batches:
        print("(empty)")
        return
    merged = RowBatch.concat(batches)
    d = merged.to_pydict()
    cols = list(d)
    rows = list(zip(*(d[c] for c in cols)))
    shown = rows[:limit]
    cells = [[_fmt(v) for v in row] for row in shown]
    widths = [
        max(len(c), *(len(r[i]) for r in cells)) if cells else len(c)
        for i, c in enumerate(cols)
    ]
    print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for r in cells:
        print("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    if len(rows) > limit:
        print(f"... ({len(rows) - limit} more rows)")


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    s = str(v)
    return s if len(s) <= 48 else s[:45] + "..."


def cmd_scripts_list(_args) -> int:
    from pixie_tpu.scripts.library import ScriptLibrary

    lib = ScriptLibrary()
    for name in lib.names():
        script = lib.load(name)
        print(f"{name:28s} {script.manifest.get('short', '')}")
    return 0


def cmd_run(args) -> int:
    from pixie_tpu.api import Client
    from pixie_tpu.scripts.library import ScriptLibrary

    script_args = {}
    for kv in args.arg or []:
        if "=" not in kv:
            print(f"--arg wants key=value, got {kv!r}", file=sys.stderr)
            return 2
        k, _, v = kv.partition("=")
        script_args[k] = v

    carnot = _build_demo_cluster(args.warm)
    conn = Client().connect_to_cluster(carnot)

    t0 = time.perf_counter()
    if os.path.exists(args.script) and args.script.endswith(".pxl"):
        with open(args.script) as f:
            pxl = f.read()
        result = conn._execute(pxl, script_args or None)
    else:
        if args.script not in ScriptLibrary().names():
            print(
                f"unknown script {args.script!r}; "
                f"try: {', '.join(ScriptLibrary().names())}",
                file=sys.stderr,
            )
            return 2
        result = conn.run_script(args.script, script_args)
    dt = time.perf_counter() - t0
    for name in sorted(result.tables):
        _render_table(name, result.tables[name], limit=args.limit)
    print(f"\n[{dt * 1e3:.0f} ms]")
    return 0


def cmd_live(args) -> int:
    """`px live <script>` — the reference's interactive refresh loop
    (src/pixie_cli/pkg/live/): re-runs the script every --interval and
    renders sortable, scrollable tables in a curses TUI."""
    from pixie_tpu.api import Client
    from pixie_tpu.live import run_live
    from pixie_tpu.scripts.library import ScriptLibrary

    script_args = {}
    for kv in args.arg or []:
        if "=" not in kv:
            print(f"--arg wants key=value, got {kv!r}", file=sys.stderr)
            return 2
        k, _, v = kv.partition("=")
        script_args[k] = v
    carnot = _build_demo_cluster(args.warm)
    conn = Client().connect_to_cluster(carnot)
    if os.path.exists(args.script) and args.script.endswith(".pxl"):
        with open(args.script) as f:
            pxl = f.read()
        execute = lambda: conn._execute(pxl, script_args or None)
    else:
        if args.script not in ScriptLibrary().names():
            print(f"unknown script {args.script!r}", file=sys.stderr)
            return 2
        execute = lambda: conn.run_script(args.script, script_args)
    run_live(
        execute,
        interval_s=args.interval,
        max_refreshes=args.max_refreshes,
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="px", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("scripts", help="script bundle operations")
    pssub = ps.add_subparsers(dest="scripts_cmd", required=True)
    pssub.add_parser("list", help="list bundled scripts").set_defaults(
        fn=cmd_scripts_list
    )

    pr = sub.add_parser("run", help="run a bundled script or .pxl file")
    pr.add_argument("script", help="script name (px/...) or path to .pxl")
    pr.add_argument(
        "--arg", action="append", help="script arg key=value", default=[]
    )
    pr.add_argument(
        "--warm",
        type=float,
        default=1.5,
        help="seconds of synthetic telemetry to collect first",
    )
    pr.add_argument("--limit", type=int, default=50, help="max rows printed")
    pr.set_defaults(fn=cmd_run)

    pl = sub.add_parser(
        "live", help="interactive live view (re-runs the script)"
    )
    pl.add_argument("script", help="script name (px/...) or path to .pxl")
    pl.add_argument(
        "--arg", action="append", help="script arg key=value", default=[]
    )
    pl.add_argument("--warm", type=float, default=1.5)
    pl.add_argument(
        "--interval", type=float, default=2.0, help="refresh seconds"
    )
    pl.add_argument(
        "--max-refreshes",
        type=int,
        default=None,
        help="exit after N refreshes (for scripted runs)",
    )
    pl.set_defaults(fn=cmd_live)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
