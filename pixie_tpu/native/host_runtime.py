"""ctypes bindings for host_runtime.cc (built lazily, cached by source
hash). Raises at import when no toolchain is available — callers catch
and fall back to numpy."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "host_runtime.cc")


def _build() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_DIR, f"_host_runtime_{digest}.so")
    if os.path.exists(so_path):
        return so_path
    # Stale builds from older sources are superseded, not reused.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    try:
        subprocess.run(
            [
                "g++", "-O3", "-march=native", "-std=c++17", "-shared",
                "-fPIC", _SRC, "-o", tmp,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, so_path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return so_path


_lib = ctypes.CDLL(_build())

_lib.fnv1a64_batch.argtypes = [
    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
]
_lib.fnv1a64_batch.restype = None
_lib.dict_encode_fixed.argtypes = [
    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
    ctypes.c_void_p, ctypes.c_int64,
    ctypes.c_void_p, ctypes.c_void_p,
]
_lib.dict_encode_fixed.restype = ctypes.c_int64


def _ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.c_void_p)


def fnv1a64_batch(strings) -> np.ndarray:
    """FNV-1a of each string's utf-8 bytes — bit-identical to the Python
    _fnv1a64 fallback."""
    encoded = [s.encode("utf-8") for s in strings]
    offsets = np.zeros(len(encoded) + 1, np.int64)
    np.cumsum([len(b) for b in encoded], out=offsets[1:])
    buf = np.frombuffer(b"".join(encoded), dtype=np.uint8)
    out = np.empty(len(encoded), np.uint64)
    if len(encoded):
        _lib.fnv1a64_batch(
            _ptr(np.ascontiguousarray(buf)) if buf.size else None,
            _ptr(offsets), len(encoded), _ptr(out),
        )
    return out


def encode_with_dict(values: np.ndarray, dict_values: list[str], u=None):
    """(codes int32, new_values list[str]): encode a string column against
    an existing dictionary; unseen values get fresh codes in
    first-occurrence order. Strings ride numpy's fixed-width U layout so
    the C++ side compares raw bytes. ``u`` lets callers reuse an already-
    converted fixed-width copy of ``values``."""
    arr = np.asarray(values, dtype=object)
    n = len(arr)
    if u is None:
        u = arr.astype("U")  # fixed-width UTF-32, C-speed conversion
    # Natural widths FIRST, then widen both to the common width — forcing
    # the dictionary into the batch's width would silently truncate longer
    # dictionary entries (and then alias their prefixes).
    dict_u = np.asarray(dict_values, dtype="U")
    width = max(u.dtype.itemsize, dict_u.dtype.itemsize, 4)
    if u.dtype.itemsize < width:
        u = u.astype(f"U{width // 4}")
    if dict_u.dtype.itemsize < width:
        dict_u = dict_u.astype(f"U{width // 4}")
    u = np.ascontiguousarray(u)
    dict_u = np.ascontiguousarray(dict_u)
    codes = np.empty(n, np.int32)
    new_rows = np.empty(n, np.int64)
    if n == 0:
        return codes, []
    n_new = _lib.dict_encode_fixed(
        _ptr(u), n, width,
        _ptr(dict_u) if len(dict_u) else None, len(dict_u),
        _ptr(codes), _ptr(new_rows),
    )
    new_values = [str(arr[i]) for i in new_rows[:n_new]]
    return codes, new_values
