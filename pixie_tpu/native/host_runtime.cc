// Native host runtime: the CPU-side hot loops that the reference
// implements in C++ (table_store write-side encoding, row hashing —
// src/table_store/, src/carnot/exec/row_tuple.h). The TPU build keeps
// JAX/XLA for device compute; this library serves the ingest path, where
// dictionary-encoding telemetry strings per batch dominates table writes.
//
// C ABI only (loaded via ctypes — no pybind11 in the image). All buffers
// are caller-allocated numpy arrays.

#include <cstddef>
#include <cstdint>
#include <vector>

namespace {

constexpr uint64_t kFnvOffset = 0xCBF29CE484222325ULL;
constexpr uint64_t kFnvPrime = 0x100000001B3ULL;

inline uint64_t fnv1a(const uint8_t* p, int64_t len) {
  uint64_t h = kFnvOffset;
  for (int64_t i = 0; i < len; ++i) {
    h = (h ^ p[i]) * kFnvPrime;
  }
  return h;
}

inline bool row_eq(const uint8_t* a, const uint8_t* b, int64_t itemsize) {
  for (int64_t i = 0; i < itemsize; ++i) {
    if (a[i] != b[i]) return false;
  }
  return true;
}

}  // namespace

extern "C" {

// FNV-1a over variable-length utf-8 slices (bit-identical to the Python
// fallback pixie_tpu/table/column.py:_fnv1a64). offsets has n+1 entries.
void fnv1a64_batch(const uint8_t* buf, const int64_t* offsets, int64_t n,
                   uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = fnv1a(buf + offsets[i], offsets[i + 1] - offsets[i]);
  }
}

// Dictionary-encode n fixed-width rows against an existing dictionary of
// dict_n fixed-width rows (same itemsize; numpy "U" layout — equality on
// raw bytes is equality on strings since widths match). Existing values
// keep their codes; unseen values get dict_n, dict_n+1, ... in
// first-occurrence order. out_codes[n]; out_new_rows receives the data-row
// index of each new value's first occurrence. Returns the new-value count.
int64_t dict_encode_fixed(const uint8_t* data, int64_t n, int64_t itemsize,
                          const uint8_t* dict_data, int64_t dict_n,
                          int32_t* out_codes, int64_t* out_new_rows) {
  // Open-addressed table of codes, sized for dict + worst-case all-new.
  int64_t cap = 16;
  while (cap < (n + dict_n + 1) * 2) cap <<= 1;
  const uint64_t mask = static_cast<uint64_t>(cap - 1);
  std::vector<int32_t> slots(static_cast<size_t>(cap), -1);

  auto row_of = [&](int32_t code) -> const uint8_t* {
    return code < dict_n
               ? dict_data + static_cast<int64_t>(code) * itemsize
               : data + out_new_rows[code - dict_n] * itemsize;
  };

  // Seed with the existing dictionary (codes 0..dict_n-1).
  for (int64_t d = 0; d < dict_n; ++d) {
    const uint8_t* p = dict_data + d * itemsize;
    uint64_t h = fnv1a(p, itemsize) & mask;
    while (slots[h] >= 0) {
      if (row_eq(row_of(slots[h]), p, itemsize)) break;  // dup in dict
      h = (h + 1) & mask;
    }
    if (slots[h] < 0) slots[h] = static_cast<int32_t>(d);
  }

  int64_t n_new = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* p = data + i * itemsize;
    uint64_t h = fnv1a(p, itemsize) & mask;
    int32_t code = -1;
    while (true) {
      int32_t cur = slots[h];
      if (cur < 0) {
        code = static_cast<int32_t>(dict_n + n_new);
        out_new_rows[n_new++] = i;
        slots[h] = code;
        break;
      }
      if (row_eq(row_of(cur), p, itemsize)) {
        code = cur;
        break;
      }
      h = (h + 1) & mask;
    }
    out_codes[i] = code;
  }
  return n_new;
}

}  // extern "C"
