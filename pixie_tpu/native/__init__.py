"""Native host runtime loader.

Compiles host_runtime.cc with the system toolchain on first import
(cached as a .so next to the source, keyed by a source hash) and exposes
it via ctypes. Importers must tolerate ImportError: every native entry
point has a pure-numpy fallback, so a missing compiler only costs speed
(the reference hard-requires its C++ runtime; ours degrades).
"""

from pixie_tpu.native import host_runtime

__all__ = ["host_runtime"]
