"""Per-query execution state & function context.

Ref: src/carnot/exec/exec_state.h — holds the table store, UDF registry,
function context (metadata state for md UDFs), and query-scoped control
(source aborts from limits, result destinations).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

from pixie_tpu.utils import trace


class QueryDeadlineExceeded(TimeoutError):
    """A query's propagated hard deadline expired (ref: the forwarder's
    per-query timeout/cancel, query_result_forwarder.go:571). Distinct
    from a source-stall TimeoutError so agents can annotate the failure
    kind for the broker's degraded result."""


@dataclasses.dataclass
class FunctionContext:
    """Passed to UDFs with ``needs_ctx`` (ref: udf.h FunctionContext) —
    carries the agent's metadata state for k8s entity lookups, plus the
    introspection surfaces UDTFs read (ref: vizier/funcs/md_udtfs serves
    GetAgentStatus/table info from the service context)."""

    metadata_state: Any = None
    table_store: Any = None
    registry: Any = None
    # Cluster view for agent-status UDTFs: an object exposing
    # ``agents() -> list[dict]`` (the broker's tracker) and/or
    # ``self_info: dict`` (this agent). None outside a vizier deployment.
    vizier_ctx: Any = None


class ExecState:
    def __init__(
        self,
        query_id: str,
        table_store,
        registry,
        router=None,
        metadata_state=None,
        result_callback: Optional[Callable] = None,
        instance: str = "local",
        compute_backend: str = "cpu",
        vizier_ctx: Any = None,
        otel_exporter: Any = None,
        deadline: Optional[float] = None,
        bridge_token: Optional[tuple] = None,
    ):
        self.query_id = query_id
        self.table_store = table_store
        self.registry = registry
        self.router = router
        self.func_ctx = FunctionContext(
            metadata_state,
            table_store=table_store,
            registry=registry,
            vizier_ctx=vizier_ctx,
        )
        # result_callback(table_name, row_batch) receives ResultSink output
        # (ref: Carnot's result destination / TransferResultChunk stream).
        self.result_callback = result_callback
        # OTel payload consumer (ref: the OTLP gRPC stub in the reference's
        # otel_export_sink_node); None drops exports.
        self.otel_exporter = otel_exporter
        self.instance = instance
        # The exec-graph is the host-side (PEM-role) engine: its eager jax
        # ops run on CPU so a remote-TPU default backend never sees per-op
        # RPCs. TPU compute goes exclusively through the compiled/staged
        # pipeline (pixie_tpu.parallel), one jit program per query.
        self.compute_backend = compute_backend
        # Batches substituted by another executor (device pipeline results),
        # keyed by InlineSourceOp.key.
        self.inline_batches: dict[str, list] = {}
        self._keep_running = True
        # Hard per-query deadline (time.monotonic() timestamp) propagated
        # from the broker (r9). None = no deadline; the stall timeout is
        # then the only guard.
        self.deadline = deadline
        # Set by cancel(): why this query was aborted (deadline, broker
        # cancellation, source stall) — surfaced in errors/annotations.
        self.cancel_reason: Optional[str] = None
        # Trace context (r11): captured at construction so nodes running
        # on other threads (and the exec graph's end-of-run per-node span
        # emission) can parent to the fragment span even off this thread.
        self.trace_ctx: Optional[tuple] = trace.current()
        # Fragment-failover attempt identity (r17): the broker-assigned
        # (slot, epoch) this execution runs as. BridgeSink pushes carry
        # it (held + committed atomically per attempt at the router) and
        # BridgeSource polls read through a per-attempt cursor so a
        # replacement consumer replays the committed stream. None = the
        # pre-r17 direct push/pop semantics.
        self.bridge_token = bridge_token

    def compute_device(self):
        if self.compute_backend is None:
            return None
        try:
            import jax

            return jax.local_devices(backend=self.compute_backend)[0]
        except Exception:
            return None

    # -- limit/source abort (ref: exec_state keep-running + limit signal) ---
    def stop_sources(self) -> None:
        self._keep_running = False

    @property
    def keep_running(self) -> bool:
        return self._keep_running

    # -- cancellation + deadlines (r9) --------------------------------------
    def cancel(self, reason: str) -> None:
        """Abort the query: stop sources and record why. Sibling nodes in
        the graph observe keep_running; the graph's abort path also closes
        sinks and releases bridge consumers."""
        if self.cancel_reason is None:
            self.cancel_reason = reason
        self._keep_running = False

    def deadline_exceeded(self) -> bool:
        return self.deadline is not None and time.monotonic() > self.deadline

    def check_deadline(self) -> None:
        if self.deadline_exceeded():
            raise QueryDeadlineExceeded(
                f"query {self.query_id}: deadline exceeded"
                + (f" ({self.cancel_reason})" if self.cancel_reason else "")
            )
