"""Execution engine: operator nodes, expression evaluation, exec graph.

Ref: src/carnot/exec/ — ExecNode lifecycle + stats (exec_node.h),
ExecutionGraph pull-on-source/push-downstream loop (exec_graph.cc),
operator nodes, expression evaluator, GRPC router (here: bridge router).
"""

from pixie_tpu.exec.exec_node import ExecNode, ExecNodeStats
from pixie_tpu.exec.exec_state import (
    ExecState,
    FunctionContext,
    QueryDeadlineExceeded,
)
from pixie_tpu.exec.exec_graph import ExecutionGraph
from pixie_tpu.exec.expression_evaluator import ExpressionEvaluator
from pixie_tpu.exec.group_encoder import GroupEncoder
from pixie_tpu.exec.router import BridgeCancelled, BridgeRouter

__all__ = [
    "BridgeCancelled",
    "BridgeRouter",
    "ExecNode",
    "ExecNodeStats",
    "ExecState",
    "ExecutionGraph",
    "ExpressionEvaluator",
    "FunctionContext",
    "GroupEncoder",
    "QueryDeadlineExceeded",
]
