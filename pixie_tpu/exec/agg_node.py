"""Hash-aggregate node via dense gids + segment reductions.

Ref: src/carnot/exec/agg_node.{h,cc} — the reference keeps an absl hash map
of RowTuple→AggHashValue with per-group UDA object instances, updated
row-at-a-time (HashRowBatch → UDA::Update), emitting on eos/eow
(ConvertAggHashMapToRowBatch), with a partial-aggregate serialize path for
the PEM→Kelvin split (EvaluatePartialAggregates).

TPU re-design: group keys densify host-side to int32 gids (GroupEncoder);
UDA state is a pytree with a leading [capacity] group axis updated by one
vectorized `uda.update(state, gids, *cols)` per batch — a masked XLA segment
reduction, not a per-row loop. Capacity grows by doubling (concat with
`uda.init(extra)`, which is the merge identity by UDA contract). The partial
stage emits a StateBatch (keys + state pytrees); the merge stage scatter-
aligns incoming states onto local gids and folds with `uda.merge` — one code
path for every MergeKind, since init == merge identity.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import numpy as np

from pixie_tpu.exec.exec_node import ExecNode
from pixie_tpu.exec.group_encoder import GroupEncoder
from pixie_tpu.plan.expressions import ColumnRef, expr_data_type
from pixie_tpu.plan.operators import AggOp, AggStage
from pixie_tpu.table.column import DictColumn, StringDictionary
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.types import DataType, Relation

INITIAL_CAPACITY = 256


@dataclasses.dataclass
class StateBatch:
    """Partial-aggregate handoff between fragments (ref: the serialized
    partial-agg row batches of partial_op_mgr.h:94). Carries group keys in
    gid order plus per-value state pytrees sliced to num_groups."""

    key_columns: list  # per group col: np.ndarray or DictColumn, len num_groups
    states: dict[str, Any]  # out_name -> pytree with leading [num_groups]
    num_groups: int
    group_names: tuple[str, ...]
    eow: bool = False
    eos: bool = False
    # Producer-side latched dictionaries for string_state UDAs, keyed by the
    # UDA's output name; the merge stage translates incoming code states
    # through these into its own latch (codes are agent-local otherwise).
    arg_dicts: dict = dataclasses.field(default_factory=dict)

    # -- wire format (PEM→Kelvin partial-agg transfer over DCN; ref: the
    # serialized partial aggregates of partial_op_mgr.h:94 riding
    # TransferResultChunk) -------------------------------------------------
    def to_bytes(self) -> bytes:
        import io

        arrays: dict[str, np.ndarray] = {}
        counter = iter(range(1 << 30))

        def attach(arr: np.ndarray) -> str:
            # Opaque names: path-derived keys can collide (a dotted user
            # column name vs a nested state key), silently overwriting
            # leaves in the npz payload.
            name = f"a{next(counter)}"
            arrays[name] = arr
            return name

        def enc(obj):
            """Pytree -> JSON-able descriptor + numpy attachments."""
            if isinstance(obj, np.ndarray):
                return {"arr": attach(obj)}
            if isinstance(obj, dict):
                return {"dict": {k: enc(v) for k, v in obj.items()}}
            if isinstance(obj, (tuple, list)):
                return {
                    "seq": [enc(v) for v in obj],
                    "tuple": isinstance(obj, tuple),
                }
            if hasattr(obj, "__array__"):  # jax arrays and scalars
                return {"arr": attach(np.asarray(obj))}
            return {"val": obj}

        keys = []
        for i, col in enumerate(self.key_columns):
            if isinstance(col, DictColumn):
                arrays[f"k{i}"] = np.asarray(col.decode().tolist(), dtype="U")
                keys.append({"kind": "str", "arr": f"k{i}"})
            else:
                arrays[f"k{i}"] = np.asarray(col)
                keys.append({"kind": "plain", "arr": f"k{i}"})
        dicts = {}
        for name, d in self.arg_dicts.items():
            arrays[f"d:{name}"] = np.asarray(
                list(d.values()), dtype="U"
            )
            dicts[name] = f"d:{name}"
        meta = {
            "num_groups": int(self.num_groups),
            "group_names": list(self.group_names),
            "eow": bool(self.eow),
            "eos": bool(self.eos),
            "keys": keys,
            "states": {
                name: enc(tree) for name, tree in self.states.items()
            },
            "arg_dicts": dicts,
        }
        buf = io.BytesIO()
        np.savez_compressed(
            buf,
            __meta__=np.frombuffer(repr(meta).encode(), dtype=np.uint8),
            **arrays,
        )
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "StateBatch":
        import ast
        import io

        with np.load(io.BytesIO(data), allow_pickle=False) as npz:
            meta = ast.literal_eval(bytes(npz["__meta__"]).decode())

            def dec(node):
                if "arr" in node:
                    return npz[node["arr"]]
                if "dict" in node:
                    return {k: dec(v) for k, v in node["dict"].items()}
                if "seq" in node:
                    seq = [dec(v) for v in node["seq"]]
                    return tuple(seq) if node["tuple"] else seq
                return node["val"]

            key_columns = []
            for k in meta["keys"]:
                arr = npz[k["arr"]]
                if k["kind"] == "str":
                    d = StringDictionary()
                    key_columns.append(
                        DictColumn(d.encode(arr.astype(object)), d)
                    )
                else:
                    key_columns.append(arr)
            arg_dicts = {
                name: StringDictionary(
                    list(npz[path].astype(object))
                )
                for name, path in meta["arg_dicts"].items()
            }
            return cls(
                key_columns=key_columns,
                states={
                    name: dec(node) for name, node in meta["states"].items()
                },
                num_groups=meta["num_groups"],
                group_names=tuple(meta["group_names"]),
                eow=meta["eow"],
                eos=meta["eos"],
                arg_dicts=arg_dicts,
            )

    def __reduce__(self):
        # Pickling rides the explicit wire format: a cross-process transport
        # that pickles bus messages moves no live object graphs, only the
        # same bytes a proto-based data plane would.
        return (StateBatch.from_bytes, (self.to_bytes(),))


@dataclasses.dataclass
class _AggSpec:
    out_name: str
    uda: Any
    arg_names: tuple[str, ...]


class AggNode(ExecNode):
    def __init__(self, op: AggOp, output_relation: Relation, node_id: int):
        super().__init__(op, output_relation, node_id)
        self.op: AggOp = op
        self._specs: list[_AggSpec] = []
        self._encoder = GroupEncoder()
        self._capacity = INITIAL_CAPACITY if op.groups else 1
        self._states: dict[str, Any] = {}
        self._key_dicts: dict[str, Optional[StringDictionary]] = {}
        # name -> (live source dictionary, snapshot length at latch time)
        self._key_dict_sources: dict[str, tuple] = {}
        self._input_relation: Optional[Relation] = None

    # -- lifecycle ----------------------------------------------------------
    def prepare_impl(self, exec_state) -> None:
        pass

    def set_input_relation(self, rel: Relation, registry) -> None:
        self._input_relation = rel
        if self.op.stage == AggStage.MERGE and self.op.pre_agg_relation is not None:
            rel = self.op.pre_agg_relation  # resolve UDAs as PARTIAL did
        self._specs = []
        for out_name, agg in self.op.values:
            arg_types = [expr_data_type(a, rel, registry) for a in agg.args]
            uda = registry.lookup_uda(agg.name, arg_types)
            if uda is None:
                raise ValueError(
                    f"no aggregate {agg.name}"
                    f"({', '.join(t.name for t in arg_types)})"
                )
            names = []
            for a in agg.args:
                if not isinstance(a, ColumnRef):
                    raise ValueError(
                        "aggregate args must be column refs (the compiler "
                        "hoists computed args into a Map)"
                    )
                names.append(a.name)
            self._specs.append(_AggSpec(out_name, uda, tuple(names)))
        self._states = {
            s.out_name: s.uda.init(self._capacity) for s in self._specs
        }

    # -- consume ------------------------------------------------------------
    def consume_next_impl(self, exec_state, batch, parent_index: int) -> None:
        if isinstance(batch, StateBatch):
            self._consume_states(batch)
            if batch.eos or (batch.eow and self.op.windowed):
                self._emit(exec_state, eow=batch.eow, eos=batch.eos)
            return
        assert isinstance(batch, RowBatch)
        if batch.num_rows:
            gids = self._gids_for(batch)
            self._ensure_capacity(self._encoder.num_groups or 1)
            for spec in self._specs:
                cols = [
                    self._arg_array(batch, n, spec.uda.string_args)
                    for n in spec.arg_names
                ]
                self._states[spec.out_name] = spec.uda.update(
                    self._states[spec.out_name], gids, *cols
                )
        if batch.eos or (batch.eow and self.op.windowed):
            self._emit(exec_state, eow=batch.eow, eos=batch.eos)

    def _latch_key_column(self, name: str, col):
        """Latch a PRIVATE snapshot of the first dictionary seen per string
        key column; re-encode cross-dictionary batches (e.g. across a union)
        into it so codes stay comparable. Snapshotting keeps encode() from
        polluting a live table's write-side dictionary with values from
        other tables/agents (code-review r2). Codes below the snapshot
        length are stable (dictionaries are append-only), so the common
        single-table case skips the re-encode."""
        if isinstance(col, DictColumn):
            existing = self._key_dicts.get(name)
            if existing is None:
                src = col.dictionary
                existing = StringDictionary(src.values())
                self._key_dicts[name] = existing
                self._key_dict_sources[name] = (src, len(existing))
            src_info = self._key_dict_sources.get(name)
            if (
                src_info is not None
                and col.dictionary is src_info[0]
                and (len(col.codes) == 0 or int(col.codes.max()) < src_info[1])
            ):
                return DictColumn(col.codes, existing)
            if col.dictionary is not existing:
                col = DictColumn(existing.encode(col.decode()), existing)
        return col

    def _gids_for(self, batch: RowBatch) -> np.ndarray:
        if not self.op.groups:
            return np.zeros(batch.num_rows, np.int32)
        key_cols = [
            self._latch_key_column(g, batch.col(g)) for g in self.op.groups
        ]
        return self._encoder.encode(key_cols)

    def _arg_array(self, batch: RowBatch, name: str, mode: str):
        col = batch.col(name)
        if isinstance(col, DictColumn):
            if mode == "hash":
                # Dictionary-independent identity: hash the (tiny) dictionary
                # once, gather through codes. int64 view keeps x64 jnp happy.
                hashes = col.dictionary.content_hashes().view(np.int64)
                return hashes[col.codes]
            if mode == "values":
                # Decoded string values (host-only UDAs that must parse
                # content, e.g. kmeans over JSON embeddings — the device
                # matcher rejects this mode so it never ships to HBM).
                return col.decode()
            col = self._latch_key_column(name, col)
            return col.codes
        return col

    def _ensure_capacity(self, needed: int) -> None:
        while self._capacity < needed:
            extra = self._capacity
            for spec in self._specs:
                grown = spec.uda.init(extra)
                self._states[spec.out_name] = jax.tree.map(
                    lambda a, b: np.concatenate([np.asarray(a), np.asarray(b)]),
                    self._states[spec.out_name],
                    grown,
                )
            self._capacity += extra

    # -- merge stage --------------------------------------------------------
    def _consume_states(self, sb: StateBatch) -> None:
        if sb.num_groups == 0:
            return
        if self.op.groups:
            key_cols = [
                self._latch_key_column(g, col)
                for g, col in zip(sb.group_names, sb.key_columns)
            ]
            idx = self._encoder.encode(key_cols)
        else:
            idx = np.zeros(sb.num_groups, np.int32)
        self._ensure_capacity(self._encoder.num_groups or 1)
        for spec in self._specs:
            incoming = sb.states[spec.out_name]
            if spec.uda.string_state and spec.out_name in sb.arg_dicts:
                incoming = self._translate_state_codes(
                    spec.arg_names[0], incoming, sb.arg_dicts[spec.out_name]
                )
            aligned = jax.tree.map(
                lambda z, inc: jax.numpy.asarray(z).at[idx].set(
                    jax.numpy.asarray(inc)
                ),
                spec.uda.init(self._capacity),
                incoming,
            )
            self._states[spec.out_name] = spec.uda.merge(
                self._states[spec.out_name], aligned
            )

    def _translate_state_codes(self, name: str, codes, incoming_dict):
        """Map a code-valued state from the producer's dictionary into this
        node's latch (adopting the producer's dictionary when nothing is
        latched yet). Sentinel/out-of-range codes pass through untouched."""
        existing = self._key_dicts.get(name)
        if existing is None:
            self._key_dicts[name] = incoming_dict
            return codes
        if existing is incoming_dict:
            return codes
        out = np.asarray(codes).copy()
        valid = (out >= 0) & (out < len(incoming_dict))
        if valid.any():
            out[valid] = existing.encode(
                incoming_dict.decode(out[valid].astype(np.int32))
            )
        return out

    # -- emit ---------------------------------------------------------------
    def _num_out_groups(self) -> int:
        if self.op.groups:
            return self._encoder.num_groups
        return 1  # group-by-none emits one row even on empty input (ref)

    def _emit(self, exec_state, eow: bool, eos: bool) -> None:
        n = self._num_out_groups()
        if self.op.stage == AggStage.PARTIAL:
            sliced = {
                s.out_name: jax.tree.map(
                    lambda a: np.asarray(a)[:n], self._states[s.out_name]
                )
                for s in self._specs
            }
            arg_dicts = {}
            for s in self._specs:
                if s.uda.string_state:
                    d = self._key_dicts.get(s.arg_names[0])
                    if d is not None:
                        # Copy: the consumer may encode into this dictionary
                        # (merge translation); never hand out our latch.
                        arg_dicts[s.out_name] = StringDictionary(d.values())
            self.send(
                exec_state,
                StateBatch(
                    key_columns=self._key_columns(),
                    states=sliced,
                    num_groups=n,
                    group_names=self.op.groups,
                    eow=eow,
                    eos=eos,
                    arg_dicts=arg_dicts,
                ),
            )
        else:
            self.send(exec_state, self._finalized_batch(n, eow=eow, eos=eos))
        if eow and not eos:
            self._reset_window()

    def _key_columns(self) -> list:
        arrays = self._encoder.key_arrays()
        if not arrays and self.op.groups:
            # Zero rows ever consumed: the encoder latched nothing, but the
            # output relation still has one (empty) column per group key.
            arrays = [np.empty(0, np.int64) for _ in self.op.groups]
        cols = []
        for g, arr in zip(self.op.groups, arrays):
            d = self._key_dicts.get(g)
            if d is not None:
                cols.append(DictColumn(arr.astype(np.int32), d))
            elif (
                self.output_relation.has_column(g)
                and self.output_relation.col(g).data_type == DataType.STRING
            ):
                cols.append(
                    DictColumn(arr.astype(np.int32), StringDictionary())
                )
            else:
                cols.append(arr)
        return cols

    def _finalized_batch(self, n: int, eow: bool, eos: bool) -> RowBatch:
        out_cols: list = []
        rel = self.output_relation
        key_cols = self._key_columns()
        for g, col in zip(self.op.groups, key_cols):
            schema = rel.col(g)
            if isinstance(col, DictColumn):
                out_cols.append(col)
            else:
                from pixie_tpu.types.dtypes import host_dtype

                out_cols.append(np.asarray(col, dtype=host_dtype(schema.data_type)))
        for spec in self._specs:
            # np, not jnp: object-dtype leaves (string-bearing host UDAs
            # like _build_request_path_clusters) are not jax arrays.
            state = jax.tree.map(
                lambda a: np.asarray(a)[:n], self._states[spec.out_name]
            )
            out = spec.uda.finalize(state)
            schema = rel.col(spec.out_name)
            if schema.data_type == DataType.STRING:
                if spec.uda.string_state:
                    latched = self._key_dicts.get(spec.arg_names[0])
                    codes = np.asarray(out)
                    if latched is None:
                        vals = np.full(len(codes), "", dtype=object)
                    else:
                        vals = latched.decode(codes)
                else:
                    vals = np.asarray(out, dtype=object)
                d = StringDictionary()
                out_cols.append(DictColumn(d.encode(vals), d))
            else:
                from pixie_tpu.types.dtypes import host_dtype

                out_cols.append(
                    np.asarray(out, dtype=host_dtype(schema.data_type))
                )
        return RowBatch(rel, out_cols, eow=eow, eos=eos)

    def _reset_window(self) -> None:
        self._encoder.reset()
        self._key_dicts.clear()
        self._key_dict_sources.clear()
        self._capacity = INITIAL_CAPACITY if self.op.groups else 1
        self._states = {
            s.out_name: s.uda.init(self._capacity) for s in self._specs
        }
