"""OTel export sink node.

Ref: src/carnot/exec/otel_export_sink_node.{h,cc} — converts row batches
into OpenTelemetry metrics/spans and ships them over OTLP gRPC. Here the
conversion targets the OTLP/JSON data model (resourceMetrics /
resourceSpans payload dicts) and hands each payload to the engine's
pluggable exporter (``exec_state.otel_exporter``) — an in-memory
collector by default; a network OTLP/HTTP exporter is a drop-in callable
(zero-egress environments keep the collector).
"""

from __future__ import annotations

import numpy as np

from pixie_tpu.exec.exec_node import SinkNode
from pixie_tpu.plan.operators import OTelExportSinkOp
from pixie_tpu.table.row_batch import RowBatch


def _attr_list(pairs) -> list:
    return [
        {"key": k, "value": {"stringValue": str(v)}} for k, v in pairs
    ]


class OTelExportSinkNode(SinkNode):
    def __init__(self, op: OTelExportSinkOp, output_relation, node_id):
        super().__init__(op, output_relation, node_id)
        self.op: OTelExportSinkOp = op

    def consume_next_impl(self, exec_state, batch, parent_index) -> None:
        if not isinstance(batch, RowBatch) or not batch.num_rows:
            return
        exporter = getattr(exec_state, "otel_exporter", None)
        if exporter is None:
            return
        d = batch.to_pydict()
        n = batch.num_rows

        def col(name):
            return d[name]

        # Rows group by their RESOURCE identity (column-valued resource
        # attributes vary per row — the reference emits one resource entry
        # per distinct value, never the first row's value for all).
        res_cols = [(k, v) for k, v, is_col in self.op.resource if is_col]
        res_consts = [
            (k, v) for k, v, is_col in self.op.resource if not is_col
        ]
        groups: dict[tuple, list[int]] = {}
        for i in range(n):
            key = tuple(col(c)[i] for _, c in res_cols)
            groups.setdefault(key, []).append(i)

        payload: dict = {}
        res_metrics, res_spans = [], []
        for key, rows in groups.items():
            resource_attrs = _attr_list(
                [(k, v) for (k, _), v in zip(res_cols, key)] + res_consts
            )
            if self.op.metrics:
                metrics = []
                for spec in self.op.metrics:
                    spec = dict(spec)
                    points = []
                    times = col(spec["time_column"])
                    values = col(spec["value_column"])
                    attrs = spec.get("attributes", ())
                    for i in rows:
                        dp = {
                            "timeUnixNano": str(int(times[i])),
                            "attributes": _attr_list(
                                (k, col(c)[i]) for k, c in attrs
                            ),
                        }
                        v = values[i]
                        if isinstance(v, (int, np.integer)):
                            dp["asInt"] = str(int(v))
                        else:
                            dp["asDouble"] = float(v)
                        points.append(dp)
                    metrics.append(
                        {
                            "name": spec["name"],
                            "description": spec.get("description", ""),
                            "unit": spec.get("unit", ""),
                            "gauge": {"dataPoints": points},
                        }
                    )
                res_metrics.append(
                    {
                        "resource": {"attributes": resource_attrs},
                        "scopeMetrics": [{"metrics": metrics}],
                    }
                )
            if self.op.spans:
                spans = []
                for spec in self.op.spans:
                    spec = dict(spec)
                    starts = col(spec["start_time_column"])
                    ends = col(spec["end_time_column"])
                    names = (
                        col(spec["name_column"])
                        if spec.get("name_column")
                        else None
                    )
                    attrs = spec.get("attributes", ())
                    for i in rows:
                        spans.append(
                            {
                                "name": str(
                                    names[i]
                                    if names is not None
                                    else spec.get("name", "span")
                                ),
                                "startTimeUnixNano": str(int(starts[i])),
                                "endTimeUnixNano": str(int(ends[i])),
                                "attributes": _attr_list(
                                    (k, col(c)[i]) for k, c in attrs
                                ),
                            }
                        )
                res_spans.append(
                    {
                        "resource": {"attributes": resource_attrs},
                        "scopeSpans": [{"spans": spans}],
                    }
                )
        if res_metrics:
            payload["resourceMetrics"] = res_metrics
        if res_spans:
            payload["resourceSpans"] = res_spans
        if payload:
            # Endpoint travels OUT-OF-BAND: the payload stays a valid OTLP
            # ExportServiceRequest so `lambda p: post(url, json=p)` is a
            # drop-in exporter. Exporters that take a second parameter
            # receive the endpoint config.
            import inspect

            try:
                two_arg = (
                    len(inspect.signature(exporter).parameters) >= 2
                )
            except (TypeError, ValueError):  # builtins like deque.append
                two_arg = False
            if two_arg:
                exporter(payload, self.op.endpoint)
            else:
                exporter(payload)
