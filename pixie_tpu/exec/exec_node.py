"""ExecNode base classes with the reference's operator lifecycle and stats.

Ref: src/carnot/exec/exec_node.h — ExecNode (:133) lifecycle
Init/Prepare/Open/GenerateNext/ConsumeNext/Close; ProcessingNode (:343),
SourceNode (:353), SinkNode (:379); ExecNodeStats (:60-128) tracks
bytes/rows/batches in/out and self/total time, surfaced per-operator in
query execution stats (carnot.cc:369-399).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.types import Relation


@dataclasses.dataclass
class ExecNodeStats:
    bytes_in: int = 0
    rows_in: int = 0
    batches_in: int = 0
    bytes_out: int = 0
    rows_out: int = 0
    batches_out: int = 0
    total_time_ns: int = 0  # includes children's ConsumeNext time
    self_time_ns: int = 0   # total minus time spent in children

    def record_in(self, batch) -> None:
        if isinstance(batch, RowBatch):
            self.bytes_in += batch.num_bytes()
            self.rows_in += batch.num_rows
        self.batches_in += 1

    def record_out(self, batch) -> None:
        if isinstance(batch, RowBatch):
            self.bytes_out += batch.num_bytes()
            self.rows_out += batch.num_rows
        self.batches_out += 1

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ExecNode:
    """Base operator node.

    Subclasses implement ``init_impl``, ``consume_next_impl`` (processing &
    sink nodes) or ``generate_next_impl`` (source nodes), and optionally
    ``open_impl``/``close_impl``. The base wires child push-down and stats.
    """

    is_source = False
    is_sink = False
    # Whether this node emits rows in nondecreasing time_ order given
    # time-ordered inputs. Reordering operators (joins: unmatched rows trail
    # matched ones) override to False; ordered unions consult their
    # ancestry's flags to decide if incremental merge-emission is sound.
    preserves_time_order = True

    def __init__(self, op, output_relation: Relation, node_id: int):
        self.op = op
        self.output_relation = output_relation
        self.node_id = node_id
        # Outgoing dataflow edges: (child, parent_slot) — the slot is which
        # input of the child this node feeds (joins distinguish build/probe;
        # a self-join has two edges to the same child).
        self.child_edges: list[tuple["ExecNode", int]] = []
        self.stats = ExecNodeStats()
        self._closed = False
        self._sent_eos = False
        self._aborted = False

    @property
    def name(self) -> str:
        return f"{self.op.op_name}[{self.node_id}]"

    def add_child(self, child: "ExecNode", parent_slot: int = 0) -> None:
        self.child_edges.append((child, parent_slot))

    @property
    def children(self) -> list["ExecNode"]:
        return [c for c, _ in self.child_edges]

    # -- lifecycle (ref: exec_node.h Init/Prepare/Open/Close) ---------------
    def init(self, exec_state) -> None:
        self.init_impl(exec_state)

    def prepare(self, exec_state) -> None:
        self.prepare_impl(exec_state)

    def open(self, exec_state) -> None:
        self.open_impl(exec_state)

    def close(self, exec_state) -> None:
        if not self._closed:
            self._closed = True
            self.close_impl(exec_state)

    def init_impl(self, exec_state) -> None:
        pass

    def prepare_impl(self, exec_state) -> None:
        pass

    def open_impl(self, exec_state) -> None:
        pass

    def close_impl(self, exec_state) -> None:
        pass

    # -- dataflow -----------------------------------------------------------
    def consume_next(self, exec_state, batch, parent_index: int = 0) -> None:
        """Push a batch into this node (ref: ConsumeNext, exec_node.h:213)."""
        start = time.perf_counter_ns()
        self.stats.record_in(batch)
        child_ns_before = sum(c.stats.total_time_ns for c in self.children)
        self.consume_next_impl(exec_state, batch, parent_index)
        child_ns = sum(c.stats.total_time_ns for c in self.children) - child_ns_before
        elapsed = time.perf_counter_ns() - start
        self.stats.total_time_ns += elapsed
        self.stats.self_time_ns += max(0, elapsed - child_ns)

    def send(self, exec_state, batch) -> None:
        """Emit a batch to all children, tracking eos propagation."""
        self.stats.record_out(batch)
        if getattr(batch, "eos", False):
            self._sent_eos = True
        for child, slot in self.child_edges:
            child.consume_next(exec_state, batch, slot)

    def consume_next_impl(self, exec_state, batch, parent_index: int) -> None:
        raise NotImplementedError(f"{self.name} cannot consume")

    # -- sources ------------------------------------------------------------
    def generate_next(self, exec_state) -> bool:
        """Pull one batch from a source; returns True if progress was made
        (ref: GenerateNext, exec_node.h:194)."""
        start = time.perf_counter_ns()
        child_ns_before = sum(c.stats.total_time_ns for c in self.children)
        progressed = self.generate_next_impl(exec_state)
        child_ns = sum(c.stats.total_time_ns for c in self.children) - child_ns_before
        elapsed = time.perf_counter_ns() - start
        self.stats.total_time_ns += elapsed
        self.stats.self_time_ns += max(0, elapsed - child_ns)
        return progressed

    def generate_next_impl(self, exec_state) -> bool:
        raise NotImplementedError(f"{self.name} is not a source")

    def abort(self) -> None:
        """Stop a source early (ref: limit abort of abortable sources via
        annotate_abortable_sources_for_limits_rule). Only called on sources
        whose every path to a sink passes through the satisfied limit."""
        self._aborted = True

    def has_batches_remaining(self) -> bool:
        """Source liveness (ref: SourceNode::HasBatchesRemaining)."""
        return not self._sent_eos and not self._aborted

    def __repr__(self):
        return self.name


class SourceNode(ExecNode):
    is_source = True


class SinkNode(ExecNode):
    is_sink = True
