"""GroupEncoder — densify group-by keys into stable int32 segment ids.

The reference hashes RowTuples into an absl flat_hash_map per batch
(src/carnot/exec/agg_node.cc HashRowBatch / row_tuple.h). XLA has no dynamic
hash maps, so group keys are densified host-side into dense, stable gids that
feed TPU segment reductions (pixie_tpu/ops/segment.py). Vectorized: each
batch pays one np.unique over the key columns plus a dict probe per *new*
unique key — telemetry group keys (service, pod, endpoint) are vastly fewer
than rows.

Strings participate via their dictionary codes (already dense per table), so
the composite key is a small int matrix.
"""

from __future__ import annotations

import numpy as np

from pixie_tpu.table.column import DictColumn


class GroupEncoder:
    def __init__(self):
        self._gids: dict[tuple, int] = {}
        # Per key column: list of values aligned with gid order (for
        # reconstructing the output key columns at finalize).
        self._key_rows: list[tuple] = []

    @property
    def num_groups(self) -> int:
        return len(self._key_rows)

    @staticmethod
    def _key_rows_of(key_cols: list) -> tuple[int, "np.ndarray", "np.ndarray"]:
        """(n, unique_rows, inverse) via one np.unique. Integer multi-keys
        whose shifted widths fit 63 bits pack into ONE int64 first —
        np.unique on a plain int64 is a radix-class sort, while the
        structured-record fallback is a comparison sort that costs minutes
        at 64M rows (the r4 config-3 cold-path hotspot). Mixed/wide keys
        keep the record path — stacking would upcast int64/float64 keys to
        float64 and collapse keys beyond 2^53."""
        arrs = [
            c.codes if isinstance(c, DictColumn) else np.asarray(c)
            for c in key_cols
        ]
        n = len(arrs[0])
        if n == 0:
            return 0, np.empty(0), np.empty(0, np.int64)
        if len(arrs) == 1:
            uniq, inverse = np.unique(arrs[0], return_inverse=True)
            rows = [(v,) for v in uniq.tolist()]
            return n, rows, inverse
        packed = GroupEncoder._pack_int_keys(arrs)
        if packed is not None:
            key, mins, widths = packed
            total_bits = sum(widths)
            if total_bits <= 24:
                # Small packed range: O(n) bincount + rank LUT beats the
                # sort inside np.unique by ~10x at 64M rows.
                counts = np.bincount(key, minlength=1 << total_bits)
                uniq = np.nonzero(counts)[0]
                rank = np.full(1 << total_bits, -1, np.int32)
                rank[uniq] = np.arange(len(uniq), dtype=np.int32)
                inverse = rank[key]
            else:
                uniq, inverse = np.unique(key, return_inverse=True)
            rows_cols = []
            rem = uniq
            for lo, w in zip(reversed(mins), reversed(widths)):
                rows_cols.append((rem & ((1 << w) - 1)) + lo)
                rem = rem >> w
            rows_cols.reverse()
            rows = list(zip(*(c.tolist() for c in rows_cols)))
        else:
            rec = np.rec.fromarrays(arrs)
            uniq, inverse = np.unique(rec, return_inverse=True)
            rows = [tuple(r.tolist()) for r in uniq]
        return n, rows, inverse

    @staticmethod
    def _pack_int_keys(arrs):
        """(packed int64 key, per-col mins, per-col bit widths) when every
        column is integral and the shifted widths fit 63 bits; else None."""
        if not all(np.issubdtype(a.dtype, np.integer) for a in arrs):
            return None
        mins, widths = [], []
        for a in arrs:
            lo = int(a.min())
            hi = int(a.max())
            rng = hi - lo
            mins.append(lo)
            widths.append(max(rng.bit_length(), 1))
        if sum(widths) > 63:
            return None
        key = np.zeros(len(arrs[0]), np.int64)
        for a, lo, w in zip(arrs, mins, widths):
            key = (key << w) | (a.astype(np.int64) - lo)
        return key, mins, widths

    def encode(self, key_cols: list) -> np.ndarray:
        """Map rows of the given key columns to gids, assigning new ids to
        unseen keys. Returns int32[n]."""
        if not key_cols:
            raise ValueError("encode requires at least one key column")
        n, rows, inverse = self._key_rows_of(key_cols)
        if n == 0:
            return np.empty(0, np.int32)
        uniq_gids = np.empty(len(rows), np.int32)
        for i, key in enumerate(rows):
            gid = self._gids.get(key)
            if gid is None:
                gid = len(self._key_rows)
                self._gids[key] = gid
                self._key_rows.append(key)
            uniq_gids[i] = gid
        return uniq_gids[inverse.ravel()].astype(np.int32, copy=False)

    def lookup(self, key_cols: list) -> np.ndarray:
        """Like encode but maps unseen keys to -1 (no assignment)."""
        n, rows, inverse = self._key_rows_of(key_cols)
        if n == 0:
            return np.empty(0, np.int32)
        uniq_gids = np.fromiter(
            (self._gids.get(key, -1) for key in rows),
            dtype=np.int32,
            count=len(rows),
        )
        return uniq_gids[inverse.ravel()].astype(np.int32, copy=False)

    def key_arrays(self) -> list[np.ndarray]:
        """Per key column, the values in gid order (int arrays; string key
        columns come back as their dictionary codes). Columns materialize
        individually so mixed key dtypes keep full width."""
        if not self._key_rows:
            return []
        ncols = len(self._key_rows[0])
        return [
            np.asarray([r[i] for r in self._key_rows])
            for i in range(ncols)
        ]

    def reset(self) -> None:
        self._gids.clear()
        self._key_rows.clear()
