"""GroupEncoder — densify group-by keys into stable int32 segment ids.

The reference hashes RowTuples into an absl flat_hash_map per batch
(src/carnot/exec/agg_node.cc HashRowBatch / row_tuple.h). XLA has no dynamic
hash maps, so group keys are densified host-side into dense, stable gids that
feed TPU segment reductions (pixie_tpu/ops/segment.py). Vectorized: each
batch pays one np.unique over the key columns plus a dict probe per *new*
unique key — telemetry group keys (service, pod, endpoint) are vastly fewer
than rows.

Strings participate via their dictionary codes (already dense per table), so
the composite key is a small int matrix.
"""

from __future__ import annotations

import numpy as np

from pixie_tpu.table.column import DictColumn


class GroupEncoder:
    def __init__(self):
        self._gids: dict[tuple, int] = {}
        # Per key column: list of values aligned with gid order (for
        # reconstructing the output key columns at finalize).
        self._key_rows: list[tuple] = []

    @property
    def num_groups(self) -> int:
        return len(self._key_rows)

    def encode(self, key_cols: list) -> np.ndarray:
        """Map rows of the given key columns to gids, assigning new ids to
        unseen keys. Returns int32[n]."""
        if not key_cols:
            n = 0
            raise ValueError("encode requires at least one key column")
        arrs = [
            c.codes if isinstance(c, DictColumn) else np.asarray(c)
            for c in key_cols
        ]
        n = len(arrs[0])
        if n == 0:
            return np.empty(0, np.int32)
        # One np.unique over the stacked key matrix; probe dict per unique.
        stacked = np.stack(arrs, axis=1) if len(arrs) > 1 else arrs[0][:, None]
        uniq, inverse = np.unique(stacked, axis=0, return_inverse=True)
        uniq_gids = np.empty(len(uniq), np.int32)
        for i, row in enumerate(uniq):
            key = tuple(row.tolist())
            gid = self._gids.get(key)
            if gid is None:
                gid = len(self._key_rows)
                self._gids[key] = gid
                self._key_rows.append(key)
            uniq_gids[i] = gid
        return uniq_gids[inverse.ravel()].astype(np.int32, copy=False)

    def lookup(self, key_cols: list) -> np.ndarray:
        """Like encode but maps unseen keys to -1 (no assignment)."""
        arrs = [
            c.codes if isinstance(c, DictColumn) else np.asarray(c)
            for c in key_cols
        ]
        stacked = np.stack(arrs, axis=1) if len(arrs) > 1 else arrs[0][:, None]
        out = np.empty(len(stacked), np.int32)
        for i, row in enumerate(stacked):
            out[i] = self._gids.get(tuple(row.tolist()), -1)
        return out

    def key_arrays(self) -> list[np.ndarray]:
        """Per key column, the values in gid order (int arrays; string key
        columns come back as their dictionary codes)."""
        if not self._key_rows:
            return []
        mat = np.asarray(self._key_rows)
        return [mat[:, i] for i in range(mat.shape[1])]

    def reset(self) -> None:
        self._gids.clear()
        self._key_rows.clear()
