"""Scalar expression evaluation over RowBatches.

Ref: src/carnot/exec/expression_evaluator.{h,cc} — the reference has two
strategies (vector-native over ColumnWrapper vectors, arrow-native over
arrays). Ours also has two, split along the TPU boundary:

- **host path** (``evaluate``): eager evaluation with numpy/jax over a
  RowBatch; HOST-executor UDFs (strings/JSON/metadata) run here. String
  columns stay dictionary-encoded; ``dict_compatible`` host funcs run on the
  dictionary's (tiny) unique values and the result is gathered through the
  codes — the row-count work never touches Python strings.
- **device path** (``device_eval``): a pure-jnp evaluation over a dict of
  arrays, safe to call inside jit/shard_map (the mesh pipeline traces it).
  String semantics are code-space; host-func subtrees must have been
  precomputed into lookup tables by ``build_aux`` (host side, per staging).

String comparisons lower to int32 code comparisons (the write-side dictionary
encode in table/column.py guarantees code comparability within a table).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from pixie_tpu.plan.expressions import (
    ColumnRef,
    Constant,
    FuncCall,
    ScalarExpression,
    expr_data_type,
)
from pixie_tpu.table.column import DictColumn, StringDictionary
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.types import DataType, Relation
from pixie_tpu.types.dtypes import host_dtype
from pixie_tpu.udf.udf import Executor


class ExpressionEvaluator:
    """Evaluates named expressions (a Map's output list) or one predicate."""

    def __init__(
        self,
        named_exprs: list[tuple[str, ScalarExpression]],
        input_relation: Relation,
        registry,
        func_ctx=None,
    ):
        self.named_exprs = list(named_exprs)
        self.input_relation = input_relation
        self.registry = registry
        self.func_ctx = func_ctx
        self._resolved: dict[int, Any] = {}
        for _, e in self.named_exprs:
            self._resolve(e)

    def _resolve(self, expr) -> None:
        """Pre-resolve UDF lookups for every FuncCall in the tree."""
        if isinstance(expr, FuncCall):
            for a in expr.args:
                self._resolve(a)
            arg_types = [
                expr_data_type(a, self.input_relation, self.registry)
                for a in expr.args
            ]
            udf = self.registry.lookup_scalar(expr.name, arg_types)
            if udf is None:
                raise ValueError(
                    f"no scalar function {expr.name}"
                    f"({', '.join(t.name for t in arg_types)})"
                )
            self._resolved[id(expr)] = (udf, arg_types)

    # ------------------------------------------------------------------ host
    def evaluate(self, batch: RowBatch, output_relation: Relation) -> RowBatch:
        env = {
            schema.name: col
            for schema, col in zip(batch.relation, batch.columns)
        }
        out_cols = []
        for (name, e), schema in zip(self.named_exprs, output_relation):
            v = self._eval(e, env, batch.num_rows)
            out_cols.append(self._to_column(v, schema.data_type, batch.num_rows))
        return RowBatch(output_relation, out_cols, eow=batch.eow, eos=batch.eos)

    def evaluate_predicate(self, batch: RowBatch) -> np.ndarray:
        assert len(self.named_exprs) == 1
        v = self._eval(self.named_exprs[0][1], dict(
            zip(batch.relation.col_names(), batch.columns)
        ), batch.num_rows)
        return np.asarray(v, dtype=bool)

    def _to_column(self, v, data_type: DataType, num_rows: int):
        if isinstance(v, DictColumn):
            if len(v) == 1 and num_rows != 1:
                # Zero-arg funcs (px._exec_hostname) produce one value for
                # the whole batch: broadcast the code, keep the dictionary.
                return DictColumn(
                    np.broadcast_to(v.codes, (num_rows,)).copy(), v.dictionary
                )
            return v
        if data_type == DataType.STRING:
            if np.ndim(v) == 0:
                v = np.full(num_rows, v, dtype=object)
            d = StringDictionary()
            return DictColumn(d.encode(np.asarray(v, dtype=object)), d)
        arr = np.asarray(v, dtype=host_dtype(data_type))
        if arr.ndim == 0:
            arr = np.full(num_rows, arr, dtype=host_dtype(data_type))
        elif arr.shape == (1,) and num_rows != 1:
            arr = np.broadcast_to(arr, (num_rows,)).copy()
        return arr

    def _eval(self, expr, env: dict, num_rows: int):
        if isinstance(expr, ColumnRef):
            return env[expr.name]
        if isinstance(expr, Constant):
            return expr.value
        assert isinstance(expr, FuncCall), expr
        udf, arg_types = self._resolved[id(expr)]
        args = [self._eval(a, env, num_rows) for a in expr.args]
        if any(t == DataType.STRING for t in arg_types):
            out = self._eval_string_func(udf, arg_types, args, expr)
        else:
            fn_args = list(args) + list(expr.init_args)
            if udf.needs_ctx:
                out = udf.fn(self.func_ctx, *fn_args)
            elif udf.executor == Executor.HOST:
                out = np.asarray(udf.fn(*fn_args))
            else:
                out = udf.fn(*fn_args)
        # String-producing funcs must hand a DictColumn to their consumer —
        # a parent device comparison would otherwise compare Python objects
        # against int32 codes.
        if udf.out_type == DataType.STRING and not isinstance(
            out, (DictColumn, str)
        ):
            arr = np.asarray(out, dtype=object)
            if arr.ndim == 0:
                return str(arr)
            d = StringDictionary()
            out = DictColumn(d.encode(arr), d)
        return out

    def _eval_string_func(self, udf, arg_types, args, expr):
        """String-typed arguments: code-space compare for DEVICE funcs,
        dictionary-value application for dict_compatible HOST funcs, decoded
        application otherwise."""
        if udf.executor == Executor.DEVICE:
            # Code-space semantics (equal/notEqual). Align every string arg
            # into one dictionary's code space.
            base: Optional[StringDictionary] = None
            for a, t in zip(args, arg_types):
                if t == DataType.STRING and isinstance(a, DictColumn):
                    base = a.dictionary
                    break
            if base is None:
                # All string args are plain Python strings (const-vs-const):
                # compare the values directly, not sentinel codes.
                return udf.fn(*args, *expr.init_args)
            mapped = []
            for a, t in zip(args, arg_types):
                if t != DataType.STRING:
                    mapped.append(a)
                elif isinstance(a, DictColumn):
                    if a.dictionary is not base:
                        mapped.append(base.encode(a.decode()))
                    else:
                        mapped.append(a.codes)
                elif isinstance(a, str):
                    # Unseen constants get -1, which equals nothing.
                    mapped.append(np.int32(base.lookup(a)))
                else:
                    mapped.append(a)
            return udf.fn(*mapped, *expr.init_args)

        # HOST executor. The dictionary fast path pairs per-value results
        # back through ONE codes array, so it requires exactly one
        # DictColumn argument (two distinct columns sharing a dictionary
        # still differ per-row) and every other argument to be a scalar —
        # a per-row array arg would be misaligned with per-unique values.
        dict_args = [a for a in args if isinstance(a, DictColumn)]
        others_scalar = all(
            isinstance(a, DictColumn) or np.ndim(a) == 0 for a in args
        )
        if udf.dict_compatible and len(dict_args) == 1 and others_scalar:
            d = dict_args[0].dictionary
            values = np.asarray(d.values(), dtype=object)
            fn_args = [
                (values if isinstance(a, DictColumn) else a) for a in args
            ] + list(expr.init_args)
            if udf.needs_ctx:
                fn_args = [self.func_ctx] + fn_args
            per_value = np.asarray(udf.fn(*fn_args))
            codes = dict_args[0].codes
            if udf.out_type == DataType.STRING:
                out_dict = StringDictionary()
                mapped = out_dict.encode(per_value.astype(object))
                # Negative codes (missing) map to "".
                empty = out_dict.get_code("")
                out_codes = np.where(codes >= 0, mapped[np.maximum(codes, 0)], empty)
                return DictColumn(out_codes.astype(np.int32), out_dict)
            safe = np.maximum(codes, 0)
            out = per_value[safe]
            if (codes < 0).any():
                out = np.where(codes < 0, np.zeros_like(out), out)
            return out
        # Fallback: decode and run row-wise over full columns.
        fn_args = [
            (a.decode() if isinstance(a, DictColumn) else a) for a in args
        ] + list(expr.init_args)
        if udf.needs_ctx:
            fn_args = [self.func_ctx] + fn_args
        return np.asarray(udf.fn(*fn_args))

    # ---------------------------------------------------------------- device
    def device_eval(self, expr, env: dict, aux: dict):
        """Pure-jnp evaluation for tracing inside jit/shard_map.

        ``env`` maps column name → array (string columns as int32 codes);
        ``aux`` maps aux keys from ``build_aux`` → arrays (constant codes as
        0-d arrays, dict-func lookup tables as 1-d arrays).
        """
        if isinstance(expr, ColumnRef):
            return env[expr.name]
        if isinstance(expr, Constant):
            if expr.data_type == DataType.STRING:
                return aux[f"const:{id(expr)}"]
            return expr.value
        udf, arg_types = self._resolved[id(expr)]
        lut_key = f"lut:{id(expr)}"
        if lut_key in aux:
            # Precomputed dictionary-value table; gather through codes.
            # Only the string COLUMN feeds the gather; string constants
            # (e.g. a pluck key) are already baked into the table.
            (arg,) = [
                self.device_eval(a, env, aux)
                for a, t in zip(expr.args, arg_types)
                if t == DataType.STRING and isinstance(a, ColumnRef)
            ]
            import jax.numpy as jnp

            return aux[lut_key][jnp.maximum(arg, 0)]
        if udf.executor != Executor.DEVICE:
            raise ValueError(
                f"{udf.name} is a HOST function with no precomputed table; "
                "cannot trace on device"
            )
        args = [self.device_eval(a, env, aux) for a in expr.args]
        return udf.fn(*args, *expr.init_args)

    def build_aux(self, expr, dictionaries: dict[str, StringDictionary]) -> dict:
        """Host-side precomputation making ``expr`` device-traceable:
        string constants → their int32 code; dict_compatible host-func
        subtrees over a single string column → per-dictionary-value LUTs."""
        aux: dict[str, np.ndarray] = {}
        self._collect_aux(expr, dictionaries, aux)
        return aux

    def _collect_aux(self, expr, dictionaries, aux) -> None:
        if isinstance(expr, Constant):
            if expr.data_type == DataType.STRING:
                # Resolve against the single dictionary in scope; the caller
                # maps which column's dictionary applies via _const_dict.
                d = self._const_dict(expr, dictionaries)
                aux[f"const:{id(expr)}"] = np.int32(
                    d.lookup(expr.value) if d is not None else -1
                )
            return
        if not isinstance(expr, FuncCall):
            return
        udf, arg_types = self._resolved[id(expr)]
        str_cols = [
            a for a, t in zip(expr.args, arg_types)
            if t == DataType.STRING and isinstance(a, ColumnRef)
        ]
        if (
            udf.executor == Executor.HOST
            and udf.dict_compatible
            and len(str_cols) == 1
            and all(
                # Non-string args must be compile-time constants; a per-row
                # column could not align with per-dictionary-value results.
                isinstance(a, Constant)
                or (isinstance(a, ColumnRef) and t == DataType.STRING)
                for a, t in zip(expr.args, arg_types)
            )
        ):
            d = dictionaries.get(str_cols[0].name)
            if d is not None:
                values = np.asarray(d.values(), dtype=object)
                fn_args = [
                    values if (t == DataType.STRING and isinstance(a, ColumnRef))
                    else (a.value if isinstance(a, Constant) else None)
                    for a, t in zip(expr.args, arg_types)
                ] + list(expr.init_args)
                if udf.needs_ctx:
                    fn_args = [self.func_ctx] + fn_args
                out = np.asarray(udf.fn(*fn_args))
                if udf.out_type == DataType.STRING:
                    raise ValueError(
                        "string-producing host funcs need a Map before the "
                        "device pipeline (reference precedent: "
                        "scalar_udfs_run_on_executor placement rules)"
                    )
                aux[f"lut:{id(expr)}"] = out
                return
        for a in expr.args:
            self._collect_aux(a, dictionaries, aux)

    def _const_dict(self, const, dictionaries):
        """Find which column's dictionary a string constant compares against
        (the sibling string ColumnRef in its parent FuncCall)."""
        for _, root in self.named_exprs:
            parent = _find_parent(root, const)
            if parent is None:
                continue
            for a in parent.args:
                if isinstance(a, ColumnRef) and a.name in dictionaries:
                    return dictionaries[a.name]
        return None


def _find_parent(root, target):
    if isinstance(root, FuncCall):
        for a in root.args:
            if a is target:
                return root
            found = _find_parent(a, target)
            if found is not None:
                return found
    return None
