"""ExecutionGraph — builds the node DAG from a plan fragment and runs it.

Ref: src/carnot/exec/exec_graph.{h,cc} — Init (:52) instantiates ExecNodes
from plan operators and wires children; Execute (:295) round-robins sources
(ExecuteSources :177), each source generating up to
``consecutive_generate_calls_per_source`` batches per turn, pushing batches
depth-first through ConsumeNext; when no source can progress the loop yields
with a timeout (waiting on bridge data or table activity); limits abort
sources via exec_state.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from pixie_tpu.exec.agg_node import AggNode
from pixie_tpu.exec.exec_node import ExecNode
from pixie_tpu.exec.join_node import EquijoinNode
from pixie_tpu.exec.nodes import (
    BridgeSinkNode,
    BridgeSourceNode,
    EmptySourceNode,
    FilterNode,
    InlineSourceNode,
    LimitNode,
    MapNode,
    MemorySinkNode,
    MemorySourceNode,
    ResultSinkNode,
    UDTFSourceNode,
    UnionNode,
)
from pixie_tpu.exec.otel_sink_node import OTelExportSinkNode
from pixie_tpu.plan.operators import (
    AggOp,
    BridgeSinkOp,
    BridgeSourceOp,
    EmptySourceOp,
    FilterOp,
    InlineSourceOp,
    JoinOp,
    LimitOp,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    OTelExportSinkOp,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)
from pixie_tpu.plan.plan import PlanFragment

CONSECUTIVE_GENERATE_CALLS_PER_SOURCE = 8  # ref: exec_graph.cc source fairness
DEFAULT_YIELD_S = 0.001

from pixie_tpu.utils import define_flag, flags as _flags  # noqa: E402

define_flag(
    "exec_source_stall_s",
    30.0,
    help_="Seconds a fragment waits on stalled sources (bridge data / "
    "table activity) before failing the query (ref: exec_graph.cc "
    "source health checks).",
)

_NODE_TYPES = {
    MemorySourceOp: MemorySourceNode,
    InlineSourceOp: InlineSourceNode,
    EmptySourceOp: EmptySourceNode,
    UDTFSourceOp: UDTFSourceNode,
    BridgeSourceOp: BridgeSourceNode,
    MapOp: MapNode,
    FilterOp: FilterNode,
    AggOp: AggNode,
    JoinOp: EquijoinNode,
    LimitOp: LimitNode,
    UnionOp: UnionNode,
    MemorySinkOp: MemorySinkNode,
    ResultSinkOp: ResultSinkNode,
    BridgeSinkOp: BridgeSinkNode,
    OTelExportSinkOp: OTelExportSinkNode,
}


class ExecutionGraph:
    def __init__(self, fragment: PlanFragment, exec_state):
        self.fragment = fragment
        self.exec_state = exec_state
        self.nodes: dict[int, ExecNode] = {}
        self.sources: list[ExecNode] = []
        self.sinks: list[ExecNode] = []
        self._init()

    # -- init (ref: ExecutionGraph::Init, exec_graph.cc:52) -----------------
    def _init(self) -> None:
        st = self.exec_state
        table_rel = lambda op: st.table_store.get_relation(op.table_name)
        relations = self.fragment.resolve_relations(st.registry, table_rel)
        for nid in self.fragment.topo_order():
            op = self.fragment.node(nid)
            node_cls = _NODE_TYPES.get(type(op))
            if node_cls is None:
                raise ValueError(f"no exec node for operator {op!r}")
            node = node_cls(op, relations[nid], nid)
            parents = self.fragment.parents(nid)
            node.parent_nodes = [self.nodes[p] for p in parents]
            for slot, p in enumerate(parents):
                self.nodes[p].add_child(node, slot)
            # Resolve input relations for expression-bearing nodes.
            if isinstance(node, (MapNode, FilterNode)):
                node.set_input_relation(relations[parents[0]], st.registry)
            elif isinstance(node, AggNode):
                node.set_input_relation(relations[parents[0]], st.registry)
            elif isinstance(node, EquijoinNode):
                node.set_input_relations(
                    relations[parents[0]], relations[parents[1]]
                )
            self.nodes[nid] = node
            if node.is_source:
                self.sources.append(node)
            if node.is_sink:
                self.sinks.append(node)
        for node in self.nodes.values():
            node.init(st)
        self._annotate_abortable_sources()

    def _annotate_abortable_sources(self) -> None:
        """For each limit, find sources whose every path to a sink passes
        through it (ref: annotate_abortable_sources_for_limits_rule): remove
        the limit from the graph; a source that can no longer reach any sink
        is abortable."""
        limit_nodes = [n for n in self.nodes.values() if isinstance(n, LimitNode)]
        sink_ids = set(self.fragment.sinks())
        for lim in limit_nodes:
            for src in self.sources:
                if self._reaches_sink_without(src.node_id, lim.node_id, sink_ids):
                    continue
                lim.abortable_sources.append(src)

    def _reaches_sink_without(self, start: int, blocked: int, sinks: set) -> bool:
        seen = set()
        stack = [start]
        while stack:
            nid = stack.pop()
            if nid == blocked or nid in seen:
                continue
            seen.add(nid)
            if nid in sinks:
                return True
            stack.extend(self.fragment.children(nid))
        return False

    # -- execute (ref: ExecutionGraph::Execute, exec_graph.cc:295) ----------
    def execute(
        self,
        timeout_s: Optional[float] = None,
        yield_fn: Optional[Callable[[], None]] = None,
    ) -> None:
        if timeout_s is None:
            # Read at call time so flags.set()/env changes after import
            # still apply.
            timeout_s = _flags.exec_source_stall_s
        import contextlib

        import jax

        from pixie_tpu.ops import segment

        st = self.exec_state
        dev = st.compute_device()
        ctx = jax.default_device(dev) if dev is not None else contextlib.nullcontext()
        order = self.fragment.topo_order()
        hint = segment.platform_hint(
            dev.platform if dev is not None else None
        )
        with ctx, hint:
            for nid in order:
                self.nodes[nid].prepare(st)
            for nid in order:
                self.nodes[nid].open(st)
            try:
                self._execute_sources(timeout_s, yield_fn)
            finally:
                for nid in reversed(order):
                    self.nodes[nid].close(st)
                self._emit_node_spans()

    def _emit_node_spans(self) -> None:
        """Per-exec-node trace spans (r11): one span per operator node
        carrying its lifetime self-time and rows/batches in/out — emitted
        once at fragment end (never per batch, so the hot ConsumeNext
        path pays nothing). Parented to the fragment span captured in the
        exec state's trace context."""
        from pixie_tpu.utils import trace

        tctx = getattr(self.exec_state, "trace_ctx", None)
        if not trace.ACTIVE or not tctx:
            return
        for node in self.nodes.values():
            s = node.stats
            trace.record(
                f"exec:{node.name}",
                s.self_time_ns,
                trace_id=tctx[0],
                parent_id=tctx[1],
                instance=self.exec_state.instance,
                attrs={
                    "rows_in": s.rows_in,
                    "rows_out": s.rows_out,
                    "batches_in": s.batches_in,
                    "batches_out": s.batches_out,
                    "bytes_in": s.bytes_in,
                    "bytes_out": s.bytes_out,
                },
            )

    def _execute_sources(self, timeout_s, yield_fn) -> None:
        """Round-robin source loop (ref: ExecuteSources, exec_graph.cc:177).

        Two clocks (r9): the STALL deadline resets whenever any source
        makes progress (source-health watchdog); the exec state's HARD
        deadline, propagated broker→agent, never resets — a fragment that
        keeps trickling batches past the query deadline still aborts, so a
        stalled query dies everywhere, not just at the client."""
        deadline = time.monotonic() + timeout_s
        running = list(self.sources)
        while running:
            if not self.exec_state.keep_running:
                break  # a limit aborted the sources / query was cancelled
            if self.exec_state.deadline_exceeded():
                self._abort("query deadline exceeded")
                self.exec_state.check_deadline()  # raises
            progressed = False
            for src in list(running):
                for _ in range(CONSECUTIVE_GENERATE_CALLS_PER_SOURCE):
                    if not self.exec_state.keep_running:
                        break
                    if not src.has_batches_remaining():
                        break
                    if not src.generate_next(self.exec_state):
                        break
                    progressed = True
                if not src.has_batches_remaining():
                    running.remove(src)
            if not running:
                break
            if not progressed:
                # Yield: wait for bridge/table data (ref: YieldWithTimeout).
                if time.monotonic() > deadline:
                    stalled = [s.name for s in running]
                    self._abort(f"sources stalled ({stalled})")
                    raise TimeoutError(
                        f"query {self.exec_state.query_id}: sources stalled "
                        f"({stalled})"
                    )
                if yield_fn is not None:
                    yield_fn()
                else:
                    time.sleep(DEFAULT_YIELD_S)
            else:
                deadline = time.monotonic() + timeout_s

    def _abort(self, reason: str) -> None:
        """Source-stall/deadline abort: propagate cancellation to sibling
        nodes (keep_running goes false, every source is abort()ed) and
        flush eos through bridge sinks so consumer fragments parked on the
        router aren't left waiting for markers that will never come (r9;
        ref: the forwarder's cancel path, query_result_forwarder.go:571)."""
        st = self.exec_state
        st.cancel(reason)
        for src in self.sources:
            src.abort()
        for node in self.nodes.values():
            if isinstance(node, BridgeSinkNode):
                try:
                    node.flush_cancel(st)
                except Exception:
                    pass  # router may already be torn down

    # -- stats (ref: exec_node.h:60-128 per-op stats; carnot.cc:369-399) ----
    def stats(self) -> dict:
        return {
            node.name: node.stats.to_dict() for node in self.nodes.values()
        }

    def result_batches(self) -> dict[str, list]:
        """Batches collected by MemorySink nodes, keyed by sink name."""
        out = {}
        for node in self.sinks:
            if isinstance(node, MemorySinkNode):
                out[node.op.name] = node.batches
        return out
