"""Hash equijoin node.

Ref: src/carnot/exec/equijoin_node.{h,cc} — build/probe hash join with
RowTuple keys over inner/left/right/outer, chunked output. The reference
probes row-at-a-time into an absl map; ours vectorizes: build-side keys
densify through a GroupEncoder (one np.unique per batch), probe batches
resolve via the same encoder's lookup, and the gather/emit is columnar.
Joins on telemetry joins (service×service, upid×upid) are low-cardinality,
so the build table is small; the probe side streams.

Build side = left input (parent 0), probe side = right (parent 1) — the
planner orders inputs so the smaller relation is left (same convention as
the reference's specified build side).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pixie_tpu.exec.exec_node import ExecNode
from pixie_tpu.exec.group_encoder import GroupEncoder
from pixie_tpu.plan.operators import JoinOp, JoinType
from pixie_tpu.table.column import DictColumn
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.types import Relation

OUTPUT_CHUNK_ROWS = 1 << 17


class EquijoinNode(ExecNode):
    def __init__(self, op: JoinOp, output_relation: Relation, node_id: int):
        super().__init__(op, output_relation, node_id)
        self.op: JoinOp = op
        self._encoder = GroupEncoder()
        self._build_batches: list[RowBatch] = []
        self._build_done = False
        self._build: Optional[RowBatch] = None
        self._build_rows_by_gid: list[list[int]] = []
        self._build_matched: Optional[np.ndarray] = None
        self._pending_probe: list[RowBatch] = []
        self._probe_eos = False
        self._left_relation: Optional[Relation] = None
        self._right_relation: Optional[Relation] = None

    def set_input_relations(self, left: Relation, right: Relation) -> None:
        self._left_relation = left
        self._right_relation = right

    def consume_next_impl(self, exec_state, batch, parent_index: int) -> None:
        if parent_index == 0:
            self._consume_build(exec_state, batch)
        else:
            self._consume_probe(exec_state, batch)

    # -- build --------------------------------------------------------------
    def _consume_build(self, exec_state, batch: RowBatch) -> None:
        if batch.num_rows:
            self._build_batches.append(batch)
        if batch.eos:
            self._finish_build()
            for pb in self._pending_probe:
                self._probe(exec_state, pb)
            self._pending_probe = []
            if self._probe_eos:
                self._finish(exec_state)

    def _finish_build(self) -> None:
        self._build_done = True
        if self._build_batches:
            self._build = RowBatch.concat(self._build_batches)
        else:
            self._build = RowBatch.with_zero_rows(self._left_relation)
        self._build_batches = []
        keys = [self._build.col(k) for k in self.op.left_on]
        if self._build.num_rows:
            gids = self._encoder.encode(keys)
        else:
            gids = np.empty(0, np.int32)
        self._build_rows_by_gid = [[] for _ in range(self._encoder.num_groups)]
        for row, g in enumerate(gids):
            self._build_rows_by_gid[g].append(row)
        self._build_matched = np.zeros(self._build.num_rows, dtype=bool)

    # -- probe --------------------------------------------------------------
    def _consume_probe(self, exec_state, batch: RowBatch) -> None:
        if not self._build_done:
            if batch.num_rows:
                self._pending_probe.append(batch)
            if batch.eos:
                self._probe_eos = True
            return
        if batch.num_rows:
            self._probe(exec_state, batch)
        if batch.eos:
            self._probe_eos = True
            self._finish(exec_state)

    def _probe(self, exec_state, batch: RowBatch) -> None:
        keys = []
        for k, bk in zip(self.op.right_on, self.op.left_on):
            col = batch.col(k)
            # Align probe string codes into the build dictionary space.
            if isinstance(col, DictColumn):
                build_col = self._build.col(bk)
                if (
                    isinstance(build_col, DictColumn)
                    and build_col.dictionary is not col.dictionary
                ):
                    col = DictColumn(
                        build_col.dictionary.encode(col.decode()),
                        build_col.dictionary,
                    )
            keys.append(col)
        gids = self._encoder.lookup(keys)
        left_idx: list[int] = []
        right_idx: list[int] = []
        unmatched_right: list[int] = []
        for row, g in enumerate(gids):
            if g < 0 or not self._build_rows_by_gid[g]:
                unmatched_right.append(row)
                continue
            for brow in self._build_rows_by_gid[g]:
                left_idx.append(brow)
                right_idx.append(row)
            self._build_matched[self._build_rows_by_gid[g]] = True
        if left_idx:
            self._emit_matches(
                exec_state,
                self._build.take(np.asarray(left_idx)),
                batch.take(np.asarray(right_idx)),
            )
        if unmatched_right and self.op.how in (JoinType.RIGHT, JoinType.OUTER):
            right_part = batch.take(np.asarray(unmatched_right))
            self._emit_matches(
                exec_state,
                _null_batch(self._left_relation, right_part.num_rows),
                right_part,
            )

    def _finish(self, exec_state) -> None:
        if self._sent_eos:
            return
        if self.op.how in (JoinType.LEFT, JoinType.OUTER) and self._build is not None:
            unmatched = np.nonzero(~self._build_matched)[0]
            if len(unmatched):
                left_part = self._build.take(unmatched)
                self._emit_matches(
                    exec_state,
                    left_part,
                    _null_batch(self._right_relation, left_part.num_rows),
                )
        self.send(
            exec_state,
            RowBatch.with_zero_rows(self.output_relation, eow=True, eos=True),
        )

    def _emit_matches(self, exec_state, left: RowBatch, right: RowBatch) -> None:
        cols = []
        for side, in_name, _ in self.op.output_columns:
            src = left if side == 0 else right
            cols.append(src.col(in_name))
        for off in range(0, left.num_rows, OUTPUT_CHUNK_ROWS):
            hi = min(off + OUTPUT_CHUNK_ROWS, left.num_rows)
            chunk = [
                c.slice(off, hi) if isinstance(c, DictColumn) else c[off:hi]
                for c in cols
            ]
            self.send(exec_state, RowBatch(self.output_relation, chunk))


def _null_batch(relation: Relation, n: int) -> RowBatch:
    """All-default rows for outer-join padding (ref: the reference emits
    type-default values for unmatched sides)."""
    data = {}
    from pixie_tpu.types import DataType

    for c in relation:
        if c.data_type == DataType.STRING:
            data[c.name] = np.full(n, "", dtype=object)
        else:
            data[c.name] = np.zeros(n, dtype=None)
    return RowBatch.from_pydict(relation, data)
