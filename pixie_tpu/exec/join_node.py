"""Hash equijoin node.

Ref: src/carnot/exec/equijoin_node.{h,cc} — build/probe hash join with
RowTuple keys over inner/left/right/outer, chunked output. The reference
probes row-at-a-time into an absl map; ours vectorizes: build-side keys
densify through a GroupEncoder (one np.unique per batch), probe batches
resolve via the same encoder's lookup, and the gather/emit is columnar.
Joins on telemetry joins (service×service, upid×upid) are low-cardinality,
so the build table is small; the probe side streams.

Build side = left input (parent 0), probe side = right (parent 1) — the
planner orders inputs so the smaller relation is left (same convention as
the reference's specified build side).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from pixie_tpu.exec.exec_node import ExecNode
from pixie_tpu.exec.group_encoder import GroupEncoder
from pixie_tpu.plan.operators import JoinOp, JoinType
from pixie_tpu.table.column import DictColumn
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.types import Relation

OUTPUT_CHUNK_ROWS = 1 << 17

# r22: lazy handle on the serving-layer cost model (importing it at
# module level would cycle through serving → vizier → parallel → exec).
_COST_MODEL = None


def _cost_model():
    global _COST_MODEL
    if _COST_MODEL is None:
        from pixie_tpu.serving import cost_model

        _COST_MODEL = cost_model
    return _COST_MODEL


class EquijoinNode(ExecNode):
    # Matched probe rows are emitted before (possibly earlier-timed)
    # RIGHT/OUTER-unmatched rows: output is not time-ordered.
    preserves_time_order = False

    def __init__(self, op: JoinOp, output_relation: Relation, node_id: int):
        super().__init__(op, output_relation, node_id)
        self.op: JoinOp = op
        self._encoder = GroupEncoder()
        self._build_batches: list[RowBatch] = []
        self._build_done = False
        self._build: Optional[RowBatch] = None
        self._build_counts: np.ndarray = np.empty(0, np.int64)
        self._build_order: np.ndarray = np.empty(0, np.int64)
        self._build_starts: np.ndarray = np.zeros(1, np.int64)
        self._build_matched: Optional[np.ndarray] = None
        self._pending_probe: list[RowBatch] = []
        self._probe_eos = False
        self._left_relation: Optional[Relation] = None
        self._right_relation: Optional[Relation] = None
        # r22 cost model: host-lane wall/rows, observed once at eos as
        # the ``join|host`` family the device-join gate compares against.
        self._cost_wall_s = 0.0
        self._cost_rows = 0
        self._cost_observed = False

    def set_input_relations(self, left: Relation, right: Relation) -> None:
        self._left_relation = left
        self._right_relation = right

    def consume_next_impl(self, exec_state, batch, parent_index: int) -> None:
        cm = _cost_model()
        if not cm.ACTIVE:
            if parent_index == 0:
                self._consume_build(exec_state, batch)
            else:
                self._consume_probe(exec_state, batch)
            return
        t0 = time.perf_counter()
        try:
            if parent_index == 0:
                self._consume_build(exec_state, batch)
            else:
                self._consume_probe(exec_state, batch)
        finally:
            self._cost_wall_s += time.perf_counter() - t0
            self._cost_rows += int(batch.num_rows)
            if self._sent_eos and not self._cost_observed:
                self._cost_observed = True
                cm.observe_family(
                    "join|host", self._cost_rows, self._cost_wall_s
                )

    # -- build --------------------------------------------------------------
    def _consume_build(self, exec_state, batch: RowBatch) -> None:
        if batch.num_rows:
            self._build_batches.append(batch)
        if batch.eos:
            self._finish_build()
            for pb in self._pending_probe:
                self._probe(exec_state, pb)
            self._pending_probe = []
            if self._probe_eos:
                self._finish(exec_state)

    def _finish_build(self) -> None:
        self._build_done = True
        if self._build_batches:
            self._build = RowBatch.concat(self._build_batches)
        else:
            self._build = RowBatch.with_zero_rows(self._left_relation)
        self._build_batches = []
        keys = [self._build.col(k) for k in self.op.left_on]
        if self._build.num_rows:
            gids = self._encoder.encode(keys)
        else:
            gids = np.empty(0, np.int32)
        # CSR layout over build rows grouped by gid: rows of group g are
        # _build_order[_build_starts[g] : _build_starts[g+1]], in build
        # order (stable sort) — the vectorized stand-in for the reference's
        # per-key bucket vectors (equijoin_node.h:48).
        n_groups = self._encoder.num_groups
        self._build_counts = np.bincount(gids, minlength=n_groups).astype(
            np.int64
        )
        self._build_order = np.argsort(gids, kind="stable")
        self._build_starts = np.concatenate(
            [[0], np.cumsum(self._build_counts)]
        )
        self._build_matched = np.zeros(self._build.num_rows, dtype=bool)

    # -- probe --------------------------------------------------------------
    def _consume_probe(self, exec_state, batch: RowBatch) -> None:
        if not self._build_done:
            if batch.num_rows:
                self._pending_probe.append(batch)
            if batch.eos:
                self._probe_eos = True
            return
        if batch.num_rows:
            self._probe(exec_state, batch)
        if batch.eos:
            self._probe_eos = True
            self._finish(exec_state)

    def _probe(self, exec_state, batch: RowBatch) -> None:
        keys = []
        for k, bk in zip(self.op.right_on, self.op.left_on):
            col = batch.col(k)
            # Align probe string codes into the build dictionary space.
            if isinstance(col, DictColumn):
                build_col = self._build.col(bk)
                if (
                    isinstance(build_col, DictColumn)
                    and build_col.dictionary is not col.dictionary
                ):
                    col = DictColumn(
                        build_col.dictionary.encode(col.decode()),
                        build_col.dictionary,
                    )
            keys.append(col)
        gids = np.asarray(self._encoder.lookup(keys), dtype=np.int64)
        n_groups = len(self._build_counts)
        if n_groups == 0:
            matched = np.zeros(len(gids), dtype=bool)
            fanout = np.zeros(len(gids), dtype=np.int64)
        else:
            g_safe = np.clip(gids, 0, n_groups - 1)
            matched = gids >= 0
            fanout = np.where(matched, self._build_counts[g_safe], 0)
            matched = matched & (fanout > 0)
            fanout = np.where(matched, fanout, 0)
        total = int(fanout.sum())
        if total:
            # probe row i pairs with build rows order[starts[g_i] + 0..c_i-1]
            right_idx = np.repeat(np.arange(len(gids)), fanout)
            run_base = np.repeat(np.cumsum(fanout) - fanout, fanout)
            ramp = np.arange(total) - run_base
            left_idx = self._build_order[
                self._build_starts[g_safe][right_idx] + ramp
            ]
            self._build_matched[left_idx] = True
            self._emit_matches(
                exec_state,
                self._build.take(left_idx),
                batch.take(right_idx),
            )
        unmatched = np.nonzero(~matched)[0]
        if len(unmatched) and self.op.how in (JoinType.RIGHT, JoinType.OUTER):
            right_part = batch.take(unmatched)
            self._emit_matches(
                exec_state,
                _null_batch(self._left_relation, right_part.num_rows),
                right_part,
            )

    def _finish(self, exec_state) -> None:
        if self._sent_eos:
            return
        if self.op.how in (JoinType.LEFT, JoinType.OUTER) and self._build is not None:
            unmatched = np.nonzero(~self._build_matched)[0]
            if len(unmatched):
                left_part = self._build.take(unmatched)
                self._emit_matches(
                    exec_state,
                    left_part,
                    _null_batch(self._right_relation, left_part.num_rows),
                )
        self.send(
            exec_state,
            RowBatch.with_zero_rows(self.output_relation, eow=True, eos=True),
        )

    def _emit_matches(self, exec_state, left: RowBatch, right: RowBatch) -> None:
        cols = []
        for side, in_name, _ in self.op.output_columns:
            src = left if side == 0 else right
            cols.append(src.col(in_name))
        for off in range(0, left.num_rows, OUTPUT_CHUNK_ROWS):
            hi = min(off + OUTPUT_CHUNK_ROWS, left.num_rows)
            chunk = [
                c.slice(off, hi) if isinstance(c, DictColumn) else c[off:hi]
                for c in cols
            ]
            self.send(exec_state, RowBatch(self.output_relation, chunk))


def _null_batch(relation: Relation, n: int) -> RowBatch:
    """All-default rows for outer-join padding (ref: the reference emits
    type-default values for unmatched sides)."""
    data = {}
    from pixie_tpu.types import DataType

    for c in relation:
        if c.data_type == DataType.STRING:
            data[c.name] = np.full(n, "", dtype=object)
        else:
            data[c.name] = np.zeros(n, dtype=None)
    return RowBatch.from_pydict(relation, data)
