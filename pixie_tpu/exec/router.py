"""Bridge router — inter-fragment dataflow.

Ref: src/carnot/exec/grpc_router.{h,cc} — the reference's GRPCRouter is a
gRPC ResultSinkService that demultiplexes incoming TransferResultChunk
streams to the right query's GRPCSourceNode, buffering until the node
registers. Ours is transport-agnostic: in one process it is a dict of
queues; the DCN transport (multi-host) wraps the same interface around
serialized batches.

r9 robustness semantics:

- ``unregister_producer`` — the broker calls it when an executing agent's
  heartbeat expires mid-query (ref: the forwarder cancels the dead agent's
  stream, query_result_forwarder.go:395): consumers re-reading
  ``producer_count`` stop waiting for eos markers that will never come and
  finalize with the rows they have (partial results).
- ``cancel_query``/tombstones — once a query is cancelled or cleaned up,
  late pushes from still-running remote fragments are dropped instead of
  re-creating buffers nobody will ever drain (the defaultdict otherwise
  leaks one queue per late pusher), and polls raise ``BridgeCancelled`` so
  consumer fragments parked on the router abort instead of spinning to
  their stall timeout.

r17 failover semantics (flag ``fragment_failover``; all opt-in per push/
poll via attempt tokens, so the r9 paths above are byte-for-byte
unchanged when the broker runs without failover):

- **Producer slots + attempt epochs.** A fragment slot (one producer's
  position on a bridge, stable across retries) is authorized for specific
  attempt epochs (``authorize_producer``). Pushes carry a
  ``token=(slot, epoch)`` and are HELD per attempt until that attempt's
  eos arrives, then committed to the consumer queue atomically — a dead
  attempt's partial rows are discarded wholesale (``revoke_producer``),
  never half-consumed, so merges can never double-count. The first
  attempt to commit wins its slot; anything later (a zombie producer the
  broker believed dead, or a hedge loser) drops at the router.
- **Replacement producers.** ``replace_producer`` revokes the dead
  attempt and authorizes its replacement WITHOUT changing the producer
  count — downstream BridgeSourceNodes keep expecting the same number of
  eos markers and simply receive the replacement's committed stream.
- **Replayable consumption.** Polls carrying a ``consumer`` token read
  through a per-attempt cursor over a RETAINED committed queue instead of
  popping — so a retried CONSUMER fragment (a dead merge agent's
  replacement) re-reads every committed item from the start and produces
  the same merge a first attempt would have. Buffers drop at
  ``cleanup_query`` as before.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Optional

_TOMBSTONE_CAP = 4096  # bounded memory of finished/cancelled query ids


class BridgeCancelled(RuntimeError):
    """Polled a bridge of a cancelled/finished query."""


class BridgeRouter:
    def __init__(self):
        self._lock = threading.Lock()
        self._queues: dict[tuple[str, str], collections.deque] = (
            collections.defaultdict(collections.deque)
        )
        self._producers: dict[tuple[str, str], int] = collections.defaultdict(int)
        # Queries whose buffers are gone for good: late pushes drop, polls
        # raise. Bounded FIFO so a long-lived router cannot grow forever.
        self._dead: set[str] = set()
        self._dead_order: collections.deque = collections.deque()
        # r17 failover state, all keyed under (query_id, bridge_id):
        # slot -> set of authorized attempt epochs; slots already won by
        # a committed attempt; and per-(slot, epoch) held items awaiting
        # their atomic commit.
        self._auth: dict[tuple[str, str], dict[Any, set]] = {}
        self._committed: dict[tuple[str, str], set] = {}
        self._held: dict[tuple[str, str, Any, int], list] = {}
        # Per-consumer-attempt read cursors over retained queues
        # (replayable consumption), keyed (query_id, bridge_id, token).
        self._cursors: dict[tuple[str, str, Any], int] = {}

    def _mark_dead_locked(self, query_id: str) -> None:
        if query_id in self._dead:
            return
        self._dead.add(query_id)
        self._dead_order.append(query_id)
        while len(self._dead_order) > _TOMBSTONE_CAP:
            self._dead.discard(self._dead_order.popleft())

    def register_producer(self, query_id: str, bridge_id: str) -> None:
        """Each upstream fragment instance that will feed a bridge registers
        so the consumer knows how many eos markers to expect (ref: the
        router's per-source connection tracking)."""
        with self._lock:
            # A fresh registration resurrects a tombstoned id: re-executing
            # a plan with an explicit query_id must behave like a new query.
            if query_id in self._dead:
                self._dead.discard(query_id)
                try:
                    self._dead_order.remove(query_id)
                except ValueError:
                    pass
            self._producers[(query_id, bridge_id)] += 1

    def unregister_producer(self, query_id: str, bridge_id: str) -> None:
        """A registered producer died before sending eos (agent lost):
        consumers re-reading producer_count stop expecting it."""
        with self._lock:
            key = (query_id, bridge_id)
            if self._producers[key] > 0:
                self._producers[key] -= 1

    # -- r17: attempt authorization ------------------------------------------
    def authorize_producer(
        self, query_id: str, bridge_id: str, slot: Any, epoch: int
    ) -> None:
        """Allow attempt ``epoch`` of fragment ``slot`` to push into this
        bridge. Does NOT change the producer count — the count is how
        many SLOTS will eventually commit, authorization is which
        attempts may fill them."""
        with self._lock:
            self._auth.setdefault((query_id, bridge_id), {}).setdefault(
                slot, set()
            ).add(epoch)

    def revoke_producer(
        self, query_id: str, bridge_id: str, slot: Any, epoch: int
    ) -> None:
        """Discard a dead/lost attempt: its authorization is removed and
        any HELD (uncommitted) items it pushed are dropped wholesale —
        downstream merges never see a partial attempt. Producer count is
        untouched; the broker unregisters separately when it gives up on
        the slot entirely (the r9 degrade path)."""
        with self._lock:
            auth = self._auth.get((query_id, bridge_id), {}).get(slot)
            if auth is not None:
                auth.discard(epoch)
            self._held.pop((query_id, bridge_id, slot, epoch), None)

    def replace_producer(
        self,
        query_id: str,
        bridge_id: str,
        slot: Any,
        old_epoch: int,
        new_epoch: int,
    ) -> None:
        """Swap a slot's authorized attempt: the dead attempt's held
        items drop, the replacement may produce, and the consumer-side
        eos expectation is unchanged (same producer count)."""
        with self._lock:
            auth = self._auth.setdefault(
                (query_id, bridge_id), {}
            ).setdefault(slot, set())
            auth.discard(old_epoch)
            auth.add(new_epoch)
            self._held.pop((query_id, bridge_id, slot, old_epoch), None)

    def num_producers(self, query_id: str, bridge_id: str) -> int:
        with self._lock:
            return max(1, self._producers[(query_id, bridge_id)])

    def producer_count(self, query_id: str, bridge_id: str) -> int:
        """Raw live-producer count (may be 0 after losses) — consumers use
        it to refresh eos expectations mid-query."""
        with self._lock:
            return self._producers[(query_id, bridge_id)]

    def push(
        self,
        query_id: str,
        bridge_id: str,
        item: Any,
        token: Optional[tuple] = None,
    ) -> None:
        with self._lock:
            if query_id in self._dead:
                return  # cancelled/finished: drop, don't re-create buffers
            if token is None:
                self._queues[(query_id, bridge_id)].append(item)
                return
            # r17 attempt-gated push: hold until this attempt's eos, then
            # commit atomically; stale/unauthorized attempts drop here.
            slot, epoch = token
            key = (query_id, bridge_id)
            if slot in self._committed.get(key, ()):
                return  # slot already won by another attempt
            if epoch not in self._auth.get(key, {}).get(slot, ()):
                return  # revoked (dead/lost) attempt: discard
            hk = (query_id, bridge_id, slot, epoch)
            held = self._held.setdefault(hk, [])
            held.append(item)
            if getattr(item, "eos", False):
                self._queues[key].extend(held)
                del self._held[hk]
                self._committed.setdefault(key, set()).add(slot)
                # Drop any sibling attempt's held items for this slot
                # (hedge loser racing the winner to commit).
                for other in [
                    k for k in self._held
                    if k[0] == query_id and k[1] == bridge_id
                    and k[2] == slot
                ]:
                    del self._held[other]

    def poll(
        self,
        query_id: str,
        bridge_id: str,
        consumer: Optional[tuple] = None,
    ) -> Optional[Any]:
        with self._lock:
            if query_id in self._dead:
                raise BridgeCancelled(
                    f"query {query_id}: bridge {bridge_id} cancelled"
                )
            q = self._queues[(query_id, bridge_id)]
            if consumer is None:
                return q.popleft() if q else None
            # r17 replayable consumption: a retried consumer fragment
            # (fresh token) re-reads the committed stream from index 0.
            ck = (query_id, bridge_id, consumer)
            cur = self._cursors.get(ck, 0)
            if cur >= len(q):
                return None
            self._cursors[ck] = cur + 1
            return q[cur]

    def cancel_query(self, query_id: str) -> None:
        """Abort a query mid-flight: drop its buffers, tombstone the id so
        late pushes are dropped and parked consumers get BridgeCancelled."""
        self.cleanup_query(query_id)

    def cleanup_query(self, query_id: str) -> None:
        """Drop a finished/cancelled query's buffers (ref: router query GC)
        and tombstone the id against late producers."""
        with self._lock:
            for key in [k for k in self._queues if k[0] == query_id]:
                del self._queues[key]
            for key in [k for k in self._producers if k[0] == query_id]:
                del self._producers[key]
            for d in (self._auth, self._committed):
                for key in [k for k in d if k[0] == query_id]:
                    del d[key]
            for d in (self._held, self._cursors):
                for key in [k for k in d if k[0] == query_id]:
                    del d[key]
            self._mark_dead_locked(query_id)
