"""Bridge router — inter-fragment dataflow.

Ref: src/carnot/exec/grpc_router.{h,cc} — the reference's GRPCRouter is a
gRPC ResultSinkService that demultiplexes incoming TransferResultChunk
streams to the right query's GRPCSourceNode, buffering until the node
registers. Ours is transport-agnostic: in one process it is a dict of
queues; the DCN transport (multi-host) wraps the same interface around
serialized batches.

r9 robustness semantics:

- ``unregister_producer`` — the broker calls it when an executing agent's
  heartbeat expires mid-query (ref: the forwarder cancels the dead agent's
  stream, query_result_forwarder.go:395): consumers re-reading
  ``producer_count`` stop waiting for eos markers that will never come and
  finalize with the rows they have (partial results).
- ``cancel_query``/tombstones — once a query is cancelled or cleaned up,
  late pushes from still-running remote fragments are dropped instead of
  re-creating buffers nobody will ever drain (the defaultdict otherwise
  leaks one queue per late pusher), and polls raise ``BridgeCancelled`` so
  consumer fragments parked on the router abort instead of spinning to
  their stall timeout.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Optional

_TOMBSTONE_CAP = 4096  # bounded memory of finished/cancelled query ids


class BridgeCancelled(RuntimeError):
    """Polled a bridge of a cancelled/finished query."""


class BridgeRouter:
    def __init__(self):
        self._lock = threading.Lock()
        self._queues: dict[tuple[str, str], collections.deque] = (
            collections.defaultdict(collections.deque)
        )
        self._producers: dict[tuple[str, str], int] = collections.defaultdict(int)
        # Queries whose buffers are gone for good: late pushes drop, polls
        # raise. Bounded FIFO so a long-lived router cannot grow forever.
        self._dead: set[str] = set()
        self._dead_order: collections.deque = collections.deque()

    def _mark_dead_locked(self, query_id: str) -> None:
        if query_id in self._dead:
            return
        self._dead.add(query_id)
        self._dead_order.append(query_id)
        while len(self._dead_order) > _TOMBSTONE_CAP:
            self._dead.discard(self._dead_order.popleft())

    def register_producer(self, query_id: str, bridge_id: str) -> None:
        """Each upstream fragment instance that will feed a bridge registers
        so the consumer knows how many eos markers to expect (ref: the
        router's per-source connection tracking)."""
        with self._lock:
            # A fresh registration resurrects a tombstoned id: re-executing
            # a plan with an explicit query_id must behave like a new query.
            if query_id in self._dead:
                self._dead.discard(query_id)
                try:
                    self._dead_order.remove(query_id)
                except ValueError:
                    pass
            self._producers[(query_id, bridge_id)] += 1

    def unregister_producer(self, query_id: str, bridge_id: str) -> None:
        """A registered producer died before sending eos (agent lost):
        consumers re-reading producer_count stop expecting it."""
        with self._lock:
            key = (query_id, bridge_id)
            if self._producers[key] > 0:
                self._producers[key] -= 1

    def num_producers(self, query_id: str, bridge_id: str) -> int:
        with self._lock:
            return max(1, self._producers[(query_id, bridge_id)])

    def producer_count(self, query_id: str, bridge_id: str) -> int:
        """Raw live-producer count (may be 0 after losses) — consumers use
        it to refresh eos expectations mid-query."""
        with self._lock:
            return self._producers[(query_id, bridge_id)]

    def push(self, query_id: str, bridge_id: str, item: Any) -> None:
        with self._lock:
            if query_id in self._dead:
                return  # cancelled/finished: drop, don't re-create buffers
            self._queues[(query_id, bridge_id)].append(item)

    def poll(self, query_id: str, bridge_id: str) -> Optional[Any]:
        with self._lock:
            if query_id in self._dead:
                raise BridgeCancelled(
                    f"query {query_id}: bridge {bridge_id} cancelled"
                )
            q = self._queues[(query_id, bridge_id)]
            return q.popleft() if q else None

    def cancel_query(self, query_id: str) -> None:
        """Abort a query mid-flight: drop its buffers, tombstone the id so
        late pushes are dropped and parked consumers get BridgeCancelled."""
        self.cleanup_query(query_id)

    def cleanup_query(self, query_id: str) -> None:
        """Drop a finished/cancelled query's buffers (ref: router query GC)
        and tombstone the id against late producers."""
        with self._lock:
            for key in [k for k in self._queues if k[0] == query_id]:
                del self._queues[key]
            for key in [k for k in self._producers if k[0] == query_id]:
                del self._producers[key]
            self._mark_dead_locked(query_id)
