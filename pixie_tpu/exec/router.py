"""Bridge router — inter-fragment dataflow.

Ref: src/carnot/exec/grpc_router.{h,cc} — the reference's GRPCRouter is a
gRPC ResultSinkService that demultiplexes incoming TransferResultChunk
streams to the right query's GRPCSourceNode, buffering until the node
registers. Ours is transport-agnostic: in one process it is a dict of
queues; the DCN transport (multi-host) wraps the same interface around
serialized batches.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Optional


class BridgeRouter:
    def __init__(self):
        self._lock = threading.Lock()
        self._queues: dict[tuple[str, str], collections.deque] = (
            collections.defaultdict(collections.deque)
        )
        self._producers: dict[tuple[str, str], int] = collections.defaultdict(int)

    def register_producer(self, query_id: str, bridge_id: str) -> None:
        """Each upstream fragment instance that will feed a bridge registers
        so the consumer knows how many eos markers to expect (ref: the
        router's per-source connection tracking)."""
        with self._lock:
            self._producers[(query_id, bridge_id)] += 1

    def num_producers(self, query_id: str, bridge_id: str) -> int:
        with self._lock:
            return max(1, self._producers[(query_id, bridge_id)])

    def push(self, query_id: str, bridge_id: str, item: Any) -> None:
        with self._lock:
            self._queues[(query_id, bridge_id)].append(item)

    def poll(self, query_id: str, bridge_id: str) -> Optional[Any]:
        with self._lock:
            q = self._queues[(query_id, bridge_id)]
            return q.popleft() if q else None

    def cleanup_query(self, query_id: str) -> None:
        """Drop a finished/cancelled query's buffers (ref: router query GC)."""
        with self._lock:
            for key in [k for k in self._queues if k[0] == query_id]:
                del self._queues[key]
            for key in [k for k in self._producers if k[0] == query_id]:
                del self._producers[key]
