"""Source, sink, and row-wise operator nodes.

Ref: src/carnot/exec/{memory_source,memory_sink,empty_source,udtf_source,
map,filter,limit,union}_node.* and grpc_{source,sink}_node.* (bridges).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pixie_tpu.exec.agg_node import StateBatch
from pixie_tpu.exec.exec_node import ExecNode, SinkNode, SourceNode
from pixie_tpu.exec.expression_evaluator import ExpressionEvaluator
from pixie_tpu.plan.operators import (
    BridgeSinkOp,
    BridgeSourceOp,
    EmptySourceOp,
    FilterOp,
    InlineSourceOp,
    LimitOp,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.table.table import TIME_COLUMN


class MemorySourceNode(SourceNode):
    """Reads a table through a time-bounded cursor (memory_source_node.h:42);
    supports infinite streaming mode (:61)."""

    def __init__(self, op: MemorySourceOp, output_relation, node_id):
        super().__init__(op, output_relation, node_id)
        self.op: MemorySourceOp = op
        self._cursor = None
        self._table = None

    def prepare_impl(self, exec_state) -> None:
        self._table = exec_state.table_store.get_table(
            self.op.table_name, self.op.tablet or ""
        )
        if self._table is None:
            raise KeyError(f"no table named {self.op.table_name!r}")
        self._cursor = self._table.cursor(
            self.op.start_time, self.op.stop_time, streaming=self.op.streaming
        )

    def generate_next_impl(self, exec_state) -> bool:
        if self._sent_eos:
            return False
        batch = self._cursor.next_batch()
        done = self._cursor.done()
        if batch is None and not done:
            return False  # streaming: nothing available yet
        if batch is None:
            batch = RowBatch.with_zero_rows(self._table.relation)
        if self.op.column_names is not None:
            batch = batch.select(list(self.op.column_names))
        # Forward STORED end-of-window markers (producers write them per
        # ingest window): windowed aggs downstream emit on them; FULL
        # non-windowed aggs ignore eow, so this is invisible elsewhere
        # (ref: memory_source_node.h streaming flag forwarding).
        self.send(
            exec_state, batch.with_flags(eow=done or batch.eow, eos=done)
        )
        return True


class EmptySourceNode(SourceNode):
    def generate_next_impl(self, exec_state) -> bool:
        if self._sent_eos:
            return False
        self.send(
            exec_state,
            RowBatch.with_zero_rows(self.output_relation, eow=True, eos=True),
        )
        return True


class UDTFSourceNode(SourceNode):
    """Runs a user-defined table function once (udtf_source_node)."""

    def __init__(self, op: UDTFSourceOp, output_relation, node_id):
        super().__init__(op, output_relation, node_id)
        self.op: UDTFSourceOp = op

    def generate_next_impl(self, exec_state) -> bool:
        if self._sent_eos:
            return False
        udtf = exec_state.registry.lookup_udtf(self.op.udtf_name)
        data = udtf.fn(exec_state.func_ctx, **dict(self.op.arg_values))
        batch = RowBatch.from_pydict(self.output_relation, data)
        self.send(exec_state, batch.with_flags(eow=True, eos=True))
        return True


class InlineSourceNode(SourceNode):
    """Emits batches stashed in exec_state.inline_batches[key]."""

    def generate_next_impl(self, exec_state) -> bool:
        if self._sent_eos:
            return False
        batches = exec_state.inline_batches.get(self.op.key, [])
        for b in batches:
            self.send(exec_state, b)
        if not batches or not batches[-1].eos:
            self.send(
                exec_state,
                RowBatch.with_zero_rows(self.output_relation, eow=True, eos=True),
            )
        return True


class BridgeSourceNode(SourceNode):
    """Receives batches routed from another fragment
    (ref: grpc_source_node.h:39 + grpc_router.h:53).

    Producer expectations are refreshed from the router mid-query (r9):
    when the broker unregisters a dead agent's bridges (heartbeat expiry
    mid-query, ref query_result_forwarder.go:395), this source stops
    waiting for eos markers that will never arrive and flushes a synthetic
    eos downstream so blocking consumers (merge aggs) finalize with the
    partial input they have."""

    def __init__(self, op: BridgeSourceOp, output_relation, node_id):
        super().__init__(op, output_relation, node_id)
        self.op: BridgeSourceOp = op
        self._upstream_eos = 0
        self._expected_producers = 1
        self._had_registrations = False
        self._forwarded_eos = False

    def prepare_impl(self, exec_state) -> None:
        self._expected_producers = exec_state.router.num_producers(
            exec_state.query_id, self.op.bridge_id
        )
        # Raw registration count at prepare: refreshes only apply when at
        # least one producer actually registered — a dangling bridge (no
        # registrations; num_producers floors at 1) must keep the old
        # stall-until-timeout semantics, not silently self-complete.
        count = getattr(exec_state.router, "producer_count", None)
        self._had_registrations = (
            count is not None
            and count(exec_state.query_id, self.op.bridge_id) > 0
        )

    def _refresh_expected(self, exec_state) -> None:
        if not self._had_registrations:
            return
        live = exec_state.router.producer_count(
            exec_state.query_id, self.op.bridge_id
        )
        # Only shrink: registrations all precede fragment launch, so a
        # smaller live count means producers were lost, never added.
        if live < self._expected_producers:
            self._expected_producers = live

    def generate_next_impl(self, exec_state) -> bool:
        # r17: a failover attempt reads through a per-attempt cursor
        # (retained queue) so a replacement consumer re-reads the whole
        # committed stream; None keeps the destructive popleft.
        item = exec_state.router.poll(
            exec_state.query_id,
            self.op.bridge_id,
            consumer=exec_state.bridge_token,
        )
        if item is None:
            self._refresh_expected(exec_state)
            if (
                self._upstream_eos >= self._expected_producers
                and not self._forwarded_eos
            ):
                # Every remaining producer is gone: flush a synthetic eos
                # so downstream blocking ops finalize (partial results).
                self._forwarded_eos = True
                self.send(
                    exec_state,
                    RowBatch.with_zero_rows(
                        self.output_relation, eow=True, eos=True
                    ),
                )
                return True
            return False
        eos = getattr(item, "eos", False)
        if eos:
            self._upstream_eos += 1
            all_done = self._upstream_eos >= self._expected_producers
            if all_done:
                self._forwarded_eos = True
            if isinstance(item, RowBatch):
                item = item.with_flags(eow=all_done and item.eow, eos=all_done)
            else:
                item.eos = all_done
                item.eow = all_done and item.eow
        self.send(exec_state, item)
        return True

    def has_batches_remaining(self) -> bool:
        if self._aborted:
            return False
        if self._upstream_eos < self._expected_producers:
            return True
        # Complete — but if completion came from producer loss (not a real
        # final eos), stay live until the synthetic eos is flushed.
        return not self._forwarded_eos


class MapNode(ExecNode):
    """Vectorized projection (map_node.*): one ExpressionEvaluator pass."""

    def __init__(self, op: MapOp, output_relation, node_id):
        super().__init__(op, output_relation, node_id)
        self.op: MapOp = op
        self._evaluator: Optional[ExpressionEvaluator] = None

    def set_input_relation(self, rel, registry, func_ctx=None) -> None:
        self._evaluator = ExpressionEvaluator(
            list(self.op.exprs), rel, registry, func_ctx
        )

    def consume_next_impl(self, exec_state, batch, parent_index) -> None:
        self._evaluator.func_ctx = exec_state.func_ctx
        self.send(exec_state, self._evaluator.evaluate(batch, self.output_relation))


class FilterNode(ExecNode):
    def __init__(self, op: FilterOp, output_relation, node_id):
        super().__init__(op, output_relation, node_id)
        self.op: FilterOp = op
        self._evaluator: Optional[ExpressionEvaluator] = None

    def set_input_relation(self, rel, registry, func_ctx=None) -> None:
        self._evaluator = ExpressionEvaluator(
            [("pred", self.op.expr)], rel, registry, func_ctx
        )

    def consume_next_impl(self, exec_state, batch, parent_index) -> None:
        self._evaluator.func_ctx = exec_state.func_ctx
        if batch.num_rows:
            mask = self._evaluator.evaluate_predicate(batch)
            if not mask.all():
                batch = batch.take(np.nonzero(mask)[0])
        self.send(exec_state, batch)


class LimitNode(ExecNode):
    """Row limit; aborts upstream sources once satisfied (limit_node.*,
    annotate_abortable_sources_for_limits_rule)."""

    def __init__(self, op: LimitOp, output_relation, node_id):
        super().__init__(op, output_relation, node_id)
        self.op: LimitOp = op
        self._seen = 0
        self._done = False
        # Sources whose every path to a sink passes through this limit;
        # filled by ExecutionGraph init (ref: the planner's
        # annotate_abortable_sources_for_limits_rule).
        self.abortable_sources: list = []

    def consume_next_impl(self, exec_state, batch, parent_index) -> None:
        if self._done:
            return
        remaining = self.op.n - self._seen
        out = batch
        if batch.num_rows > remaining:
            out = batch.slice(0, remaining)
        self._seen += out.num_rows
        if self._seen >= self.op.n:
            self._done = True
            out = out.with_flags(eow=True, eos=True)
            for src in self.abortable_sources:
                src.abort()
        self.send(exec_state, out)


class UnionNode(ExecNode):
    """k-way union. With a time_ column, performs an incremental time-ordered
    merge: on every batch, rows up to the minimum high-watermark time across
    still-live parents are merged and emitted (the reference union_node's
    streaming ordered merge) — so streaming queries make progress and the
    buffer stays bounded. Without a time column, batches pass through and eos
    waits for all parents."""

    def __init__(self, op: UnionOp, output_relation, node_id):
        super().__init__(op, output_relation, node_id)
        self.op: UnionOp = op
        self._num_parents = 1
        self._eos_seen = 0
        self._buffer: list[RowBatch] = []  # new, not-yet-sorted batches
        self._sorted_rest: Optional[RowBatch] = None  # retained sorted run
        self._ordered = False
        self._incremental = True
        self._watermarks: list = []
        self._parent_eos: list = []
        self._pending_min = None  # min buffered time: cheap no-op guard

    def prepare_impl(self, exec_state) -> None:
        self._num_parents = len(getattr(self, "parent_nodes", [None]))
        self._ordered = self.output_relation.has_column(TIME_COLUMN)
        self._watermarks = [None] * self._num_parents
        self._parent_eos = [False] * self._num_parents
        # Incremental emission is only sound when every parent stream is
        # time-nondecreasing. That is a *plan* property: decide it up front
        # by walking each parent's ancestry — joins reorder rows (unmatched
        # rows trail matched ones), so any union fed by a join buffers until
        # eos and sorts globally (ADVICE r2 medium: a runtime watermark
        # check alone cannot restore order once rows have been emitted).
        self._incremental = self._ancestry_order_preserving()

    def _ancestry_order_preserving(self) -> bool:
        """True iff every ancestor declares preserves_time_order. Bridge
        sources have no visible ancestry, but the distributed splitter cuts
        plans *before* blocking ops (splitter.h:52) so upstream fragments
        contain only order-preserving ops; the runtime monotonicity guard
        covers anything that violates that invariant anyway."""
        stack = list(getattr(self, "parent_nodes", []) or [])
        seen: set = set()
        while stack:
            node = stack.pop()
            if node is None or id(node) in seen:
                continue
            seen.add(id(node))
            if not getattr(node, "preserves_time_order", True):
                return False
            stack.extend(getattr(node, "parent_nodes", []) or [])
        return True

    def consume_next_impl(self, exec_state, batch, parent_index) -> None:
        eos = batch.eos
        if self._ordered:
            if batch.num_rows:
                self._buffer.append(batch)
                times = np.asarray(batch.col(TIME_COLUMN))
                tmin = times.min()
                tmax = times.max()
                # Defense-in-depth for streams the plan walk can't see
                # through (e.g. a reordering op beyond a bridge): a batch
                # that is internally unsorted or starts before its parent's
                # watermark flips us to the buffer-until-eos global sort.
                # Best-effort only — it cannot recall rows already emitted.
                if self._incremental:
                    prev = self._watermarks[parent_index]
                    if (prev is not None and tmin < prev) or (
                        batch.num_rows > 1
                        and np.any(times[1:] < times[:-1])
                    ):
                        self._incremental = False
                self._watermarks[parent_index] = (
                    tmax
                    if self._watermarks[parent_index] is None
                    else max(self._watermarks[parent_index], tmax)
                )
                self._pending_min = (
                    tmin
                    if self._pending_min is None
                    else min(self._pending_min, tmin)
                )
            if eos:
                self._parent_eos[parent_index] = True
                self._eos_seen += 1
            if self._eos_seen >= self._num_parents:
                self._flush(exec_state)
            elif self._incremental:
                self._emit_ready(exec_state)
            return
        if batch.num_rows:
            self.send(exec_state, batch.with_flags(eow=False, eos=False))
        if eos:
            self._eos_seen += 1
            if self._eos_seen >= self._num_parents:
                self.send(
                    exec_state,
                    RowBatch.with_zero_rows(
                        self.output_relation, eow=True, eos=True
                    ),
                )

    def _merged_pending(self) -> Optional[RowBatch]:
        """Sort only the new batches, then linear-merge with the retained
        sorted run — avoids re-sorting the whole buffer per batch when one
        parent lags (the remainder can grow large)."""
        new = None
        if self._buffer:
            new = RowBatch.concat(self._buffer)
            order = np.argsort(np.asarray(new.col(TIME_COLUMN)), kind="stable")
            new = new.take(order)
        self._buffer = []
        rest = self._sorted_rest
        self._sorted_rest = None
        if rest is None or not rest.num_rows:
            return new
        if new is None or not new.num_rows:
            return rest
        a = np.asarray(rest.col(TIME_COLUMN))
        b = np.asarray(new.col(TIME_COLUMN))
        # Interleave two sorted runs: each b-row lands after the a-rows that
        # precede it (stable: ties keep rest before new).
        b_pos = np.searchsorted(a, b, side="right") + np.arange(len(b))
        total = len(a) + len(b)
        mask = np.ones(total, dtype=bool)
        mask[b_pos] = False
        perm = np.empty(total, dtype=np.int64)
        perm[np.nonzero(mask)[0]] = np.arange(len(a))
        perm[b_pos] = len(a) + np.arange(len(b))
        return RowBatch.concat([rest, new]).take(perm)

    def _emit_ready(self, exec_state) -> None:
        """Emit rows with time strictly below the min watermark of live
        parents — later rows from those parents can still sort before
        anything at/after it (per-parent batches arrive time-ordered)."""
        live = [
            self._watermarks[i]
            for i in range(self._num_parents)
            if not self._parent_eos[i]
        ]
        if any(w is None for w in live):
            return  # a live parent hasn't produced yet: no safe cutoff
        cutoff = min(live) if live else None
        if cutoff is None or (
            self._pending_min is None or self._pending_min >= cutoff
        ):
            return  # nothing can be ready: skip the concat+sort entirely
        merged = self._merged_pending()
        if merged is None:
            return
        times = np.asarray(merged.col(TIME_COLUMN))
        n_ready = int(np.searchsorted(times, cutoff, side="left"))
        if n_ready == 0:
            self._sorted_rest = merged  # keep the merged run for next time
            return
        self.send(
            exec_state,
            merged.slice(0, n_ready).with_flags(eow=False, eos=False),
        )
        rest = merged.slice(n_ready, merged.num_rows)
        self._sorted_rest = rest if rest.num_rows else None
        self._pending_min = times[n_ready] if rest.num_rows else None

    def _flush(self, exec_state) -> None:
        merged = self._merged_pending()
        if merged is not None:
            self.send(exec_state, merged.with_flags(eow=True, eos=True))
        else:
            self.send(
                exec_state,
                RowBatch.with_zero_rows(self.output_relation, eow=True, eos=True),
            )
        self._buffer = []
        self._sorted_rest = None


class MemorySinkNode(SinkNode):
    """Collects results into an in-memory output table (memory_sink_node)."""

    def __init__(self, op: MemorySinkOp, output_relation, node_id):
        super().__init__(op, output_relation, node_id)
        self.op: MemorySinkOp = op
        self.batches: list[RowBatch] = []

    def consume_next_impl(self, exec_state, batch, parent_index) -> None:
        self.batches.append(batch)


class ResultSinkNode(SinkNode):
    """Streams result batches to the query's result destination
    (ref: grpc_sink external mode → TransferResultChunk)."""

    def __init__(self, op: ResultSinkOp, output_relation, node_id):
        super().__init__(op, output_relation, node_id)
        self.op: ResultSinkOp = op

    def consume_next_impl(self, exec_state, batch, parent_index) -> None:
        if exec_state.result_callback is not None:
            exec_state.result_callback(self.op.table_name, batch)


class BridgeSinkNode(SinkNode):
    """Sends batches (row or state) to a bridge for another fragment
    (ref: grpc_sink_node.h:54 internal mode)."""

    def __init__(self, op: BridgeSinkOp, output_relation, node_id):
        super().__init__(op, output_relation, node_id)
        self.op: BridgeSinkOp = op
        self._pushed_eos = False

    def consume_next_impl(self, exec_state, batch, parent_index) -> None:
        if getattr(batch, "eos", False):
            self._pushed_eos = True
        exec_state.router.push(
            exec_state.query_id,
            self.op.bridge_id,
            batch,
            token=exec_state.bridge_token,
        )

    def flush_cancel(self, exec_state) -> None:
        """On fragment abort (stall/deadline, r9): if no eos crossed this
        bridge yet, push a zero-row eos marker so the consumer fragment
        finalizes with partial input instead of stalling to its own
        timeout waiting on a producer that aborted. A failover attempt
        (r17) skips the flush: committing an empty stream would WIN the
        slot and lock the retry out — the broker's revoke/replace covers
        the consumer instead."""
        if self._pushed_eos:
            return
        self._pushed_eos = True
        if exec_state.bridge_token is not None:
            return
        exec_state.router.push(
            exec_state.query_id,
            self.op.bridge_id,
            RowBatch.with_zero_rows(self.output_relation, eow=True, eos=True),
        )
