"""Metadata snapshot store.

Ref: src/shared/metadata/metadata_state.{h,cc} (AgentMetadataState: immutable
k8s world snapshot), state_manager.{h,cc} (applies updates, publishes new
snapshots). Consumers always read a consistent snapshot; the manager swaps
snapshots atomically (a Python reference assignment) as updates arrive.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional


@dataclasses.dataclass(frozen=True)
class PodInfo:
    pod_id: str
    name: str  # "<namespace>/<pod>"
    namespace: str
    service_id: str
    node_name: str
    ip: str
    phase: str = "Running"
    start_time_ns: int = 0  # ref: k8s_objects PodInfo start_timestamp_ns


@dataclasses.dataclass(frozen=True)
class ServiceInfo:
    service_id: str
    name: str  # "<namespace>/<service>"
    namespace: str


@dataclasses.dataclass(frozen=True)
class MetadataState:
    """Immutable snapshot. All maps are lookups by id/key."""

    asid: int = 0
    hostname: str = "localhost"
    pods: dict = dataclasses.field(default_factory=dict)  # pod_id -> PodInfo
    services: dict = dataclasses.field(default_factory=dict)  # svc_id -> ServiceInfo
    upid_to_pod: dict = dataclasses.field(default_factory=dict)  # upid str -> pod_id
    ip_to_pod: dict = dataclasses.field(default_factory=dict)  # ip -> pod_id
    dns: dict = dataclasses.field(default_factory=dict)  # ip -> hostname
    # Per-process attributes (ref: shared/metadata pids.* PIDInfo).
    upid_to_container: dict = dataclasses.field(default_factory=dict)
    upid_to_cmdline: dict = dataclasses.field(default_factory=dict)

    # -- resolution helpers (the surface metadata UDFs use) ----------------
    def pod_for_upid(self, upid: str) -> Optional[PodInfo]:
        pid = self.upid_to_pod.get(upid)
        return self.pods.get(pid) if pid else None

    def service_for_upid(self, upid: str) -> Optional[ServiceInfo]:
        pod = self.pod_for_upid(upid)
        if pod is None:
            return None
        return self.services.get(pod.service_id)

    def pod_for_ip(self, ip: str) -> Optional[PodInfo]:
        pid = self.ip_to_pod.get(ip)
        return self.pods.get(pid) if pid else None


class MetadataStateManager:
    """Swappable current-snapshot holder (ref: AgentMetadataStateManager)."""

    def __init__(self, state: MetadataState | None = None):
        self._lock = threading.Lock()
        self._state = state or MetadataState()
        self._epoch = 0

    def current(self) -> MetadataState:
        return self._state

    def set_state(self, state: MetadataState) -> None:
        with self._lock:
            self._state = state
            self._epoch += 1

    @property
    def epoch(self) -> int:
        return self._epoch

    # -- incremental update surface (what a k8s watcher would call) --------
    def apply_update(
        self,
        pods: list[PodInfo] = (),
        services: list[ServiceInfo] = (),
        upids: dict | None = None,
    ) -> None:
        with self._lock:
            s = self._state
            new_pods = dict(s.pods)
            new_ip = dict(s.ip_to_pod)
            for p in pods:
                new_pods[p.pod_id] = p
                if p.ip:
                    new_ip[p.ip] = p.pod_id
            new_services = dict(s.services)
            for sv in services:
                new_services[sv.service_id] = sv
            new_upids = dict(s.upid_to_pod)
            if upids:
                new_upids.update(upids)
            self._state = dataclasses.replace(
                s,
                pods=new_pods,
                services=new_services,
                upid_to_pod=new_upids,
                ip_to_pod=new_ip,
            )
            self._epoch += 1


def make_synthetic_state(
    num_services: int = 8, pods_per_service: int = 3, asid: int = 1
) -> MetadataState:
    """Deterministic synthetic k8s topology for tests/benchmarks (analogous
    in role to the reference's testing fixtures, not a port of them)."""
    pods, services, upid_to_pod, ip_to_pod = {}, {}, {}, {}
    svc_names = [f"default/svc-{i}" for i in range(num_services)]
    for i, sname in enumerate(svc_names):
        sid = f"svc-id-{i}"
        services[sid] = ServiceInfo(sid, sname, "default")
        for j in range(pods_per_service):
            pid = f"pod-id-{i}-{j}"
            ip = f"10.0.{i}.{j + 1}"
            pods[pid] = PodInfo(
                pod_id=pid,
                name=f"default/svc-{i}-pod-{j}",
                namespace="default",
                service_id=sid,
                node_name=f"node-{j % 4}",
                ip=ip,
            )
            ip_to_pod[ip] = pid
            upid = f"{asid}:{1000 + i * pods_per_service + j}:1"
            upid_to_pod[upid] = pid
    return MetadataState(
        asid=asid,
        pods=pods,
        services=services,
        upid_to_pod=upid_to_pod,
        ip_to_pod=ip_to_pod,
        dns={ip: p.name for ip, p in ((ip, pods[pid]) for ip, pid in ip_to_pod.items())},
    )
