"""K8s-entity metadata state (ref: src/shared/metadata/).

The reference keeps a per-agent immutable ``AgentMetadataState`` snapshot
(pods/services/containers/UPIDs) built from NATS-delivered k8s updates
(state_manager.{h,cc}); Stirling uses it for PID->pod resolution and Carnot's
metadata UDFs use it for `df.ctx[...]`. Ours is an in-process snapshot store
fed by the ingest layer (synthetic topology for now) with the same
consumer-facing surface: metadata scalar UDFs + the compiler's ctx[] rewrite.

UPID format note: the reference packs (asid, pid, start_ts) into a UINT128;
here a UPID is the string "asid:pid:start_ts" (dictionary-encoded, so
metadata lookups run once per distinct process, not per row).
"""

from pixie_tpu.metadata.state import (  # noqa: F401
    MetadataState,
    MetadataStateManager,
    PodInfo,
    ServiceInfo,
)
