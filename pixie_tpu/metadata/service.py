"""Metadata service: watch k8s, persist entities, broadcast to agents.

Ref: src/vizier/services/metadata/controllers/k8smeta/
k8s_metadata_{controller,handler,store}.go — a controller watches the k8s
API (pods/services/endpoints/...), a handler turns watch events into
updates persisted in the datastore, and agents receive incremental
updates over NATS (here: the in-proc/TCP bus, topic
``metadata_updates``). On restart the service REHYDRATES its world from
the datastore — the reference's "resume" story (SURVEY §5: durable state
= metadata KV; telemetry is ephemeral).

The watcher is pluggable: production would wrap a real k8s client;
tests/demos drive ``emit_pod``/``emit_service`` by hand (the reference
tests its handler exactly this way, with fake watch events).
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Optional

from pixie_tpu.metadata.state import (
    MetadataState,
    MetadataStateManager,
    PodInfo,
    ServiceInfo,
)
from pixie_tpu.vizier.datastore import Datastore

METADATA_UPDATES_TOPIC = "metadata_updates"

_POD_PREFIX = "/md/pod/"
_SVC_PREFIX = "/md/service/"
_UPID_PREFIX = "/md/upid/"


class MetadataService:
    """Persists entity updates and broadcasts them (k8smeta controller +
    handler + store, collapsed to one in-process service)."""

    def __init__(self, datastore: Datastore, bus=None):
        self.store = datastore
        self.bus = bus
        self._lock = threading.Lock()

    # -- rehydration (restart/resume path) ----------------------------------
    def snapshot(self) -> MetadataState:
        pods = {}
        ip_to_pod = {}
        for _, raw in self.store.get_prefix(_POD_PREFIX):
            p = PodInfo(**json.loads(raw))
            pods[p.pod_id] = p
            if p.ip:
                ip_to_pod[p.ip] = p.pod_id
        services = {}
        for _, raw in self.store.get_prefix(_SVC_PREFIX):
            s = ServiceInfo(**json.loads(raw))
            services[s.service_id] = s
        upid_to_pod = {
            k[len(_UPID_PREFIX):]: raw.decode()
            for k, raw in self.store.get_prefix(_UPID_PREFIX)
        }
        return MetadataState(
            pods=pods,
            services=services,
            upid_to_pod=upid_to_pod,
            ip_to_pod=ip_to_pod,
        )

    # -- watch-event ingestion (the k8s handler surface) --------------------
    def handle_pod_update(self, pod: PodInfo, deleted: bool = False) -> None:
        with self._lock:
            key = _POD_PREFIX + pod.pod_id
            if deleted:
                self.store.delete(key)
                # Processes of a deleted pod are gone too — without this,
                # historical upids accumulate in the store (and every
                # rehydrated snapshot) forever.
                for k, raw in self.store.get_prefix(_UPID_PREFIX):
                    if raw.decode() == pod.pod_id:
                        self.store.delete(k)
            else:
                self.store.set(
                    key, json.dumps(dataclasses.asdict(pod)).encode()
                )
        self._broadcast(
            {"type": "pod", "deleted": deleted,
             "pod": dataclasses.asdict(pod)}
        )

    def handle_service_update(
        self, svc: ServiceInfo, deleted: bool = False
    ) -> None:
        with self._lock:
            key = _SVC_PREFIX + svc.service_id
            if deleted:
                self.store.delete(key)
            else:
                self.store.set(
                    key, json.dumps(dataclasses.asdict(svc)).encode()
                )
        self._broadcast(
            {"type": "service", "deleted": deleted,
             "service": dataclasses.asdict(svc)}
        )

    def handle_upid(self, upid: str, pod_id: str) -> None:
        with self._lock:
            self.store.set(_UPID_PREFIX + upid, pod_id.encode())
        self._broadcast({"type": "upid", "upid": upid, "pod_id": pod_id})

    def _broadcast(self, msg: dict) -> None:
        if self.bus is not None:
            self.bus.publish(METADATA_UPDATES_TOPIC, msg)


class MetadataUpdateListener:
    """Agent-side consumer: applies broadcast updates into the agent's
    MetadataStateManager (ref: the agent manager's k8s-update message
    handler feeding AgentMetadataStateManager)."""

    def __init__(self, bus, manager: MetadataStateManager):
        self.manager = manager
        self._sub = bus.subscribe(METADATA_UPDATES_TOPIC)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            msg = self._sub.get(timeout=0.05)
            if msg is None:
                continue
            self.apply(msg)

    def apply(self, msg: dict) -> None:
        kind = msg.get("type")
        if kind == "pod" and not msg.get("deleted"):
            self.manager.apply_update(pods=[PodInfo(**msg["pod"])])
        elif kind == "service" and not msg.get("deleted"):
            self.manager.apply_update(
                services=[ServiceInfo(**msg["service"])]
            )
        elif kind == "upid":
            self.manager.apply_update(upids={msg["upid"]: msg["pod_id"]})
        elif kind == "pod" and msg.get("deleted"):
            st = self.manager.current()
            pod_id = msg["pod"]["pod_id"]
            pods = dict(st.pods)
            pods.pop(pod_id, None)
            ip_to_pod = {
                ip: pid
                for ip, pid in st.ip_to_pod.items()
                if pid != pod_id
            }
            upid_to_pod = {
                u: pid
                for u, pid in st.upid_to_pod.items()
                if pid != pod_id
            }
            self.manager.set_state(
                dataclasses.replace(
                    st,
                    pods=pods,
                    ip_to_pod=ip_to_pod,
                    upid_to_pod=upid_to_pod,
                )
            )

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self._sub.unsubscribe()


class FakeK8sWatcher:
    """Test/demo watcher: hand-driven watch events (the reference unit-
    tests its handler with fake informer events the same way)."""

    def __init__(self, service: MetadataService):
        self.service = service

    def emit_pod(self, pod: PodInfo, deleted: bool = False) -> None:
        self.service.handle_pod_update(pod, deleted)

    def emit_service(self, svc: ServiceInfo, deleted: bool = False) -> None:
        self.service.handle_service_update(svc, deleted)

    def emit_process(self, upid: str, pod_id: str) -> None:
        self.service.handle_upid(upid, pod_id)
