"""PxL compiler: Python-ast front end → operator IR → logical plan.

Ref: src/carnot/planner/compiler/ — parser (libpypa there, stdlib ``ast``
here since PxL is Python syntax), ASTVisitorImpl building the QLObject layer
(objects/), operator IR (ir/), Analyzer rewrite rules, Optimizer, plan
emission (compiler.cc:47-109).
"""

from pixie_tpu.compiler.compiler import Compiler, CompilerError

__all__ = ["Compiler", "CompilerError"]
