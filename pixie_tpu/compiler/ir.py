"""Mutable operator IR graph.

Ref: src/carnot/planner/ir/ (all_ir_nodes.h) — the compiler builds a mutable
operator graph (MemorySource, Map, BlockingAgg, Join, Filter, Limit,
GRPCSink...), the analyzer/optimizer rewrite it, and it lowers to the plan
proto. Our IR reuses the (frozen) plan operator dataclasses as payloads;
rewrites swap payloads with dataclasses.replace. Relations are resolved
eagerly as nodes are added — type errors surface at the script line that
caused them, like the reference's compile errors.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from pixie_tpu.compiler.errors import CompilerError
from pixie_tpu.plan import dag
from pixie_tpu.plan.operators import MemorySourceOp, Operator
from pixie_tpu.plan.plan import Plan, PlanFragment
from pixie_tpu.types import Relation


class IRGraph:
    def __init__(self, registry, table_relations: dict[str, Relation]):
        self.registry = registry
        self.table_relations = dict(table_relations)
        self._ops: dict[int, Operator] = {}
        self._parents: dict[int, list[int]] = {}
        self._relations: dict[int, Relation] = {}
        self._next = 0

    # -- construction -------------------------------------------------------
    def add(self, op: Operator, parents: list[int] = ()) -> int:
        parents = list(parents)
        inputs = [self._relations[p] for p in parents]
        if isinstance(op, MemorySourceOp):
            if op.table_name not in self.table_relations:
                raise CompilerError(
                    f"table {op.table_name!r} does not exist; available: "
                    f"{sorted(self.table_relations)}"
                )
            rel = op.output_relation(
                inputs, self.registry,
                table_relation=self.table_relations[op.table_name],
            )
        else:
            rel = op.output_relation(inputs, self.registry)
        nid = self._next
        self._next += 1
        self._ops[nid] = op
        self._parents[nid] = parents
        self._relations[nid] = rel
        return nid

    def replace_op(self, nid: int, op: Operator, recompute: bool = True) -> None:
        """Swap a node's payload and recompute relations downstream. Pass
        recompute=False when batching several rewrites (then call
        ``recompute_all`` once) — mid-batch the graph may be transiently
        inconsistent (e.g. a source narrowed before its consumer is)."""
        self._ops[nid] = op
        if recompute:
            for n in self.topo_order():
                if n == nid or nid in self._ancestors(n):
                    self._recompute_relation(n)

    def recompute_all(self) -> None:
        for n in self.topo_order():
            self._recompute_relation(n)

    def _recompute_relation(self, nid: int) -> None:
        op = self._ops[nid]
        inputs = [self._relations[p] for p in self._parents[nid]]
        if isinstance(op, MemorySourceOp):
            self._relations[nid] = op.output_relation(
                inputs, self.registry,
                table_relation=self.table_relations[op.table_name],
            )
        else:
            self._relations[nid] = op.output_relation(inputs, self.registry)

    def _ancestors(self, nid: int) -> set:
        out, stack = set(), list(self._parents[nid])
        while stack:
            p = stack.pop()
            if p not in out:
                out.add(p)
                stack.extend(self._parents[p])
        return out

    # -- queries ------------------------------------------------------------
    def op(self, nid: int) -> Operator:
        return self._ops[nid]

    def relation(self, nid: int) -> Relation:
        return self._relations[nid]

    def parents(self, nid: int) -> list[int]:
        return list(self._parents[nid])

    def children(self, nid: int) -> list[int]:
        return dag.children_of(self._parents, nid)

    def nodes(self) -> list[int]:
        return list(self._ops)

    def sinks(self) -> list[int]:
        with_children = {p for ps in self._parents.values() for p in ps}
        return [n for n in self._ops if n not in with_children]

    def topo_order(self) -> list[int]:
        return dag.topo_order(self._parents)

    def prune_dead(self, keep: Optional[set] = None) -> None:
        """Drop nodes that reach no sink-worthy node (ref: optimizer pruning
        of unused operator chains)."""
        from pixie_tpu.plan.operators import (
            BridgeSinkOp,
            MemorySinkOp,
            OTelExportSinkOp,
            ResultSinkOp,
        )

        keep = set(keep or ())
        live = set(keep)
        for n, op in self._ops.items():
            if isinstance(
                op,
                (ResultSinkOp, MemorySinkOp, BridgeSinkOp, OTelExportSinkOp),
            ):
                live.add(n)
        # Walk ancestors of live nodes.
        stack = list(live)
        while stack:
            n = stack.pop()
            for p in self._parents[n]:
                if p not in live:
                    live.add(p)
                    stack.append(p)
        for n in list(self._ops):
            if n not in live:
                del self._ops[n], self._parents[n], self._relations[n]

    # -- lowering -----------------------------------------------------------
    def to_plan(self, query_id: str = "") -> Plan:
        """Emit a single-fragment logical plan (the distributed planner
        splits it; ref: compiler emits planpb consumed by distributed)."""
        plan = Plan(query_id)
        frag = plan.add_fragment()
        mapping: dict[int, int] = {}
        for nid in self.topo_order():
            mapping[nid] = frag.add(
                self._ops[nid], [mapping[p] for p in self._parents[nid]]
            )
        return plan

    def __repr__(self):
        parts = []
        for nid in self.topo_order():
            src = f"{self._parents[nid]}→" if self._parents[nid] else ""
            parts.append(f"{src}{nid}:{self._ops[nid].op_name}")
        return f"IR[{', '.join(parts)}]"
