"""pxtrace — the dynamic-trace mutation compiler.

Ref: src/carnot/planner/probes/probes.h:213 (MutationsIR),
tracepoint_generator.* — PxL programs importing ``pxtrace`` define probes
(@pxtrace.probe('Func') functions returning output-column specs built
from ArgExpr/RetExpr/FunctionLatency) and deploy them with
UpsertTracepoint(name, table, probe_fn, target, ttl). Compilation
produces TracepointDeployment mutations, not a query plan
(LogicalPlanner::CompileTrace, logical_planner.h:61).

The reference lowers deployments through a DWARF-resolving dwarvifier
into BCC uprobes (dynamic_tracer.{h,cc}); this build's agents install a
synthetic DynamicTraceConnector with the same table schema instead —
kernel probing is out of scope on TPU hosts (BASELINE.md), the
compile/registry/deploy/table lifecycle is the parity surface.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

from pixie_tpu.compiler.errors import CompilerError
from pixie_tpu.types import DataType, Relation, SemanticType


@dataclasses.dataclass(frozen=True)
class TraceColumn:
    name: str
    kind: str  # "arg" | "ret" | "latency"
    expr: str  # arg name / return path ('$0.a') / "" for latency

    @property
    def data_type(self) -> DataType:
        # Without DWARF type resolution, args/returns surface as strings;
        # latency is always ns (the dwarvifier would refine these).
        return DataType.INT64 if self.kind == "latency" else DataType.STRING


@dataclasses.dataclass(frozen=True)
class TracepointDeployment:
    name: str
    table_name: str
    target_fn: str  # the traced symbol (@pxtrace.probe arg)
    target: str = ""  # process selector (PodProcess/SharedObject/upid)
    ttl_ns: int = 300_000_000_000
    columns: tuple = ()  # TraceColumn

    def output_relation(self) -> Relation:
        cols = [
            ("time_", DataType.TIME64NS, SemanticType.ST_TIME_NS),
            ("upid", DataType.STRING, SemanticType.ST_UPID),
        ]
        cols += [
            (
                c.name,
                c.data_type,
                SemanticType.ST_DURATION_NS
                if c.kind == "latency"
                else SemanticType.ST_NONE,
            )
            for c in self.columns
        ]
        return Relation.of(*cols)


class MutationsIR:
    """Compiled mutations (ref: probes.h:213)."""

    def __init__(self):
        self.deployments: list[TracepointDeployment] = []
        self.deletions: list[str] = []


class _TraceExpr:
    def __init__(self, kind: str, expr: str = ""):
        self.kind = kind
        self.expr = expr


class _ProbeFn:
    def __init__(self, fn, target_fn: str):
        self.fn = fn
        self.target_fn = target_fn


_TTL_RE = re.compile(r"^(\d+)(ns|us|ms|s|m|h)$")
_TTL_NS = {"ns": 1, "us": 10**3, "ms": 10**6, "s": 10**9,
           "m": 60 * 10**9, "h": 3600 * 10**9}


def parse_ttl(ttl) -> int:
    if isinstance(ttl, (int, float)):
        return int(ttl)
    m = _TTL_RE.match(str(ttl))
    if not m:
        raise CompilerError(f"bad tracepoint TTL {ttl!r} (want e.g. '5m')")
    return int(m.group(1)) * _TTL_NS[m.group(2)]


class PxTraceModule:
    """The ``pxtrace`` module object bound into mutation scripts."""

    def __init__(self, mutations: MutationsIR):
        self._mutations = mutations

    # -- probe definition ---------------------------------------------------
    def probe(self, target_fn: str):
        def deco(fn):
            return _ProbeFn(fn, target_fn)

        return deco

    @staticmethod
    def ArgExpr(expr: str) -> _TraceExpr:
        return _TraceExpr("arg", str(expr))

    @staticmethod
    def RetExpr(expr: str) -> _TraceExpr:
        return _TraceExpr("ret", str(expr))

    @staticmethod
    def FunctionLatency() -> _TraceExpr:
        return _TraceExpr("latency")

    # -- target selectors ---------------------------------------------------
    @staticmethod
    def PodProcess(pod: str, container: str = "") -> str:
        return f"pod:{pod}" + (f"/{container}" if container else "")

    @staticmethod
    def SharedObject(name: str, upid=None) -> str:
        return f"so:{name}"

    # -- mutations ----------------------------------------------------------
    def UpsertTracepoint(
        self, name: str, table_name: str, probe_fn, target, ttl
    ) -> None:
        if not isinstance(probe_fn, _ProbeFn):
            raise CompilerError(
                "UpsertTracepoint needs a @pxtrace.probe(...) function"
            )
        out = probe_fn.fn()
        if out is None:
            raise CompilerError(
                "Improper probe definition: missing output spec of probe, "
                "add a return statement"
            )
        columns = []
        for item in out if isinstance(out, (list, tuple)) else [out]:
            if not isinstance(item, dict) or len(item) != 1:
                raise CompilerError(
                    "probe output entries must be single-key dicts"
                )
            ((col, spec),) = item.items()
            if not isinstance(spec, _TraceExpr):
                raise CompilerError(
                    f"probe output {col!r} must be an ArgExpr/RetExpr/"
                    "FunctionLatency"
                )
            columns.append(TraceColumn(col, spec.kind, spec.expr))
        self._mutations.deployments.append(
            TracepointDeployment(
                name=name,
                table_name=table_name,
                target_fn=probe_fn.target_fn,
                target=str(target),
                ttl_ns=parse_ttl(ttl),
                columns=tuple(columns),
            )
        )

    def DeleteTracepoint(self, name: str) -> None:
        self._mutations.deletions.append(name)


def is_mutation_script(query: str) -> bool:
    return bool(re.search(r"^\s*import\s+pxtrace\s*$", query, re.M))


def compile_trace(query: str, registry=None) -> MutationsIR:
    """PxL mutation script -> MutationsIR (LogicalPlanner::CompileTrace)."""
    from pixie_tpu.compiler.ast_visitor import ASTVisitor
    from pixie_tpu.compiler.ir import IRGraph
    from pixie_tpu.compiler.objects import PxModule

    if registry is None:
        from pixie_tpu.udf.registry import default_registry

        registry = default_registry()
    mutations = MutationsIR()
    ir = IRGraph(registry, {})
    px = PxModule(ir, registry)
    visitor = ASTVisitor(
        px, globals_={"pxtrace": PxTraceModule(mutations)}
    )
    visitor.run(query)
    return mutations
