"""Compiler facade: PxL source → logical Plan.

Ref: src/carnot/planner/compiler/compiler.cc:47-109 (Compile/CompileToIR/
QueryToIR): parse → ASTVisitor over QLObjects → IR → Analyzer → Optimizer →
plan emission.
"""

from __future__ import annotations

from typing import Optional

from pixie_tpu.compiler import analyzer
from pixie_tpu.compiler.ast_visitor import ASTVisitor
from pixie_tpu.compiler.ir import IRGraph
from pixie_tpu.compiler.objects import CompilerError, PxModule
from pixie_tpu.plan.plan import Plan
from pixie_tpu.types import Relation

__all__ = ["Compiler", "CompilerError"]


class Compiler:
    def __init__(self, registry=None):
        if registry is None:
            from pixie_tpu.udf.registry import default_registry

            registry = default_registry()
        self.registry = registry

    def compile_to_ir(
        self,
        query: str,
        table_relations: dict[str, Relation],
        now_ns: Optional[int] = None,
        script_args: Optional[dict] = None,
    ) -> IRGraph:
        ir = IRGraph(self.registry, table_relations)
        px = PxModule(ir, self.registry, now_ns)
        visitor = ASTVisitor(px, globals_=script_args)
        visitor.run(query)
        if not px.display_calls:
            raise CompilerError(
                "script produced no output — call px.display(df, name)"
            )
        analyzer.run_all(ir)
        return ir

    def compile(
        self,
        query: str,
        table_relations: dict[str, Relation],
        now_ns: Optional[int] = None,
        script_args: Optional[dict] = None,
        query_id: str = "",
    ) -> Plan:
        ir = self.compile_to_ir(query, table_relations, now_ns, script_args)
        return ir.to_plan(query_id)
