"""Compiler facade: PxL source → logical Plan.

Ref: src/carnot/planner/compiler/compiler.cc:47-109 (Compile/CompileToIR/
QueryToIR): parse → ASTVisitor over QLObjects → IR → Analyzer → Optimizer →
plan emission.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from pixie_tpu.compiler import analyzer
from pixie_tpu.compiler.ast_visitor import ASTVisitor, _UserFunc
from pixie_tpu.compiler.ir import IRGraph
from pixie_tpu.compiler.objects import CompilerError, DataFrameObj, PxModule
from pixie_tpu.plan.plan import Plan
from pixie_tpu.types import Relation

__all__ = ["Compiler", "CompilerError", "FuncToExecute"]


@dataclasses.dataclass(frozen=True)
class FuncToExecute:
    """One vis-spec function invocation (ref: QueryRequest.FuncToExecute in
    src/api/proto/vizierpb — name + string arg values + output table)."""

    name: str
    args: dict
    output_table: str


def _cast_arg(annotation, value):
    """Arg values arrive as strings (vis.json); cast per the function's
    parameter annotation (int/float; px.* semantic wrappers are strings)."""
    if isinstance(annotation, ast.Name):
        if annotation.id == "int":
            return int(value)
        if annotation.id == "float":
            return float(value)
        if annotation.id == "bool":
            return value in (True, "true", "True", "1")
    return value


class Compiler:
    def __init__(self, registry=None):
        if registry is None:
            from pixie_tpu.udf.registry import default_registry

            registry = default_registry()
        self.registry = registry

    def compile_to_ir(
        self,
        query: str,
        table_relations: dict[str, Relation],
        now_ns: Optional[int] = None,
        script_args: Optional[dict] = None,
        exec_funcs: Optional[list[FuncToExecute]] = None,
    ) -> IRGraph:
        ir = IRGraph(self.registry, table_relations)
        px = PxModule(ir, self.registry, now_ns)
        visitor = ASTVisitor(px, globals_=script_args)
        visitor.run(query)
        for ef in exec_funcs or []:
            fn = visitor.env.get(ef.name)
            if not isinstance(fn, _UserFunc):
                raise CompilerError(
                    f"exec func {ef.name!r} is not defined by the script"
                )
            annotations = {
                a.arg: a.annotation for a in fn.node.args.args
            }
            kwargs = {}
            for k, v in ef.args.items():
                if k not in annotations:
                    raise CompilerError(
                        f"{ef.name}() has no parameter {k!r}"
                    )
                kwargs[k] = _cast_arg(annotations[k], v)
            df = fn(**kwargs)
            if not isinstance(df, DataFrameObj):
                raise CompilerError(
                    f"exec func {ef.name!r} must return a DataFrame"
                )
            px.display(df, ef.output_table)
        if not px.display_calls:
            raise CompilerError(
                "script produced no output — call px.display(df, name)"
            )
        analyzer.run_all(ir)
        return ir

    def compile(
        self,
        query: str,
        table_relations: dict[str, Relation],
        now_ns: Optional[int] = None,
        script_args: Optional[dict] = None,
        query_id: str = "",
        exec_funcs: Optional[list[FuncToExecute]] = None,
    ) -> Plan:
        ir = self.compile_to_ir(
            query, table_relations, now_ns, script_args, exec_funcs
        )
        return ir.to_plan(query_id)
