"""Analyzer/optimizer rewrite rules over the IR.

Ref: src/carnot/planner/compiler/analyzer/ (rule executor with ~20 rewrite
rules resolving types/metadata/groups) and compiler/optimizer/ (operator
merging and pruning). Our object layer resolves types/metadata eagerly, so
the rules left here are the optimizer ones that matter for TPU execution:

- merge_consecutive_maps: every ``df.x = ...`` emits a full-width Map; the
  merge collapses chains into one Map so the device pipeline sees a single
  fused projection (XLA then fuses it into the aggregation's prologue).
- prune_columns: narrows MemorySource reads and Map outputs to columns that
  some sink actually needs — less host→HBM staging traffic.
"""

from __future__ import annotations

import dataclasses

from pixie_tpu.plan.expressions import (
    AggregateExpression,
    ColumnRef,
    Constant,
    FuncCall,
    referenced_columns,
)
from pixie_tpu.plan.operators import (
    AggOp,
    FilterOp,
    JoinOp,
    LimitOp,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    ResultSinkOp,
    UnionOp,
)


def substitute(expr, mapping: dict):
    """Replace ColumnRefs by expressions from ``mapping``."""
    if isinstance(expr, ColumnRef):
        return mapping.get(expr.name, expr)
    if isinstance(expr, FuncCall):
        return FuncCall(
            expr.name,
            tuple(substitute(a, mapping) for a in expr.args),
            expr.init_args,
        )
    return expr


def merge_consecutive_maps(ir) -> int:
    """Map(B)∘Map(A) → Map(B∘A) when A's only consumer is B."""
    merged = 0
    changed = True
    while changed:
        changed = False
        for nid in ir.topo_order():
            if nid not in ir._ops:
                continue
            op = ir._ops.get(nid)
            if not isinstance(op, MapOp):
                continue
            (parent,) = ir.parents(nid) or (None,)
            if parent is None:
                continue
            pop = ir._ops.get(parent)
            if not isinstance(pop, MapOp):
                continue
            if len(ir.children(parent)) != 1:
                continue
            upstream = dict(pop.exprs)
            new_exprs = tuple(
                (name, substitute(e, upstream)) for name, e in op.exprs
            )
            # Splice: nid's parent becomes pop's parent.
            ir._ops[nid] = MapOp(new_exprs)
            ir._parents[nid] = ir.parents(parent)
            del ir._ops[parent], ir._parents[parent], ir._relations[parent]
            ir._recompute_relation(nid)
            merged += 1
            changed = True
            break
    return merged


def _required_inputs(op, needed_out: set, input_rels) -> list[set]:
    """Which input columns each parent must provide, given the columns this
    node's consumers need."""
    if isinstance(op, MapOp):
        used = set()
        for name, e in op.exprs:
            if name in needed_out:
                used |= referenced_columns(e)
        return [used]
    if isinstance(op, FilterOp):
        return [set(needed_out) | referenced_columns(op.expr)]
    if isinstance(op, AggOp):
        used = set(op.groups)
        for _, agg in op.values:
            used |= referenced_columns(agg)
        return [used]
    if isinstance(op, JoinOp):
        left_need = {
            in_name
            for side, in_name, out in op.output_columns
            if side == 0 and out in needed_out
        } | set(op.left_on)
        right_need = {
            in_name
            for side, in_name, out in op.output_columns
            if side == 1 and out in needed_out
        } | set(op.right_on)
        return [left_need, right_need]
    if isinstance(op, (LimitOp, MemorySinkOp, ResultSinkOp)):
        return [set(needed_out)]
    if isinstance(op, UnionOp):
        return [set(needed_out) for _ in input_rels]
    # Conservatively require everything for other ops.
    return [set(r.col_names()) for r in input_rels]


def prune_columns(ir) -> int:
    """Narrow sources (and full-width Maps) to the columns sinks consume."""
    needed: dict[int, set] = {}
    order = ir.topo_order()
    # Seed: sinks need all their columns.
    for nid in order:
        needed[nid] = set()
    for nid in reversed(order):
        op = ir._ops[nid]
        if isinstance(op, (ResultSinkOp, MemorySinkOp)):
            needed[nid] = set(ir.relation(nid).col_names())
        parents = ir.parents(nid)
        input_rels = [ir.relation(p) for p in parents]
        reqs = _required_inputs(op, needed[nid], input_rels)
        for p, req in zip(parents, reqs):
            needed[p] |= req
    changed = 0
    for nid in order:
        op = ir._ops[nid]
        need = needed[nid]
        if isinstance(op, MemorySourceOp):
            current = ir.relation(nid).col_names()
            keep = tuple(c for c in current if c in need)
            if keep and set(keep) != set(current):
                ir.replace_op(
                    nid,
                    dataclasses.replace(op, column_names=keep),
                    recompute=False,
                )
                changed += 1
        elif isinstance(op, MapOp):
            keep = tuple((n, e) for n, e in op.exprs if n in need)
            if keep and len(keep) != len(op.exprs):
                ir.replace_op(nid, MapOp(keep), recompute=False)
                changed += 1
        elif isinstance(op, JoinOp):
            keep = tuple(oc for oc in op.output_columns if oc[2] in need)
            if keep and len(keep) != len(op.output_columns):
                ir.replace_op(
                    nid,
                    dataclasses.replace(op, output_columns=keep),
                    recompute=False,
                )
                changed += 1
        elif isinstance(op, AggOp):
            keep = tuple(v for v in op.values if v[0] in need or not need)
            if keep and len(keep) != len(op.values):
                ir.replace_op(
                    nid, dataclasses.replace(op, values=keep), recompute=False
                )
                changed += 1
    if changed:
        # Union branches can diverge in width after pruning: a Map branch
        # shrinks to what sinks consume while a Filter branch keeps full
        # width (filters do not project) — e.g. px/dns_flow_graph's
        # df.append(leftovers). Project only the branches whose
        # POST-prune columns diverge from the target, BEFORE relations
        # recompute (the UnionOp's own consistency check would raise
        # mid-recompute otherwise).
        predicted = _predicted_columns(ir, order)
        for nid in order:
            if not isinstance(ir._ops[nid], UnionOp):
                continue
            need = needed[nid]
            parents = ir.parents(nid)
            if not need or not parents:
                continue
            target = [c for c in predicted[parents[0]] if c in need]
            if not target:
                continue
            new_parents = []
            for p in parents:
                if predicted[p] == target:
                    new_parents.append(p)
                    continue
                proj = ir.add(
                    MapOp(tuple((c, ColumnRef(c)) for c in target)), [p]
                )
                new_parents.append(proj)
                changed += 1
            ir._parents[nid] = new_parents
        ir.recompute_all()
    return changed


def _predicted_columns(ir, order) -> dict[int, list]:
    """Post-prune output column lists per node, computed WITHOUT touching
    stored relations (they may be transiently inconsistent mid-batch)."""
    out: dict[int, list] = {}
    for nid in order:
        op = ir._ops[nid]
        parents = ir.parents(nid)
        if isinstance(op, MemorySourceOp):
            out[nid] = (
                list(op.column_names)
                if op.column_names is not None
                else list(ir.relation(nid).col_names())
            )
        elif isinstance(op, MapOp):
            out[nid] = [n for n, _ in op.exprs]
        elif isinstance(op, JoinOp):
            out[nid] = [o for _, _, o in op.output_columns]
        elif isinstance(op, AggOp):
            out[nid] = list(op.groups) + [n for n, _ in op.values]
        elif parents:
            out[nid] = list(out[parents[0]])
        else:
            out[nid] = list(ir.relation(nid).col_names())
    return out


def run_all(ir) -> None:
    merge_consecutive_maps(ir)
    prune_columns(ir)
    ir.prune_dead()
