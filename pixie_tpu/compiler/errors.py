"""Compiler error type (ref: compilerpb error payloads with line info)."""


class CompilerError(Exception):
    pass
