"""QLObject layer: the objects a PxL script manipulates.

Ref: src/carnot/planner/compiler/objects/ — PixieModule (px), Dataframe
(objects/dataframe.h:40), expression objects, metadata property resolution.
Each DataFrame wraps an IR node id; operations append IR nodes and return new
DataFrames. Relations resolve eagerly so script errors carry the offending
operation.
"""

from __future__ import annotations

import dataclasses
import re
import time
from typing import Any, Optional

from pixie_tpu.plan.expressions import (
    AggregateExpression,
    ColumnRef,
    Constant,
    FuncCall,
    ScalarExpression,
    expr_data_type,
)
from pixie_tpu.plan.operators import (
    AggOp,
    FilterOp,
    JoinOp,
    JoinType,
    LimitOp,
    MapOp,
    MemorySourceOp,
    OTelExportSinkOp,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)
from pixie_tpu.types import DataType, SemanticType


from pixie_tpu.compiler.errors import CompilerError  # noqa: E402


def _lit_type(v) -> DataType:
    if isinstance(v, bool):
        return DataType.BOOLEAN
    if isinstance(v, int):
        return DataType.INT64
    if isinstance(v, float):
        return DataType.FLOAT64
    if isinstance(v, str):
        return DataType.STRING
    raise CompilerError(f"unsupported literal {v!r}")


def to_expr(v) -> ScalarExpression:
    if isinstance(v, ColumnExpr):
        return v.expr
    if isinstance(v, ScalarExpression):
        return v
    return Constant(v, _lit_type(v))


_BIN_FUNCS = {
    "__add__": "add",
    "__sub__": "subtract",
    "__mul__": "multiply",
    "__truediv__": "divide",
    "__mod__": "modulo",
    "__pow__": "pow",
    "__and__": "logical_and",
    "__or__": "logical_or",
    "__eq__": "equal",
    "__ne__": "notEqual",
    "__lt__": "lessThan",
    "__le__": "lessThanEqual",
    "__gt__": "greaterThan",
    "__ge__": "greaterThanEqual",
}


class ColumnExpr:
    """A scalar expression bound to a DataFrame (ref: ExprObject)."""

    def __init__(self, expr: ScalarExpression, df: Optional["DataFrameObj"] = None):
        self.expr = expr
        self.df = df

    def _bin(self, name: str, other, reflected=False):
        a, b = to_expr(self), to_expr(other)
        if reflected:
            a, b = b, a
        return ColumnExpr(FuncCall(name, (a, b)), self.df or getattr(other, "df", None))

    def __invert__(self):
        return ColumnExpr(FuncCall("logical_not", (to_expr(self),)), self.df)

    def __neg__(self):
        return ColumnExpr(FuncCall("negate", (to_expr(self),)), self.df)

    def __repr__(self):
        return f"ColumnExpr({self.expr!r})"

    def __hash__(self):  # __eq__ is overloaded; keep hashable by identity
        return id(self)


for _dunder, _fname in _BIN_FUNCS.items():
    def _make(fname, refl):
        def op(self, other):
            return self._bin(fname, other, reflected=refl)
        return op
    setattr(ColumnExpr, _dunder, _make(_fname, False))
    _r = _dunder.replace("__", "__r", 1)
    if _dunder in (
        "__add__", "__sub__", "__mul__", "__truediv__", "__mod__", "__pow__",
    ):
        setattr(ColumnExpr, _r, _make(_fname, True))


@dataclasses.dataclass
class FuncRef:
    """``px.<name>`` — callable scalar function and/or aggregate reference
    (ref: FuncObject). PxL uses the bare reference in agg tuples."""

    name: str
    registry: Any

    def __call__(self, *args, **kwargs):
        if kwargs:
            raise CompilerError(f"px.{self.name} takes positional args only")
        # Flatten dict literals into alternating key/value args, but only for
        # the functions that take them that way (the reference's compiler does
        # this when lowering ScriptReference, objects/pixie_module) — a UDF
        # that legitimately accepts a dict must not be silently exploded.
        if self.name in ("script_reference",):
            flat: list = []
            for a in args:
                if isinstance(a, dict):
                    for k, v in a.items():
                        flat.extend([k, v])
                else:
                    flat.append(a)
            args = tuple(flat)
        df = next(
            (a.df for a in args if isinstance(a, ColumnExpr) and a.df), None
        )
        # Resolve the overload: prefer all args as column/constant
        # expressions; fall back to peeling trailing literals off into
        # init_args (ref: udf.h init-arg signatures like the regex pattern).
        exprs: list = []
        tail: list = []
        for a in args:
            if isinstance(a, (ColumnExpr, ScalarExpression)) or (
                isinstance(a, (str, int, float, bool)) and not tail
            ):
                exprs.append(to_expr(a))
            else:
                tail.append(a)
        rel = df.relation if df is not None else None
        for split in range(len(exprs), -1, -1):
            head = tuple(exprs[:split])
            init = tuple(
                (e.value if isinstance(e, Constant) else e)
                for e in exprs[split:]
            ) + tuple(tail)
            if any(isinstance(e, ScalarExpression) and not isinstance(e, Constant)
                   for e in exprs[split:]):
                break  # cannot demote column refs to init args
            try:
                types = [
                    expr_data_type(e, rel, self.registry) for e in head
                ] if rel is not None else [
                    e.data_type if isinstance(e, Constant) else None
                    for e in head
                ]
            except (KeyError, ValueError):
                continue
            if None not in types and (
                self.registry.lookup_scalar(self.name, types) is not None
                or self.registry.lookup_uda(self.name, types) is not None
            ):
                return ColumnExpr(FuncCall(self.name, head, init), df)
        # No overload matched; emit with the all-exprs shape so the type
        # error names the function with its actual argument types.
        return ColumnExpr(FuncCall(self.name, tuple(exprs), tuple(tail)), df)


_TIME_SUFFIX_NS = {
    "ns": 1,
    "us": 1_000,
    "ms": 1_000_000,
    "s": 1_000_000_000,
    "m": 60_000_000_000,
    "h": 3_600_000_000_000,
    "d": 86_400_000_000_000,
}


def parse_relative_time(s: str, now_ns: int) -> int:
    """'-5m' → now-5min in ns (ref: planner time parsing)."""
    m = re.fullmatch(r"(-?\d+(?:\.\d+)?)(ns|us|ms|s|m|h|d)", s.strip())
    if not m:
        raise CompilerError(f"cannot parse time {s!r}")
    return int(now_ns + float(m.group(1)) * _TIME_SUFFIX_NS[m.group(2)])


# ctx[key] → metadata UDF over the UPID column (ref: the analyzer's
# metadata resolution rules rewriting df.ctx into upid_to_* calls).
_CTX_FUNCS = {
    "service": "upid_to_service_name",
    "service_name": "upid_to_service_name",
    "service_id": "upid_to_service_id",
    "pod": "upid_to_pod_name",
    "pod_name": "upid_to_pod_name",
    "pod_id": "upid_to_pod_id",
    "namespace": "upid_to_namespace",
    "node": "upid_to_node_name",
    "node_name": "upid_to_node_name",
    "pid": "upid_to_pid",
    "asid": "upid_to_asid",
    "container": "upid_to_container_name",
    "container_name": "upid_to_container_name",
    "container_id": "upid_to_container_id",
    "cmdline": "upid_to_cmdline",
}


# ctx[key] over a pod_id-keyed frame (network_stats has no upid — ref:
# px/node and px/pod resolve ctx through pod_id there).
_POD_ID_CTX_FUNCS = {
    "pod": "pod_id_to_pod_name",
    "pod_name": "pod_id_to_pod_name",
    "service": "pod_id_to_service_name",
    "service_name": "pod_id_to_service_name",
    "service_id": "pod_id_to_service_id",
    "namespace": "pod_id_to_namespace",
    "node": "pod_id_to_node_name",
    "node_name": "pod_id_to_node_name",
}


class CtxAccessor:
    def __init__(self, df: "DataFrameObj"):
        self.df = df

    def __getitem__(self, key: str) -> ColumnExpr:
        fn = _CTX_FUNCS.get(key)
        if fn is None:
            raise CompilerError(
                f"ctx[{key!r}] is not a known metadata property "
                f"(have: {sorted(_CTX_FUNCS)})"
            )
        if (
            not self.df._has_upid_column()
            and self.df.relation.has_column("pod_id")
            and key in _POD_ID_CTX_FUNCS
        ):
            return ColumnExpr(
                FuncCall(_POD_ID_CTX_FUNCS[key], (ColumnRef("pod_id"),)),
                self.df,
            )
        upid = self.df._upid_column()
        return ColumnExpr(FuncCall(fn, (ColumnRef(upid),)), self.df)


def _parse_window(window) -> int:
    """Window size → nanoseconds (ref: ParseAllTimeFormats accepting ints
    and duration strings)."""
    import numpy as _np

    if isinstance(window, (int, _np.integer)):
        return int(window)
    if isinstance(window, str):
        return -parse_relative_time("-" + window.lstrip("-"), 0)
    if isinstance(window, ColumnExpr) and isinstance(window.expr, Constant):
        return int(window.expr.value)
    raise CompilerError(f"rolling: cannot parse window {window!r}")


def _reject_rolling_operand(left, right, op_name: str) -> None:
    """Combining a rolling view with another frame has no well-defined
    window semantics (the other side's time_ is unbinned); fail loudly
    instead of silently losing or misapplying the window axis. Aggregate
    the rolling view first, then merge/append the per-window rows."""
    if getattr(left, "_rolling_on", None) is not None or (
        getattr(right, "_rolling_on", None) is not None
    ):
        raise CompilerError(
            f"{op_name}() over a rolling view is unsupported: aggregate "
            "the windowed frame first, then combine the per-window rows"
        )


class GroupedDataFrame:
    def __init__(self, df: "DataFrameObj", by: tuple[str, ...]):
        self.df = df
        self.by = by
        for g in by:
            if not df.relation.has_column(g):
                raise CompilerError(
                    f"groupby column {g!r} not in {df.relation.col_names()}"
                )

    def agg(self, **kwargs) -> "DataFrameObj":
        return self.df._agg(self.by, kwargs)


class DataFrameObj:
    """The PxL DataFrame (ref: objects/dataframe.h:40)."""

    def __init__(self, ir, node_id: int):
        self._ir = ir
        self._id = node_id

    # -- plumbing -----------------------------------------------------------
    @property
    def relation(self):
        return self._ir.relation(self._id)

    def _wrap(self, nid: int) -> "DataFrameObj":
        out = DataFrameObj(self._ir, nid)
        # A rolling() view survives intervening ops (filter, assign, drop):
        # the window marker rides every derived frame so the window group
        # axis cannot be silently lost before groupby().agg() (ADVICE r4).
        rolling_on = getattr(self, "_rolling_on", None)
        if rolling_on is not None:
            out._rolling_on = rolling_on
        return out

    def _col(self, name: str) -> ColumnExpr:
        if not self.relation.has_column(name):
            raise CompilerError(
                f"column {name!r} not found; have {self.relation.col_names()}"
            )
        return ColumnExpr(ColumnRef(name), self)

    def _has_upid_column(self) -> bool:
        return any(
            c.semantic_type == SemanticType.ST_UPID for c in self.relation
        )

    def _upid_column(self) -> str:
        for c in self.relation:
            if c.semantic_type == SemanticType.ST_UPID:
                return c.name
        if self.relation.has_column("upid"):
            return "upid"
        raise CompilerError(
            "ctx[] requires a UPID column in the DataFrame "
            f"(have {self.relation.col_names()})"
        )

    # -- script surface -----------------------------------------------------
    @property
    def ctx(self) -> CtxAccessor:
        return CtxAccessor(self)

    def __getitem__(self, item):
        if isinstance(item, str):
            return self._col(item)
        if isinstance(item, tuple) and all(isinstance(n, str) for n in item):
            item = list(item)  # df['a', 'b', ...] projection sugar
        if isinstance(item, list):
            exprs = tuple((n, ColumnRef(n)) for n in item)
            for n in item:
                if not self.relation.has_column(n):
                    raise CompilerError(
                        f"column {n!r} not found; have {self.relation.col_names()}"
                    )
            return self._wrap(self._ir.add(MapOp(exprs), [self._id]))
        if isinstance(item, ColumnExpr):
            return self._wrap(
                self._ir.add(FilterOp(item.expr), [self._id])
            )
        raise CompilerError(f"cannot index DataFrame with {item!r}")

    def assign_column(self, name: str, value) -> "DataFrameObj":
        """df.x = expr — emits a Map keeping existing columns (updated in
        place if `name` exists) plus the new one."""
        expr = to_expr(value)
        exprs = []
        replaced = False
        for c in self.relation:
            if c.name == name:
                exprs.append((name, expr))
                replaced = True
            else:
                exprs.append((c.name, ColumnRef(c.name)))
        if not replaced:
            exprs.append((name, expr))
        return self._wrap(self._ir.add(MapOp(tuple(exprs)), [self._id]))

    def drop(self, columns=None) -> "DataFrameObj":
        if isinstance(columns, str):
            columns = [columns]
        drop = set(columns or ())
        missing = drop - set(self.relation.col_names())
        if missing:
            raise CompilerError(f"drop: no such columns {sorted(missing)}")
        exprs = tuple(
            (c.name, ColumnRef(c.name))
            for c in self.relation
            if c.name not in drop
        )
        return self._wrap(self._ir.add(MapOp(exprs), [self._id]))

    def head(self, n: int = 5) -> "DataFrameObj":
        return self._wrap(self._ir.add(LimitOp(int(n)), [self._id]))

    def groupby(self, by) -> GroupedDataFrame:
        if isinstance(by, str):
            by = [by]
        return GroupedDataFrame(self, tuple(by))

    def rolling(self, window, on: str = "time_") -> "DataFrameObj":
        """Windowed view: subsequent groupby().agg() aggregates per
        (window, groups) with ``on`` rewritten to the window start.

        Ref: objects/dataframe.cc:386-407 RollingHandler validates the
        same surface (on='time_' only, window > 0) but the reference's
        RollingIR never lowers (rolling_ir.cc ToProto: 'Rolling operator
        not yet implemented'). We lower it TPU-first instead: the window
        id becomes one more dense group axis (floor-binned time), which
        the device pipeline's segment reductions handle natively — so
        rolling queries actually execute here."""
        if on != "time_":
            raise CompilerError(
                f"Windowing is only supported on time_ at the moment, "
                f"not {on}"
            )
        if not self.relation.has_column(on):
            raise CompilerError(f"rolling: no column {on!r}")
        window_ns = _parse_window(window)
        if window_ns <= 0:
            raise CompilerError("Window size must be > 0")
        binned = self.assign_column(
            on,
            ColumnExpr(
                FuncCall(
                    "bin",
                    (ColumnRef(on), Constant(window_ns, DataType.INT64)),
                ),
                self,
            ),
        )
        out = self._wrap(binned._id)
        out._rolling_on = on
        return out

    def agg(self, **kwargs) -> "DataFrameObj":
        return self._agg((), kwargs)

    def _agg(self, groups: tuple[str, ...], kwargs: dict) -> "DataFrameObj":
        rolling_on = getattr(self, "_rolling_on", None)
        if rolling_on is not None:
            if not self.relation.has_column(rolling_on):
                raise CompilerError(
                    f"rolling window column {rolling_on!r} was dropped "
                    "before agg(); keep it in the frame so the window axis "
                    "can group"
                )
            if rolling_on not in groups:
                # Rolling view: the window id is one more group axis, and
                # the output rows carry the window start in that column —
                # for groupby().agg() AND bare df.agg() alike.
                groups = (rolling_on,) + groups
        values = []
        for out_name, spec in kwargs.items():
            if not isinstance(spec, tuple) or len(spec) < 2:
                raise CompilerError(
                    f"agg {out_name}=... must be a (columns..., px.fn) tuple"
                )
            *cols, fn = spec
            fn_name = fn.name if isinstance(fn, FuncRef) else str(fn)
            for col in cols:
                if not self.relation.has_column(col):
                    raise CompilerError(
                        f"agg over unknown column {col!r}; have "
                        f"{self.relation.col_names()}"
                    )
            values.append(
                (
                    out_name,
                    AggregateExpression(
                        fn_name, tuple(ColumnRef(c) for c in cols)
                    ),
                )
            )
        nid = self._ir.add(
            AggOp(groups=groups, values=tuple(values)), [self._id]
        )
        # The agg CONSUMES the rolling view: its output is per-window rows,
        # not another windowed frame — construct directly so the marker
        # does not ride _wrap into downstream aggregations.
        return DataFrameObj(self._ir, nid)

    def merge(
        self,
        right: "DataFrameObj",
        how: str = "inner",
        left_on=None,
        right_on=None,
        suffixes=("_x", "_y"),
    ) -> "DataFrameObj":
        if isinstance(left_on, str):
            left_on = [left_on]
        if isinstance(right_on, str):
            right_on = [right_on]
        if left_on == [] and right_on == []:
            # Cross join (ref: px/cluster's add_time_window_column merges
            # a 1-row window table with left_on=[]): lower to an inner
            # join on a synthetic constant key, dropped from the output.
            key = "__cross_key__"
            lc = self.assign_column(
                key, ColumnExpr(Constant(1, DataType.INT64), self)
            )
            rc = right.assign_column(
                key, ColumnExpr(Constant(1, DataType.INT64), right)
            )
            out = lc.merge(
                rc,
                how=how,
                left_on=[key],
                right_on=[key],
                suffixes=suffixes,
            )
            # The key exists on BOTH sides, so both copies get suffixed.
            return out.drop([key + suffixes[0], key + suffixes[1]])
        if not left_on or not right_on:
            raise CompilerError("merge requires left_on and right_on")
        _reject_rolling_operand(self, right, "merge")
        lrel, rrel = self.relation, right.relation
        rnames = set(rrel.col_names())
        out_cols = []
        for c in lrel:
            out = c.name + suffixes[0] if c.name in rnames else c.name
            out_cols.append((0, c.name, out))
        lnames = set(lrel.col_names())
        for c in rrel:
            out = c.name + suffixes[1] if c.name in lnames else c.name
            out_cols.append((1, c.name, out))
        op = JoinOp(
            how=JoinType(how),
            left_on=tuple(left_on),
            right_on=tuple(right_on),
            output_columns=tuple(out_cols),
        )
        nid = self._ir.add(op, [self._id, right._id])
        return self._wrap(nid)

    def append(self, other: "DataFrameObj") -> "DataFrameObj":
        _reject_rolling_operand(self, other, "append")
        return self._wrap(
            self._ir.add(UnionOp(), [self._id, other._id])
        )

    def stream(self) -> "DataFrameObj":
        """Mark the source chain streaming (memory_source_node.h:61)."""
        for nid in [self._id] + list(self._ir._ancestors(self._id)):
            op = self._ir.op(nid)
            if isinstance(op, MemorySourceOp):
                self._ir.replace_op(
                    nid, dataclasses.replace(op, streaming=True)
                )
        return self

    def __repr__(self):
        return f"DataFrame({self.relation!r})"


def _col_name(v, what: str) -> str:
    """Column name from a ColumnExpr used in an OTel spec."""
    if isinstance(v, ColumnExpr) and isinstance(v.expr, ColumnRef):
        return v.expr.name
    raise CompilerError(
        f"px.otel {what} must be a plain DataFrame column reference"
    )


class _OTelData:
    def __init__(self, resource: dict, data: list, endpoint=None):
        if "service.name" not in resource:
            raise CompilerError(
                "px.otel.Data resource must include 'service.name'"
            )
        self.resource = resource
        self.data = data if isinstance(data, (list, tuple)) else [data]
        self.endpoint = endpoint

    def to_op(self, df: "DataFrameObj"):
        if any(s["kind"] == "gauge" for s in self.data) and not (
            df.relation.has_column("time_")
        ):
            # Ref: otel.h Gauge doc — "The source DataFrame must have a
            # `time_` column ... or the compiler will throw an error."
            raise CompilerError(
                "px.otel.metric.Gauge requires a time_ column on the "
                "exported DataFrame"
            )
        # Every referenced column must exist in the EXPORTED frame — a
        # typo or a column from another DataFrame must fail at compile
        # time, not as a KeyError mid-query.
        refs = [
            v for _, v in (
                (k, v) for k, v in self.resource.items()
                if isinstance(v, ColumnExpr)
            )
        ]
        for spec in self.data:
            f = spec["fields"]
            refs += [f[k] for k in ("value_column", "time_column",
                                    "start_time_column", "end_time_column",
                                    "name_column") if f.get(k)]
            refs += [c for _, c in f.get("attributes", ())]
        for r in refs:
            # _col_name first: computed expressions must get the accurate
            # "must be a plain column reference" error, not a bogus
            # missing-column complaint about the function name.
            name = _col_name(r, "spec") if isinstance(r, ColumnExpr) else r
            if not df.relation.has_column(name):
                raise CompilerError(
                    f"px.otel spec references column {name!r} not present "
                    f"in the exported DataFrame "
                    f"(have {df.relation.col_names()})"
                )
        resource = tuple(
            (
                (k, _col_name(v, "resource"), True)
                if isinstance(v, ColumnExpr)
                else (k, str(v), False)
            )
            for k, v in self.resource.items()
        )
        metrics, spans = [], []
        for spec in self.data:
            if spec["kind"] == "gauge":
                metrics.append(tuple(sorted(spec["fields"].items())))
            else:
                spans.append(tuple(sorted(spec["fields"].items())))
        return OTelExportSinkOp(
            resource=resource,
            metrics=tuple(metrics),
            spans=tuple(spans),
            endpoint=self.endpoint,
        )


class _OTelMetricNS:
    @staticmethod
    def Gauge(name, value, description="", attributes=None, unit=""):
        return {
            "kind": "gauge",
            "fields": {
                "name": str(name),
                "value_column": _col_name(value, "Gauge value"),
                "time_column": "time_",
                "description": description,
                "unit": unit,
                "attributes": tuple(
                    (k, _col_name(v, "attribute"))
                    for k, v in (attributes or {}).items()
                ),
            },
        }


class _OTelTraceNS:
    @staticmethod
    def Span(name, start_time, end_time, attributes=None):
        fields = {
            "start_time_column": _col_name(start_time, "Span start_time"),
            "end_time_column": _col_name(end_time, "Span end_time"),
            "attributes": tuple(
                (k, _col_name(v, "attribute"))
                for k, v in (attributes or {}).items()
            ),
        }
        if isinstance(name, ColumnExpr):
            fields["name_column"] = _col_name(name, "Span name")
            fields["name"] = ""
        else:
            fields["name_column"] = ""
            fields["name"] = str(name)
        return {"kind": "span", "fields": fields}


class _OTelModule:
    """px.otel namespace (ref: planner/objects/otel.h OTelModule)."""

    metric = _OTelMetricNS()
    trace = _OTelTraceNS()

    @staticmethod
    def Data(resource: dict, data, endpoint=None) -> _OTelData:
        return _OTelData(resource, data, endpoint)

    @staticmethod
    def Endpoint(url: str, headers=None, insecure: bool = False) -> str:
        # Full connection config rides as JSON when more than a URL is
        # given — silently dropping auth headers would surface as baffling
        # 401s at the collector.
        if headers or insecure:
            import json as _json

            return _json.dumps(
                {
                    "url": str(url),
                    "headers": dict(headers or {}),
                    "insecure": bool(insecure),
                }
            )
        return str(url)


class PxModule:
    """The ``px`` module object (ref: objects/pixie_module.*)."""

    def __init__(self, ir, registry, now_ns: Optional[int] = None):
        self._ir = ir
        self._registry = registry
        self.now_ns = now_ns if now_ns is not None else time.time_ns()
        self.display_calls: list[tuple[int, str]] = []  # (ir node, name)

    # -- frame construction -------------------------------------------------
    def DataFrame(
        self,
        table: str,
        select=None,
        start_time=None,
        end_time=None,
    ) -> DataFrameObj:
        nid = self._ir.add(
            MemorySourceOp(
                table_name=table,
                column_names=tuple(select) if select else None,
                start_time=self._time(start_time),
                stop_time=self._time(end_time),
            )
        )
        return DataFrameObj(self._ir, nid)

    def _time(self, t) -> Optional[int]:
        if t is None:
            return None
        if isinstance(t, str):
            return parse_relative_time(t, self.now_ns)
        return int(t)

    def display(self, df: DataFrameObj, name: str = "output") -> None:
        if not isinstance(df, DataFrameObj):
            raise CompilerError("px.display takes a DataFrame")
        nid = self._ir.add(ResultSinkOp(name), [df._id])
        self.display_calls.append((nid, name))

    def debug(self, df: DataFrameObj, name: str = "output") -> None:
        """Ref: px.debug — display under a '_'-prefixed table name
        (planner/objects/pixie_module.cc kDebugTableCmdID)."""
        self.display(df, "_" + name)

    # -- OTel export (ref: planner/objects/otel.h px.otel module +
    #    px.export lowering to OTelExportSinkOperator) ---------------------
    @property
    def otel(self) -> "_OTelModule":
        return _OTelModule()

    def export(self, df: DataFrameObj, data: "_OTelData") -> None:
        if not isinstance(df, DataFrameObj):
            raise CompilerError("px.export takes a DataFrame")
        if not isinstance(data, _OTelData):
            raise CompilerError(
                "px.export takes a px.otel.Data(...) config"
            )
        nid = self._ir.add(data.to_op(df), [df._id])
        # Exports are sinks: they keep the query alive like a display.
        self.display_calls.append((nid, "__otel__"))

    # -- time helpers -------------------------------------------------------
    def now(self) -> int:
        return self.now_ns

    @staticmethod
    def parse_duration(s) -> int:
        """'-5m' -> -300000000000 ns (ref: compile-time ParseDuration,
        objects/pixie_module; px/pod uses px.now() + px.parse_duration)."""
        if isinstance(s, (int, float)):
            return int(s)
        return parse_relative_time(str(s), 0)

    @staticmethod
    def nanoseconds(n):
        return int(n)

    @staticmethod
    def microseconds(n):
        return int(n) * 1_000

    @staticmethod
    def milliseconds(n):
        return int(n) * 1_000_000

    @staticmethod
    def seconds(n):
        return int(n) * 1_000_000_000

    @staticmethod
    def minutes(n):
        return int(n) * 60_000_000_000

    @staticmethod
    def hours(n):
        return int(n) * 3_600_000_000_000

    @staticmethod
    def days(n):
        return int(n) * 86_400_000_000_000

    def DurationNanos(self, n):
        if isinstance(n, ColumnExpr):
            return ColumnExpr(FuncCall("DurationNanos", (to_expr(n),)), n.df)
        return int(n)

    def Time(self, n):
        if isinstance(n, ColumnExpr):
            return ColumnExpr(FuncCall("Time", (to_expr(n),)), n.df)
        return int(n)

    # Semantic type wrappers (px.Service/px.Namespace/... appear both as
    # parameter annotations and as value casts like px.Node(hostname)).
    @staticmethod
    def Service(v=None):
        return v

    @staticmethod
    def Namespace(v=None):
        return v

    @staticmethod
    def Pod(v=None):
        return v

    @staticmethod
    def Node(v=None):
        return v

    @staticmethod
    def Container(v=None):
        return v

    @staticmethod
    def Bytes(v=None):
        return v

    @staticmethod
    def Percent(v=None):
        return v

    @staticmethod
    def UPID(v=None):
        return v

    # -- function namespace -------------------------------------------------
    def __getattr__(self, name: str):
        # Fall through to registry functions: px.mean, px.quantiles,
        # px.upid_to_service_name, px.bin, ... Underscore-prefixed names
        # resolve only when registered (the reference ships _exec_*,
        # _predict_request_path_cluster, etc.); dunders never do — Python
        # protocol probes (__deepcopy__ and friends) must raise cleanly.
        reg = self.__dict__.get("_registry")
        if name.startswith("__") or (
            name.startswith("_")
            and not (
                reg is not None
                and (
                    reg.has_scalar(name)
                    or reg.has_uda(name)
                    or reg.lookup_udtf(name) is not None
                )
            )
        ):
            raise AttributeError(name)
        if reg is not None and reg.lookup_udtf(name) is not None:
            # UDTF call produces a DataFrame (ref: the compiler lowers
            # px.GetAgentStatus() to a UDTFSourceOperator).
            udtf = reg.lookup_udtf(name)

            def make_udtf_source(*args, **kwargs):
                params = list(udtf.arg_spec)
                if len(args) > len(params):
                    raise CompilerError(
                        f"px.{name}() takes {len(params)} positional "
                        f"args, got {len(args)}"
                    )
                for p, a in zip(params, args):
                    kwargs.setdefault(p, a)
                unknown = set(kwargs) - set(params)
                if unknown:
                    raise CompilerError(
                        f"px.{name}() has no args {sorted(unknown)}"
                    )
                nid = self._ir.add(
                    UDTFSourceOp(
                        udtf_name=name,
                        arg_values=tuple(
                            (p, kwargs[p]) for p in params if p in kwargs
                        ),
                    )
                )
                return DataFrameObj(self._ir, nid)

            return make_udtf_source
        if reg is not None and (reg.has_scalar(name) or reg.has_uda(name)):
            return FuncRef(name, reg)
        raise CompilerError(f"px has no attribute or function {name!r}")
