"""AST interpreter for PxL scripts.

Ref: src/carnot/planner/compiler/ast_visitor.{h,cc} (ASTVisitorImpl) — walks
the parsed Python AST, manipulating QLObjects. PxL is Python syntax, so the
stdlib ``ast`` module replaces the reference's libpypa parser
(parser/parser.h:38).

Supported surface (the reference's scripts use exactly this shape):
module-level statements, assignments (names, df.attr, df['col']), function
defs + calls, binary/compare/bool/unary ops, literals, lists/tuples/dicts,
f-strings over compile-time values, and px.* calls.
"""

from __future__ import annotations

import ast
from typing import Any, Optional

from pixie_tpu.compiler.objects import (
    ColumnExpr,
    CompilerError,
    DataFrameObj,
    PxModule,
)

_BINOP_FUNCS = {
    ast.Add: "__add__",
    ast.Sub: "__sub__",
    ast.Mult: "__mul__",
    ast.Div: "__truediv__",
    ast.Mod: "__mod__",
    ast.Pow: "__pow__",
    ast.BitAnd: "__and__",
    ast.BitOr: "__or__",
}

_CMPOP_FUNCS = {
    ast.Eq: "__eq__",
    ast.NotEq: "__ne__",
    ast.Lt: "__lt__",
    ast.LtE: "__le__",
    ast.Gt: "__gt__",
    ast.GtE: "__ge__",
}


class _UserFunc:
    """A PxL-defined function, interpreted in a child scope on call."""

    def __init__(self, visitor: "ASTVisitor", node: ast.FunctionDef, closure: dict):
        self.visitor = visitor
        self.node = node
        self.closure = closure

    def __call__(self, *args, **kwargs):
        params = [a.arg for a in self.node.args.args]
        defaults = self.node.args.defaults
        bound = dict(self.closure)
        # Bind defaults right-aligned, then positionals, then keywords.
        for name, d in zip(params[len(params) - len(defaults):], defaults):
            bound[name] = self.visitor._eval(d, bound)
        for name, v in zip(params, args):
            bound[name] = v
        for k, v in kwargs.items():
            if k not in params:
                raise CompilerError(
                    f"{self.node.name}() got unexpected keyword {k!r}"
                )
            bound[k] = v
        missing = [p for p in params if p not in bound]
        if missing:
            raise CompilerError(
                f"{self.node.name}() missing arguments {missing}"
            )
        return self.visitor._exec_body(self.node.body, bound)


class _Return(Exception):
    def __init__(self, value):
        self.value = value


import re as _re

# A line that ends a parenthesized value with no trailing comma, followed
# by a line that starts another keyword argument — the libpypa-tolerated
# shape in shipped px/ scripts.
_KWARG_LINE = _re.compile(r"[)\]'\"\w]\s*$")
_NEXT_KWARG = _re.compile(r"^\s*\w+\s*=[^=]")


def _repair_missing_kwarg_commas(source: str):
    """Insert the commas libpypa forgives: between a line ending a kwarg
    value and a following `name=...` line at the same call depth. Returns
    the repaired source, or None if nothing looked repairable."""
    lines = source.split("\n")
    changed = False
    depth = 0
    for i, line in enumerate(lines):
        stripped = line.split("#", 1)[0]
        new_depth = depth + (
            stripped.count("(") + stripped.count("[")
            - stripped.count(")") - stripped.count("]")
        )
        if (
            depth > 0
            and new_depth > 0
            and _KWARG_LINE.search(stripped)
            and not stripped.rstrip().endswith(",")
            and i + 1 < len(lines)
            and _NEXT_KWARG.match(lines[i + 1])
        ):
            lines[i] = line.rstrip() + ","
            changed = True
        depth = max(new_depth, 0)
    return "\n".join(lines) if changed else None


class ASTVisitor:
    def __init__(self, px: PxModule, globals_: Optional[dict] = None):
        self.px = px
        self.env: dict[str, Any] = {"px": px}
        if globals_:
            self.env.update(globals_)

    # -- statements ---------------------------------------------------------
    def run(self, source: str) -> None:
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            # The reference's PxL parser (libpypa-based) tolerates a
            # missing comma between keyword arguments across lines, and
            # several SHIPPED px/ scripts rely on it (px/service line 101,
            # px/pod, px/namespace, px/services: `x=('c', px.count)` with
            # no trailing comma). Vendored scripts must run byte-identical
            # (SURVEY §7.5), so repair exactly that shape and reparse.
            repaired = _repair_missing_kwarg_commas(source)
            if repaired is not None:
                try:
                    tree = ast.parse(repaired)
                except SyntaxError:
                    raise CompilerError(
                        f"PxL syntax error: {e}"
                    ) from None
            else:
                raise CompilerError(f"PxL syntax error: {e}") from None
        try:
            self._exec_body(tree.body, self.env, module_level=True)
        except _Return:
            raise CompilerError("return outside function")

    def _exec_body(self, body, scope: dict, module_level: bool = False):
        try:
            for stmt in body:
                self._exec_stmt(stmt, scope)
        except _Return as r:
            if module_level:
                raise
            return r.value
        return None

    def _exec_stmt(self, stmt, scope: dict) -> None:
        try:
            self._exec_stmt_inner(stmt, scope)
        except CompilerError as e:
            if not getattr(e, "_located", False):
                e._located = True
                e.args = (f"line {stmt.lineno}: {e.args[0]}",) + e.args[1:]
            raise

    def _exec_stmt_inner(self, stmt, scope: dict) -> None:
        if isinstance(stmt, ast.Assign):
            value = self._eval(stmt.value, scope)
            for target in stmt.targets:
                self._assign(target, value, scope)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign(stmt.target, self._eval(stmt.value, scope), scope)
        elif isinstance(stmt, ast.AugAssign):
            cur = self._eval(_load_of(stmt.target), scope)
            fn = _BINOP_FUNCS.get(type(stmt.op))
            if fn is None:
                raise CompilerError(f"unsupported operator {stmt.op}")
            self._assign(stmt.target, _apply_binop(cur, fn, self._eval(stmt.value, scope)), scope)
        elif isinstance(stmt, ast.Expr):
            self._eval(stmt.value, scope)
        elif isinstance(stmt, ast.FunctionDef):
            fn = _UserFunc(self, stmt, scope)
            # Apply decorators innermost-first (@pxtrace.probe('Func')).
            for deco in reversed(stmt.decorator_list):
                fn = self._eval(deco, scope)(fn)
            scope[stmt.name] = fn
        elif isinstance(stmt, ast.Return):
            raise _Return(
                self._eval(stmt.value, scope) if stmt.value else None
            )
        elif isinstance(stmt, ast.If):
            cond = self._eval(stmt.test, scope)
            if isinstance(cond, ColumnExpr):
                raise CompilerError(
                    "if over column expressions is not supported; use "
                    "px.select or a filter df[cond]"
                )
            branch = stmt.body if cond else stmt.orelse
            for s in branch:
                self._exec_stmt(s, scope)
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            # Scripts may `import px`; the name is pre-bound.
            pass
        elif isinstance(stmt, ast.Pass):
            pass
        else:
            raise CompilerError(
                f"unsupported statement {type(stmt).__name__}"
            )

    def _assign(self, target, value, scope: dict) -> None:
        if isinstance(target, ast.Name):
            scope[target.id] = value
        elif isinstance(target, ast.Attribute):
            obj = self._eval(target.value, scope)
            if not isinstance(obj, DataFrameObj):
                raise CompilerError(
                    f"cannot set attribute on {type(obj).__name__}"
                )
            new_df = obj.assign_column(target.attr, value)
            self._rebind(target.value, obj, new_df, scope)
        elif isinstance(target, ast.Subscript):
            obj = self._eval(target.value, scope)
            key = self._eval(target.slice, scope)
            if not isinstance(obj, DataFrameObj) or not isinstance(key, str):
                raise CompilerError("only df['col'] = ... assignment supported")
            new_df = obj.assign_column(key, value)
            self._rebind(target.value, obj, new_df, scope)
        elif isinstance(target, (ast.Tuple, ast.List)):
            vals = list(value)
            if len(vals) != len(target.elts):
                raise CompilerError("unpacking arity mismatch")
            for t, v in zip(target.elts, vals):
                self._assign(t, v, scope)
        else:
            raise CompilerError(
                f"unsupported assignment target {type(target).__name__}"
            )

    def _rebind(self, node, old, new, scope: dict) -> None:
        """df.x = ... mutates the *name* df points at (PxL dataframes are
        value-semantics over an immutable IR — the reference rebinds the
        variable in its var table the same way)."""
        if isinstance(node, ast.Name):
            scope[node.id] = new
        else:
            raise CompilerError(
                "column assignment requires a simple variable target"
            )

    # -- expressions --------------------------------------------------------
    def _eval(self, node, scope: dict):
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            if node.id not in scope:
                raise CompilerError(f"name {node.id!r} is not defined")
            return scope[node.id]
        if isinstance(node, ast.Attribute):
            obj = self._eval(node.value, scope)
            if isinstance(obj, DataFrameObj):
                if node.attr in (
                    "ctx", "relation", "groupby", "agg", "merge", "head",
                    "drop", "append", "stream", "rolling",
                ):
                    return getattr(obj, node.attr)
                return obj._col(node.attr)
            try:
                return getattr(obj, node.attr)
            except AttributeError:
                raise CompilerError(
                    f"{type(obj).__name__} has no attribute {node.attr!r}"
                ) from None
        if isinstance(node, ast.Subscript):
            obj = self._eval(node.value, scope)
            key = self._eval(node.slice, scope)
            try:
                return obj[key]
            except (KeyError, IndexError, TypeError) as e:
                raise CompilerError(str(e)) from None
        if isinstance(node, ast.Call):
            fn = self._eval(node.func, scope)
            args = [self._eval(a, scope) for a in node.args]
            kwargs = {
                kw.arg: self._eval(kw.value, scope)
                for kw in node.keywords
                if kw.arg is not None
            }
            if not callable(fn):
                raise CompilerError(f"{fn!r} is not callable")
            return fn(*args, **kwargs)
        if isinstance(node, ast.BinOp):
            fn = _BINOP_FUNCS.get(type(node.op))
            if fn is None:
                raise CompilerError(f"unsupported operator {node.op}")
            return _apply_binop(
                self._eval(node.left, scope), fn, self._eval(node.right, scope)
            )
        if isinstance(node, ast.Compare):
            if len(node.ops) != 1:
                raise CompilerError("chained comparisons are not supported")
            if isinstance(node.ops[0], (ast.In, ast.NotIn)):
                left = self._eval(node.left, scope)
                items = self._eval(node.comparators[0], scope)
                if not isinstance(left, ColumnExpr):
                    contained = left in items
                    return (
                        contained
                        if isinstance(node.ops[0], ast.In)
                        else not contained
                    )
                if not isinstance(items, (list, tuple)) or not items:
                    raise CompilerError(
                        "'in' over a column requires a non-empty "
                        "list/tuple of constants"
                    )
                # Lower to the equal-chains the engine already executes:
                # OR of == for `in`, AND of != for `not in`. The serving
                # normalizer re-folds the OR-of-equals shape into one
                # LUT-lane IN term for predicate batching.
                eq, join = (
                    ("__eq__", "__or__")
                    if isinstance(node.ops[0], ast.In)
                    else ("__ne__", "__and__")
                )
                out = _apply_binop(left, eq, items[0])
                for v in items[1:]:
                    out = _apply_binop(out, join, _apply_binop(left, eq, v))
                return out
            fn = _CMPOP_FUNCS.get(type(node.ops[0]))
            if fn is None:
                raise CompilerError(f"unsupported comparison {node.ops[0]}")
            return _apply_binop(
                self._eval(node.left, scope),
                fn,
                self._eval(node.comparators[0], scope),
            )
        if isinstance(node, ast.BoolOp):
            vals = [self._eval(v, scope) for v in node.values]
            if not any(isinstance(v, ColumnExpr) for v in vals):
                # Plain compile-time values keep Python truthiness
                # semantics (short-circuit value, not bitwise).
                out = vals[0]
                for v in vals[1:]:
                    out = (out and v) if isinstance(node.op, ast.And) else (out or v)
                return out
            fname = "__and__" if isinstance(node.op, ast.And) else "__or__"
            out = vals[0]
            for v in vals[1:]:
                out = _apply_binop(out, fname, v)
            return out
        if isinstance(node, ast.UnaryOp):
            v = self._eval(node.operand, scope)
            if isinstance(node.op, ast.Not):
                return ~v if isinstance(v, ColumnExpr) else (not v)
            if isinstance(node.op, ast.USub):
                return -v
            if isinstance(node.op, ast.UAdd):
                return +v if not isinstance(v, ColumnExpr) else v
            raise CompilerError(f"unsupported unary op {node.op}")
        if isinstance(node, (ast.List, ast.Tuple)):
            vals = [self._eval(e, scope) for e in node.elts]
            return vals if isinstance(node, ast.List) else tuple(vals)
        if isinstance(node, ast.Dict):
            return {
                self._eval(k, scope): self._eval(v, scope)
                for k, v in zip(node.keys, node.values)
            }
        if isinstance(node, ast.JoinedStr):
            parts = []
            for v in node.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                elif isinstance(v, ast.FormattedValue):
                    val = self._eval(v.value, scope)
                    if isinstance(val, (ColumnExpr, DataFrameObj)):
                        raise CompilerError(
                            "f-strings over columns are not supported; use "
                            "string functions"
                        )
                    parts.append(str(val))
            return "".join(parts)
        if isinstance(node, ast.IfExp):
            cond = self._eval(node.test, scope)
            if isinstance(cond, ColumnExpr):
                raise CompilerError("use px.select for column conditionals")
            return self._eval(node.body if cond else node.orelse, scope)
        raise CompilerError(f"unsupported expression {type(node).__name__}")


def _apply_binop(left, fname: str, right):
    if isinstance(left, ColumnExpr) or isinstance(right, ColumnExpr):
        if not isinstance(left, ColumnExpr):
            # Reflected: build via the column operand.
            refl = {
                "__add__": "__radd__", "__sub__": "__rsub__",
                "__mul__": "__rmul__", "__truediv__": "__rtruediv__",
                "__mod__": "__rmod__", "__pow__": "__rpow__",
                "__eq__": "__eq__",
                "__ne__": "__ne__", "__lt__": "__gt__", "__le__": "__ge__",
                "__gt__": "__lt__", "__ge__": "__le__",
                "__and__": "__and__", "__or__": "__or__",
            }[fname]
            return getattr(right, refl)(left)
        return getattr(left, fname)(right)
    import operator as op

    table = {
        "__add__": op.add, "__sub__": op.sub, "__mul__": op.mul,
        "__truediv__": op.truediv, "__mod__": op.mod, "__pow__": op.pow,
        "__and__": op.and_, "__or__": op.or_, "__eq__": op.eq,
        "__ne__": op.ne, "__lt__": op.lt, "__le__": op.le,
        "__gt__": op.gt, "__ge__": op.ge,
    }
    return table[fname](left, right)


def _load_of(target):
    """Copy of an assignment target as a Load-context expression."""
    import copy

    node = copy.deepcopy(target)
    node.ctx = ast.Load()
    return node
