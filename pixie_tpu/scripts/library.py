"""Script library: load and run bundled PxL scripts (manifest + vis.json).

Ref: src/cloud/scriptmgr/ (serves the script bundle) +
src/vizier/services/query_broker's exec_funcs execution of vis.json specs —
the UI resolves a script's `variables` against user-supplied args, then asks
the compiler to execute the vis spec's functions
(`QueryRequest.exec_funcs`). Here the whole path is in-process: resolve
variables, build FuncToExecute list, hand it to the engine.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from pixie_tpu.compiler.compiler import FuncToExecute
from pixie_tpu.compiler.objects import CompilerError

_BUNDLED_ROOT = os.path.join(os.path.dirname(__file__))


@dataclasses.dataclass
class Script:
    name: str  # e.g. "px/service_stats"
    pxl: str
    vis: dict
    manifest: dict

    @property
    def variables(self) -> list[dict]:
        return list(self.vis.get("variables", []))

    def resolve_variables(self, args: Optional[dict] = None) -> dict:
        """User args + vis.json defaults -> variable values (strings; the
        exec-func layer casts per function annotation)."""
        args = dict(args or {})
        values: dict[str, str] = {}
        for v in self.variables:
            name = v["name"]
            if name in args:
                values[name] = args.pop(name)
            elif "defaultValue" in v:
                values[name] = v["defaultValue"]
            else:
                raise CompilerError(
                    f"script {self.name}: missing required arg {name!r}"
                )
            valid = v.get("validValues")
            if valid and values[name] not in valid:
                raise CompilerError(
                    f"script {self.name}: {name}={values[name]!r} not in "
                    f"{valid}"
                )
        if args:
            raise CompilerError(
                f"script {self.name}: unknown args {sorted(args)}"
            )
        return values

    def exec_funcs(self, args: Optional[dict] = None) -> list[FuncToExecute]:
        """The vis spec's function invocations with variables bound:
        every globalFunc (output = its outputName) and every widget that
        carries its own func (output = widget name)."""
        values = self.resolve_variables(args)

        def bind(func: dict) -> dict:
            bound = {}
            for a in func.get("args", []):
                if "variable" in a:
                    bound[a["name"]] = values[a["variable"]]
                else:
                    bound[a["name"]] = a.get("value", "")
            return bound

        out: list[FuncToExecute] = []
        if not self.vis.get("globalFuncs") and not any(
            w.get("func") for w in self.vis.get("widgets", [])
        ):
            # Display-only scripts (px/agent_status): the module body calls
            # px.display itself; nothing to invoke (args were validated by
            # resolve_variables above).
            return []
        for gf in self.vis.get("globalFuncs", []):
            out.append(
                FuncToExecute(
                    name=gf["func"]["name"],
                    args=bind(gf["func"]),
                    output_table=gf["outputName"],
                )
            )
        for w in self.vis.get("widgets", []):
            func = w.get("func")
            if func:
                out.append(
                    FuncToExecute(
                        name=func["name"],
                        args=bind(func),
                        output_table=w.get("name", func["name"]),
                    )
                )
        return out


def _parse_manifest(text: str) -> dict:
    """Minimal YAML subset: 'key: value' + folded blocks ('key: >')."""
    out: dict = {}
    key = None
    folded: list[str] = []
    for line in text.splitlines():
        if line.startswith("#") or line.strip() == "---":
            continue
        if line[:1].isspace():
            if key is not None:
                folded.append(line.strip())
            continue
        if key is not None and folded:
            out[key] = " ".join(folded)
        key = None
        folded = []
        if ":" in line:
            k, _, v = line.partition(":")
            v = v.strip()
            if v in (">", "|", ""):
                key = k.strip()
            else:
                out[k.strip()] = v
    if key is not None and folded:
        out[key] = " ".join(folded)
    return out


class ScriptLibrary:
    """Loads bundled scripts (and optional extra roots) by name."""

    def __init__(self, roots: Optional[list[str]] = None):
        self.roots = list(roots or []) + [_BUNDLED_ROOT]

    def names(self) -> list[str]:
        found = set()
        for root in self.roots:
            for prefix in sorted(os.listdir(root)):
                pdir = os.path.join(root, prefix)
                if not os.path.isdir(pdir):
                    continue
                for s in sorted(os.listdir(pdir)):
                    if os.path.isdir(os.path.join(pdir, s)):
                        found.add(f"{prefix}/{s}")
        return sorted(found)

    def load(self, name: str) -> Script:
        for root in self.roots:
            d = os.path.join(root, *name.split("/"))
            if not os.path.isdir(d):
                continue
            pxl_files = [f for f in os.listdir(d) if f.endswith(".pxl")]
            if len(pxl_files) != 1:
                raise CompilerError(
                    f"script {name}: expected one .pxl, found {pxl_files}"
                )
            with open(os.path.join(d, pxl_files[0])) as f:
                pxl = f.read()
            vis = {}
            vis_path = os.path.join(d, "vis.json")
            if os.path.exists(vis_path):
                with open(vis_path) as f:
                    vis = json.load(f)
            manifest = {}
            mpath = os.path.join(d, "manifest.yaml")
            if os.path.exists(mpath):
                with open(mpath) as f:
                    manifest = _parse_manifest(f.read())
            return Script(name=name, pxl=pxl, vis=vis, manifest=manifest)
        raise KeyError(f"no script named {name!r}")

    def run(self, carnot, name: str, args: Optional[dict] = None, **kwargs):
        """Execute a named script end to end on an engine instance."""
        script = self.load(name)
        return carnot.execute_query(
            script.pxl, exec_funcs=script.exec_funcs(args), **kwargs
        )
