"""Incremental materialized views (r20): dashboards read merged
partial-agg state, they don't fold.

Ref: Pixie's design point (PAPER.md) is PxL scripts re-executed on an
interval against an in-memory columnar store — the dominant serving
workload is the SAME aggregation re-folded every few seconds. This
module converts each repeat into a watermark-bounded delta fold plus a
cheap merged read:

    append ──▶ [maintenance tick] fold rows [watermark, end) through
               the view's projection/predicates into a PARTIAL
               StateBatch, merge into the carried state, persist
               (StateBatch wire codec + watermark) to the datastore
    read   ──▶ delta-fold the unflushed tail [watermark, end), merge
               with the carried state, MERGE-finalize under the
               QUERY's output names — bit-identical to folding the
               full table from scratch

Machinery reused rather than rebuilt: the r6 mergeable StateBatch wire
format and PARTIAL/MERGE AggNode stages do the folding and state
persistence; the r15/r16 datastore-backed cron runner
(vizier/cron.py, a views-prefixed CronScriptStore) makes view
definitions restart-surviving; the r7 fold-signature posture (fold
identity excludes output names) becomes a name-erased match key; the
r16 predicate normalizer (parallel/pipeline.predicate_fold_digest)
canonicalizes the predicate suffix by VALUE so dictionary growth never
flips a match.

Bit-identity contract: view-served reads equal the from-scratch fold
exactly for every order-insensitive-exact UDA lane — counts, integer
sums, float sums over exactly-representable values (the telemetry
case: durations, bytes, status codes), HLL register max, count-min
integer adds — because carried-then-delta merge preserves both the
group first-appearance order and the exact arithmetic of a single
pass. Lanes whose value depends on fold grouping (float sums over
arbitrary reals differ in final ulps) keep the same contract the
device/host split already has: test-pinned on exact-representable
data.

Match + serve: ``QueryBroker.execute_script`` probes
``ViewRegistry.try_serve`` BEFORE admission ever queues the query.
The probe is an O(1) dict lookup on the script text in steady state
(first sight of a text pays one compile+match, cached either way);
a hit requires the name-erased signature AND the predicate digest to
agree, then the carried state's out-names are positionally remapped
to the query's names for the finalize. Served queries record a
``view_hit`` rung above ``ring_hit`` on the r18 placement ladder and
stamp freshness on the QueryResult.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
import time
import uuid
from typing import Any, Optional

import numpy as np

from pixie_tpu.compiler.analyzer import substitute
from pixie_tpu.exec.agg_node import AggNode, StateBatch
from pixie_tpu.exec.exec_state import FunctionContext
from pixie_tpu.exec.expression_evaluator import ExpressionEvaluator
from pixie_tpu.parallel.pipeline import (
    match_fragment,
    predicate_fold_digest,
)
from pixie_tpu.plan.operators import AggStage, MemorySinkOp, ResultSinkOp
from pixie_tpu.table.column import DictColumn, StringDictionary
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.utils import faults, flags, metrics_registry
from pixie_tpu.vizier.cron import CronScript, CronScriptStore, ScriptRunner
from pixie_tpu.vizier.datastore import Datastore

_M = metrics_registry()
_VIEW_HITS = _M.counter(
    "broker_view_hits_total",
    "Queries answered from a materialized view's merged state before "
    "admission.",
)
_VIEW_MISSES = _M.counter(
    "broker_view_misses_total",
    "View probes that fell through to normal admission, by reason.",
)
_VIEW_STALENESS = _M.gauge(
    "view_staleness_seconds",
    "Seconds since a view's last successful maintenance (set on every "
    "maintenance and on every served read).",
)
_VIEW_MAINTAIN = _M.histogram(
    "view_maintain_seconds",
    "Wall seconds per view maintenance tick (delta fold + merge + "
    "persist).",
)
_VIEW_MAINTAIN_ERRORS = _M.counter(
    "view_maintain_errors_total",
    "View maintenance ticks that failed (the per-view breaker opens "
    "after consecutive failures; an open breaker serves nothing).",
)
_VIEW_REBUILDS = _M.counter(
    "view_rebuilds_total",
    "Carried state discarded because already-folded rows expired from "
    "the table — the from-scratch fold can no longer see them, so "
    "bit-identity demands a rebuild from the new min_row_id.",
)
_VIEW_TAIL_ROUTED = _M.counter(
    "view_tail_folds_routed_total",
    "View-hit tail delta folds attributed to the view's maintain "
    "agent (the tracker pick recorded at registration) instead of "
    "the broker, by view and agent.",
)

_SCRIPT_PREFIX = "/view_scripts/"
_STATE_PREFIX = "/view_state/"
_CHUNK_ROWS = 1 << 16
_BREAKER_THRESHOLD = 3
_PROBE_CACHE_CAP = 512
_READ_MEMO_CAP = 32


class _CaptureStats:
    total_time_ns = 0


class _Capture:
    """Duck-typed ExecNode child: collects emitted batches. Carries a
    zeroed stats shim because the parent's consume_next accounts child
    self-time over ``child.stats.total_time_ns``."""

    def __init__(self):
        self.batches: list = []
        self.stats = _CaptureStats()

    def consume_next(self, exec_state, batch, parent_index=0) -> None:
        self.batches.append(batch)


def _compile_match(broker, script: str):
    """Compile ``script`` and match the maintainable shape: ONE fragment,
    non-streaming all-time MemorySource → (Map|Filter)* → Agg(FULL,
    not windowed) → sink. Raises ValueError with the refusal reason.

    Windowed aggregation needs no special case here: a time-bucket
    group key (a Map expression over time_) is just another composed
    group expression — the view carries one state row per bucket and
    the bucketed read falls out of the ordinary merge."""
    logical = broker.compiler.compile(
        script, broker.table_relations, now_ns=0
    )
    frags = logical.fragments
    if len(frags) != 1:
        raise ValueError("view scripts must compile to one fragment")
    frag = frags[0]
    relations = frag.resolve_relations(
        broker.registry, lambda op: broker.table_relations[op.table_name]
    )
    m = match_fragment(frag, relations)
    if m is None:
        raise ValueError(
            "not a maintainable source→map/filter→agg chain"
        )
    if m.agg_op.stage != AggStage.FULL or m.agg_op.windowed:
        raise ValueError("views maintain FULL non-windowed aggregates")
    if (
        m.source_op.start_time is not None
        or m.source_op.stop_time is not None
    ):
        raise ValueError(
            "time-bounded scripts are not view-maintainable (bucket by "
            "a time key instead)"
        )
    children = frag.children(m.agg_nid)
    if len(children) != 1:
        return_err = "aggregate feeds more than one consumer"
        raise ValueError(return_err)
    sink_op = frag.node(children[0])
    if isinstance(sink_op, ResultSinkOp):
        sink_name = sink_op.table_name
    elif isinstance(sink_op, MemorySinkOp):
        sink_name = sink_op.name
    else:
        raise ValueError("aggregate must feed the result sink directly")
    pre_agg_rel = relations[frag.parents(m.agg_nid)[0]]
    out_rel = relations[m.agg_nid]
    return m, pre_agg_rel, out_rel, sink_name


def _erased_signature(m) -> str:
    """Name-erased fold-unit identity (the r7 ``_fold_signature``
    posture): table + ORDERED composed group exprs + ORDERED
    (uda, composed args, init_args) lanes, with every output name
    erased — two scripts differing only in output naming match the
    same view, and the read positionally remaps state to the query's
    names."""
    groups = [repr(m.col_exprs[g]) for g in m.agg_op.groups]
    lanes = []
    for _out, agg in m.agg_op.values:
        args = tuple(
            repr(substitute(a, m.col_exprs)) for a in agg.args
        )
        lanes.append((agg.name, args, tuple(map(repr, agg.init_args))))
    return "|".join(
        [
            "view",
            m.source_op.table_name,
            "g:" + ";".join(groups),
            "v:" + repr(lanes),
        ]
    )


def _with_flags(sb: StateBatch, eow: bool, eos: bool) -> StateBatch:
    return dataclasses.replace(sb, eow=eow, eos=eos)


_EMPTY_TRIGGER = "empty"


@dataclasses.dataclass
class _ProbeEntry:
    """Per-script-text probe cache entry (hit or remembered miss)."""

    view_id: Optional[str]  # None = miss
    miss_reason: str = ""
    # Hit side: the QUERY's own plan objects for the finalize.
    agg_op: Any = None
    pre_agg_rel: Any = None
    out_rel: Any = None
    sink_name: str = ""
    out_names: tuple = ()
    group_names: tuple = ()


class MaterializedView:
    """One maintained view: compiled match + carried PARTIAL state +
    watermark. All state transitions happen under ``_lock`` (ticks and
    reads serialize per view; reads of DIFFERENT views run freely)."""

    def __init__(self, view_id, name, script, m, pre_agg_rel, out_rel,
                 sink_name, signature, pred_digest, refresh_interval_s,
                 registry, func_ctx):
        self.view_id = view_id
        self.name = name
        self.script = script
        self.m = m
        self.table_name = m.source_op.table_name
        self.pre_agg_rel = pre_agg_rel
        self.out_rel = out_rel
        self.sink_name = sink_name
        self.signature = signature
        self.pred_digest = pred_digest
        self.refresh_interval_s = refresh_interval_s
        self.out_names = tuple(n for n, _a in m.agg_op.values)
        self.group_names = tuple(m.agg_op.groups)
        self._registry = registry
        self._func_ctx = func_ctx
        self.partial_op = dataclasses.replace(
            m.agg_op, stage=AggStage.PARTIAL
        )
        self.partial_rel = self.partial_op.output_relation(
            [pre_agg_rel], registry
        )
        # Projection to pre-agg terms + pre-agg predicates, both in
        # SOURCE terms — the same ExpressionEvaluator the host engine's
        # Map/Filter nodes run, so the folded row set is identical.
        self._proj = ExpressionEvaluator(
            [(c.name, m.col_exprs[c.name]) for c in pre_agg_rel],
            m.source_relation,
            registry,
            func_ctx,
        )
        self._pred_evs = [
            ExpressionEvaluator(
                [("p", p)], m.source_relation, registry, func_ctx
            )
            for p in m.predicates
        ]
        # Carried state (eow/eos normalized False) + coverage.
        self.state: Optional[StateBatch] = None
        self.watermark = 0
        self.base_min: Optional[int] = None
        self.last_refresh = 0.0
        self.hits = 0
        self.maintains = 0
        self.rebuilds = 0
        self.rows_folded = 0
        self.fail_count = 0
        self.breaker_open = False
        self.last_error: Optional[str] = None
        # r21: the maintain agent this view's tail folds route to —
        # the tracker pick recorded at registration (None until the
        # tracker can name an owner; re-resolved lazily on first read).
        self.maintain_agent: Optional[str] = None
        self._lock = threading.RLock()
        self._read_memo: dict = {}

    # -- fold machinery ------------------------------------------------------
    def _new_partial_node(self):
        node = AggNode(self.partial_op, self.partial_rel, 0)
        node.set_input_relation(self.pre_agg_rel, self._registry)
        cap = _Capture()
        node.add_child(cap)
        return node, cap

    def _project(self, batch: RowBatch) -> RowBatch:
        if self._pred_evs:
            mask = None
            for ev in self._pred_evs:
                m2 = ev.evaluate_predicate(batch)
                mask = m2 if mask is None else (mask & m2)
            if not mask.all():
                batch = batch.take(np.nonzero(mask)[0])
        proj = self._proj.evaluate(batch, self.pre_agg_rel)
        proj.eow = False
        proj.eos = False
        return proj

    def _fold_range(self, table, from_row, to_row):
        """PARTIAL-fold table rows [from_row, to_row) through the
        view's predicates + projection. Returns (StateBatch | None,
        rows_seen) — None when no row survived (or none existed)."""
        node, cap = self._new_partial_node()
        fed = False
        row = from_row
        rows = 0
        while row < to_row:
            batch, nxt = table._read_from(row, _CHUNK_ROWS, None, None)
            if batch is None or nxt <= row:
                break
            start_id = nxt - batch.num_rows
            if nxt > to_row:
                batch = batch.slice(0, to_row - start_id)
            row = min(nxt, to_row)
            rows += batch.num_rows
            proj = self._project(batch)
            if proj.num_rows:
                node.consume_next(None, proj, 0)
                fed = True
        if not fed:
            return None, rows
        node.consume_next(
            None,
            RowBatch.with_zero_rows(self.pre_agg_rel, eos=True),
            0,
        )
        return _with_flags(cap.batches[-1], False, False), rows

    def _merge_parts(self, parts):
        """Combine StateBatches through a PARTIAL restage — carried
        FIRST, then deltas, so group first-appearance order matches a
        single pass over the full row stream."""
        parts = [p for p in parts if p is not None]
        if not parts:
            return None
        if len(parts) == 1:
            return parts[0]
        node, cap = self._new_partial_node()
        for sb in parts[:-1]:
            node.consume_next(None, _with_flags(sb, False, False), 0)
        node.consume_next(None, _with_flags(parts[-1], False, True), 0)
        return _with_flags(cap.batches[-1], False, False)

    # -- maintenance ---------------------------------------------------------
    def maintain(self, table) -> dict:
        """One tick: rebuild-if-expired guard, delta fold
        [watermark, end), merge into carried state. Caller persists."""
        t0 = time.time()
        if faults.ACTIVE:
            faults.check("views.maintain")
        with self._lock:
            mn = table.min_row_id()
            if self.base_min is None:
                self.base_min = mn
                self.watermark = mn
            elif mn > self.base_min:
                # Rows the carried state folded have expired; the
                # from-scratch fold can't see them, so neither may we.
                self.state = None
                self.base_min = mn
                self.watermark = mn
                self.rebuilds += 1
                _VIEW_REBUILDS.inc(view=self.name)
            end = table.end_row_id()
            delta, rows = self._fold_range(table, self.watermark, end)
            if delta is not None:
                self.state = self._merge_parts([self.state, delta])
            self.watermark = end
            self.rows_folded += rows
            self.last_refresh = time.time()
            self.fail_count = 0
            self.breaker_open = False
            self.last_error = None
            self._read_memo.clear()
            self.maintains += 1
        dt = time.time() - t0
        _VIEW_MAINTAIN.observe(dt, view=self.name)
        _VIEW_STALENESS.set(0.0, view=self.name)
        return {"rows": rows, "seconds": dt}

    def record_failure(self, err: Exception) -> None:
        with self._lock:
            self.fail_count += 1
            self.last_error = str(err)
            if self.fail_count >= _BREAKER_THRESHOLD:
                self.breaker_open = True
        _VIEW_MAINTAIN_ERRORS.inc(view=self.name)

    # -- persistence ---------------------------------------------------------
    def envelope(self) -> bytes:
        with self._lock:
            # String group-key columns persist their exact (codes,
            # dictionary) pair alongside the StateBatch payload: the
            # generic wire codec rebuilds string keys through a fresh
            # dictionary whose small-array encode path assigns codes in
            # VALUE-sorted order, and the MERGE stage's gid assignment
            # sorts by code — a recovered state would then finalize its
            # groups in a different order than the live one, breaking
            # restart bit-identity. Restoring codes verbatim keeps the
            # recovered merge permutation-identical.
            string_keys = []
            if self.state is not None:
                for col in self.state.key_columns:
                    if isinstance(col, DictColumn):
                        string_keys.append({
                            "codes": np.asarray(col.codes).tolist(),
                            "values": col.dictionary.values(),
                        })
                    else:
                        string_keys.append(None)
            meta = {
                "view_id": self.view_id,
                "name": self.name,
                "signature": self.signature,
                "pred_digest": self.pred_digest,
                "watermark": int(self.watermark),
                "base_min": (
                    int(self.base_min) if self.base_min is not None
                    else None
                ),
                "last_refresh": float(self.last_refresh),
                "string_keys": string_keys,
            }
            body = self.state.to_bytes() if self.state is not None else b""
        return json.dumps(meta).encode() + b"\x00" + body

    def recover(self, raw: bytes) -> bool:
        """Adopt a persisted envelope. False (start cold) when the
        stored signature/digest no longer matches the recompiled
        definition — a changed script must never serve stale state."""
        try:
            head, _sep, body = raw.partition(b"\x00")
            meta = json.loads(head)
            if (
                meta.get("signature") != self.signature
                or meta.get("pred_digest") != self.pred_digest
                or meta.get("base_min") is None
            ):
                return False
            state = StateBatch.from_bytes(body) if body else None
            if state is not None:
                for i, spec in enumerate(meta.get("string_keys") or []):
                    if spec is not None:
                        state.key_columns[i] = DictColumn(
                            np.asarray(spec["codes"], dtype=np.int32),
                            StringDictionary(list(spec["values"])),
                        )
        except Exception:
            return False
        with self._lock:
            self.state = (
                _with_flags(state, False, False)
                if state is not None else None
            )
            self.watermark = int(meta["watermark"])
            self.base_min = int(meta["base_min"])
            self.last_refresh = float(meta.get("last_refresh", 0.0))
        return True

    # -- read ----------------------------------------------------------------
    def _rename(self, sb: StateBatch, out_names, group_names):
        """Positionally remap a carried/delta StateBatch onto the
        QUERY's output and group names (the signature guarantees lane
        order and group order agree)."""
        if sb is None:
            return None
        states = {
            qn: sb.states[vn]
            for qn, vn in zip(out_names, self.out_names)
        }
        arg_dicts = {
            qn: sb.arg_dicts[vn]
            for qn, vn in zip(out_names, self.out_names)
            if vn in sb.arg_dicts
        }
        return StateBatch(
            key_columns=sb.key_columns,
            states=states,
            num_groups=sb.num_groups,
            group_names=tuple(group_names),
            eow=False,
            eos=False,
            arg_dicts=arg_dicts,
        )

    def read(self, table, entry: _ProbeEntry, tail_agent=None,
             tail_wrap=None):
        """Serve one query: carried state ⊕ tail delta fold, MERGE-
        finalized under the query's names. Returns (RowBatch, freshness
        dict) or (None, reason) when the view cannot serve. When
        ``tail_wrap`` is given the tail fold runs through it (r21 view
        admission placement: the registry attributes the fold to the
        view's maintain agent); memo hits never re-enter the wrapper."""
        with self._lock:
            if self.breaker_open:
                return None, "breaker_open"
            if self.maintains == 0 and self.state is None:
                return None, "cold"
            staleness = time.time() - self.last_refresh
            rail = flags.view_max_staleness_s
            if rail and staleness > rail:
                return None, "stale"
            end = table.end_row_id()
            memo_key = (
                self.watermark, end, entry.out_names, entry.group_names,
            )
            memo = self._read_memo.get(memo_key)
            if memo is None:
                def _fold():
                    return self._fold_range(table, self.watermark, end)

                if tail_wrap is not None:
                    tail, tail_rows = tail_wrap(_fold)
                else:
                    tail, tail_rows = _fold()
                carried = self._rename(
                    self.state, entry.out_names, entry.group_names
                )
                tail = self._rename(
                    tail, entry.out_names, entry.group_names
                )
                parts = [p for p in (carried, tail) if p is not None]
                merge_op = dataclasses.replace(
                    entry.agg_op,
                    stage=AggStage.MERGE,
                    pre_agg_relation=entry.pre_agg_rel,
                )
                node = AggNode(merge_op, entry.out_rel, 0)
                node.set_input_relation(
                    merge_op.merge_input_relation(entry.pre_agg_rel),
                    self._registry,
                )
                cap = _Capture()
                node.add_child(cap)
                if not parts:
                    # Zero groups observed: an empty eos StateBatch
                    # still triggers the emit, reproducing the host
                    # engine's empty-input semantics exactly (0 rows
                    # grouped; one identity row group-by-none).
                    node.consume_next(
                        None,
                        StateBatch(
                            key_columns=[], states={}, num_groups=0,
                            group_names=tuple(entry.group_names),
                            eow=False, eos=True,
                        ),
                        0,
                    )
                else:
                    for sb in parts[:-1]:
                        node.consume_next(None, sb, 0)
                    node.consume_next(
                        None, _with_flags(parts[-1], False, True), 0
                    )
                batch = cap.batches[-1]
                memo = (batch, end - self.watermark)
                if len(self._read_memo) >= _READ_MEMO_CAP:
                    self._read_memo.pop(next(iter(self._read_memo)))
                self._read_memo[memo_key] = memo
            batch, tail_rows = memo
            self.hits += 1
            wm = self.watermark
        _VIEW_STALENESS.set(staleness, view=self.name)
        return batch, {
            "view": self.name,
            "view_id": self.view_id,
            "staleness_s": staleness,
            "watermark": int(wm),
            "tail_rows": int(tail_rows),
            "tail_agent": tail_agent,
        }

    def status(self, table=None) -> dict:
        with self._lock:
            return {
                "view_id": self.view_id,
                "name": self.name,
                "table": self.table_name,
                "sink": self.sink_name,
                "signature": self.signature,
                "pred_digest": self.pred_digest,
                "watermark": int(self.watermark),
                "end_row_id": (
                    int(table.end_row_id()) if table is not None else None
                ),
                "groups": int(self.state.num_groups)
                if self.state is not None else 0,
                "staleness_s": (
                    time.time() - self.last_refresh
                    if self.maintains else None
                ),
                "refresh_interval_s": self.refresh_interval_s,
                "hits": self.hits,
                "maintains": self.maintains,
                "rebuilds": self.rebuilds,
                "rows_folded": self.rows_folded,
                "breaker_open": self.breaker_open,
                "fail_count": self.fail_count,
                "last_error": self.last_error,
            }


class ViewRegistry:
    """The broker's materialized-view plane: registration, persisted
    maintenance ticks (datastore-backed cron runner), and the
    pre-admission serve probe.

    In-process placement posture: maintenance folds run in this
    process against the shared TableStore — the agent whose ring
    holds the table (the broker tracker's ownership view, surfaced
    per view in /viewz as ``maintain_agent``) is where that work
    lands in a multi-process deployment."""

    def __init__(self, broker, table_store, datastore=None,
                 owner_fn=None):
        self._broker = broker
        self._tables = table_store
        self._ds = datastore if datastore is not None else Datastore()
        self._registry = broker.registry
        self._func_ctx = FunctionContext(
            table_store=table_store, registry=broker.registry
        )
        self._owner_fn = owner_fn
        self.store = CronScriptStore(self._ds, prefix=_SCRIPT_PREFIX)
        self.runner = ScriptRunner(broker, self.store, executor=self._tick)
        self._lock = threading.RLock()
        self._views: dict[str, MaterializedView] = {}
        self._by_key: dict[tuple, str] = {}  # (signature, digest) -> id
        self._probe_cache: dict[str, _ProbeEntry] = {}
        self.hits = 0
        self.misses = 0

    # -- lifecycle -----------------------------------------------------------
    def attach(self) -> "ViewRegistry":
        """Recover persisted view definitions + state, then start the
        tickers (restart survival: the first read after recovery folds
        only [persisted watermark, end) — never a full refold)."""
        for sid, cs in self.store.all().items():
            try:
                self._ensure_view(sid, cs)
            except Exception:
                # A definition that no longer compiles (schema drift)
                # must not take the registry down; it just won't serve.
                continue
        self.runner.sync()
        return self

    def stop(self) -> None:
        self.runner.stop()

    def _ensure_view(self, view_id: str, cs: CronScript):
        with self._lock:
            view = self._views.get(view_id)
            if view is not None and view.script == cs.script:
                return view
            m, pre_rel, out_rel, sink = _compile_match(
                self._broker, cs.script
            )
            sig = _erased_signature(m)
            digest = predicate_fold_digest(
                m.predicates, m.source_relation, self._registry,
                self._func_ctx,
            )
            if digest is None:
                raise ValueError(
                    "predicates outside the normalizable class cannot "
                    "key a view"
                )
            view = MaterializedView(
                view_id,
                cs.configs.get("name") or view_id,
                cs.script, m, pre_rel, out_rel, sink, sig, digest,
                cs.frequency_s, self._registry, self._func_ctx,
            )
            raw = self._ds.get(_STATE_PREFIX + view_id)
            if raw is not None:
                view.recover(raw)
            # r21: record the maintain-agent pick at registration —
            # tail folds on read route to it (view admission placement).
            view.maintain_agent = self._maintain_agent(view.table_name)
            self._views[view_id] = view
            self._by_key[(sig, digest)] = view_id
            self._probe_cache.clear()
            return view

    # -- registration --------------------------------------------------------
    def register(self, script: str, name: Optional[str] = None,
                 refresh_interval_s: Optional[float] = None) -> str:
        """Validate + persist + schedule a view; runs one synchronous
        maintenance so the view serves immediately. Raises ValueError
        for unsupported shapes. Idempotent: the view id derives from
        the name-erased identity, so re-registering an equivalent
        script upserts."""
        m, _pre, _out, _sink = _compile_match(self._broker, script)
        sig = _erased_signature(m)
        digest = predicate_fold_digest(
            m.predicates, m.source_relation, self._registry,
            self._func_ctx,
        )
        if digest is None:
            raise ValueError(
                "predicates outside the normalizable class cannot key "
                "a view"
            )
        view_id = "view-" + hashlib.sha256(
            (sig + "\x00" + digest).encode()
        ).hexdigest()[:12]
        cs = CronScript(
            view_id,
            script,
            refresh_interval_s
            if refresh_interval_s is not None
            else flags.view_refresh_interval_s,
            {"name": name or view_id},
        )
        self._ensure_view(view_id, cs)
        self.runner.upsert_script(cs)
        self._tick(cs)
        return view_id

    def unregister(self, view_id: str) -> None:
        with self._lock:
            self.runner.delete_script(view_id)
            self._ds.delete(_STATE_PREFIX + view_id)
            view = self._views.pop(view_id, None)
            if view is not None:
                self._by_key.pop(
                    (view.signature, view.pred_digest), None
                )
            self._probe_cache.clear()

    # -- maintenance (ScriptRunner executor) ---------------------------------
    def _tick(self, cs: CronScript) -> None:
        view = self._ensure_view(cs.script_id, cs)
        table = self._tables.get_table(view.table_name)
        if table is None:
            view.record_failure(
                ValueError(f"table {view.table_name!r} not found")
            )
            raise ValueError(f"table {view.table_name!r} not found")
        try:
            view.maintain(table)
            self._ds.set(_STATE_PREFIX + view.view_id, view.envelope())
        except Exception as e:
            view.record_failure(e)
            raise

    # -- serve probe ---------------------------------------------------------
    def _probe_compile(self, query: str) -> _ProbeEntry:
        try:
            m, pre_rel, out_rel, sink = _compile_match(
                self._broker, query
            )
            sig = _erased_signature(m)
            digest = predicate_fold_digest(
                m.predicates, m.source_relation, self._registry,
                self._func_ctx,
            )
        except Exception:
            return _ProbeEntry(None, miss_reason="no_match")
        if digest is None:
            return _ProbeEntry(None, miss_reason="predicates")
        view_id = self._by_key.get((sig, digest))
        if view_id is None:
            reason = (
                "digest_mismatch"
                if any(
                    k[0] == sig for k in self._by_key
                )
                else "no_view"
            )
            return _ProbeEntry(None, miss_reason=reason)
        return _ProbeEntry(
            view_id,
            agg_op=m.agg_op,
            pre_agg_rel=pre_rel,
            out_rel=out_rel,
            sink_name=sink,
            out_names=tuple(n for n, _a in m.agg_op.values),
            group_names=tuple(m.agg_op.groups),
        )

    def try_serve(self, query: str, tenant: str = "default"):
        """The pre-admission probe: O(1) text lookup in steady state.
        Returns a QueryResult (freshness-stamped) or None to fall
        through to normal admission + execution."""
        from pixie_tpu.engine import QueryResult

        with self._lock:
            entry = self._probe_cache.get(query)
            if entry is None:
                entry = self._probe_compile(query)
                if len(self._probe_cache) >= _PROBE_CACHE_CAP:
                    self._probe_cache.pop(next(iter(self._probe_cache)))
                self._probe_cache[query] = entry
            if entry.view_id is None:
                self.misses += 1
                _VIEW_MISSES.inc(reason=entry.miss_reason)
                return None
            view = self._views.get(entry.view_id)
        if view is None:
            self.misses += 1
            _VIEW_MISSES.inc(reason="unregistered")
            return None
        table = self._tables.get_table(view.table_name)
        if table is None:
            self.misses += 1
            _VIEW_MISSES.inc(reason="no_table")
            return None
        t0 = time.perf_counter_ns()
        tail_agent, tail_wrap = self._tail_route(view)
        batch, info = view.read(
            table, entry, tail_agent=tail_agent, tail_wrap=tail_wrap
        )
        if batch is None:
            self.misses += 1
            _VIEW_MISSES.inc(reason=info)
            return None
        self.hits += 1
        _VIEW_HITS.inc(view=view.name, tenant=tenant)
        result = QueryResult(
            query_id=str(uuid.uuid4()),
            tables={entry.sink_name: [batch]},
            exec_stats={},
            compile_time_ns=0,
            exec_time_ns=time.perf_counter_ns() - t0,
        )
        result.view = info
        return result

    # -- tail-fold routing (r21) ---------------------------------------------
    def _tail_route(self, view: MaterializedView):
        """Resolve where a view hit's unflushed-tail delta fold is
        attributed. Returns (agent_id, wrap) — (None, None) when the
        flag is off or no maintain agent is known. The wrap charges
        the fold to the maintain agent's WFQ load / inflight / table
        heat for exactly the duration of the fold, so the rebalancer
        and the placement ladder see the tail work where the r18
        posture says it belongs — never the broker."""
        if not flags.view_tail_placement:
            return None, None
        agent = view.maintain_agent
        if agent is None:
            # Registration may have preceded agent discovery; adopt
            # the tracker's current pick once it can name an owner.
            agent = view.maintain_agent = self._maintain_agent(
                view.table_name
            )
        if agent is None:
            return None, None
        placement = getattr(self._broker, "placement", None)

        def wrap(fold, _agent=agent, _view=view, _placement=placement):
            if _placement is not None:
                _placement.route_view_tail(
                    _agent, frozenset([_view.table_name])
                )
            try:
                return fold()
            finally:
                if _placement is not None:
                    _placement.release(_agent)
                _VIEW_TAIL_ROUTED.inc(view=_view.name, agent=_agent)

        return agent, wrap

    # -- observability -------------------------------------------------------
    def _maintain_agent(self, table_name: str) -> Optional[str]:
        if self._owner_fn is not None:
            try:
                return self._owner_fn(table_name)
            except Exception:
                return None
        try:
            # The r18 posture: maintenance work belongs on the agent
            # whose ring holds the table (failover_view carries the
            # ownership sets the placement ladder ranks on).
            for a in self._broker.tracker.failover_view():
                if table_name in (a.get("tables") or set()):
                    return a.get("agent_id")
        except Exception:
            pass
        return None

    def status(self) -> dict:
        with self._lock:
            views = list(self._views.values())
            hits, misses = self.hits, self.misses
        out = []
        for v in views:
            s = v.status(self._tables.get_table(v.table_name))
            s["maintain_agent"] = (
                v.maintain_agent or self._maintain_agent(v.table_name)
            )
            out.append(s)
        total = hits + misses
        return {
            "enabled": bool(flags.materialized_views),
            "views": out,
            "hits": hits,
            "misses": misses,
            "hit_rate": (hits / total) if total else 0.0,
        }
