"""Closed-loop admission control (r16): a controller, not a knob.

Ref posture: Monarch/GWP close the monitoring loop all the way to
actuation — the r15 attribution/SLO plane made this engine's serving
signals first-class (admission-wait quantiles on ``admission_wait_
seconds``, queue depth, per-dispatch device wall time in the
``device_dispatches`` ring, HBM residency snapshots), and this module
feeds them back into the three serving knobs the r15 1000-client soak
proved latency actually lives behind:

- ``admission_max_concurrent`` — MIMD (multiplicative increase ×2 /
  decrease ÷2) inside hard guard rails
  [``admission_controller_min_concurrent``,
  ``admission_controller_max_concurrent``]: raise while admitted
  queries spend more than ``admission_controller_wait_target_ms`` at
  p50 in the queue AND the residency pool has headroom; halve on HBM
  pressure (pinned past 90% of budget); decay one step toward the
  flag-default baseline when the engine idles far below target.
- ``shared_scan_window_ms`` — additive ±step within
  [0, ``admission_controller_max_window_ms``]: deepen the batching
  window while the queue has depth (a longer window widens
  predicate-batched scans, multiplying effective concurrency), shrink
  it when the queue drains (the leader-side queue-depth gate already
  skips an idle window entirely).
- ``hbm_budget_mb`` — raise 25% per window that saw ``hbm_budget``
  admission rejections, never past
  ``admission_controller_max_hbm_mb``; shrink 25% (never below the
  flag-default baseline) after a long stretch of <30% utilization.
  With no configured budget or no ceiling rail the controller refuses
  to touch HBM at all.

Stability contracts (test-pinned in tests/test_slo.py): an EMPTY
window — zero admitted queries, zero rejections — holds every knob
(signal absence is not evidence of idleness: the engine may be wedged
upstream); every actuation is clamped to its rails; and each change is
recorded on an actuation TRAIL (knob, from, to, reason, window
signals) surfaced at /statusz and by tools/soak_serving.py.

The loop rides the existing cron machinery exactly like the r15
SLOManager: one persisted ``CronScript`` whose ticker calls ``step()``
through the runner's executor hook, so the controller survives broker
restarts and ticks at ``admission_controller_interval_s``.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional

from pixie_tpu.serving import cost_model as _cost_model
from pixie_tpu.utils import flags, metrics_registry
from pixie_tpu.vizier.slo import CounterWindow, HistogramWindow

_M = metrics_registry()
_ACTUATIONS = _M.counter(
    "admission_controller_actuations_total",
    "Admission-controller knob changes, by knob and direction.",
)
_TICKS = _M.counter(
    "admission_controller_ticks_total",
    "Admission-controller evaluation ticks (incl. hold decisions).",
)
_KNOB = _M.gauge(
    "admission_controller_knob",
    "Current controller-actuated knob values, by knob.",
)


class AdmissionControlLoop:
    """Reads the serving telemetry window, actuates the serving flags.

    ``residency_fn`` returns a ResidencyPool.snapshot()-shaped dict
    (used_bytes/pinned_bytes/budget_bytes); ``queue_depth_fn`` the live
    admission queue depth. Both default to the broker's wiring when
    attached via ``QueryBroker.start_admission_controller``."""

    _SCRIPT_ID = "admission-controller"

    def __init__(
        self,
        residency_fn=None,
        queue_depth_fn=None,
        registry=None,
    ):
        self._residency_fn = residency_fn
        self._queue_depth_fn = queue_depth_fn
        reg = registry or metrics_registry()
        self._lock = threading.Lock()
        # Window views over the r15 planes: admitted-query wait
        # quantiles, admissions, hbm_budget rejections.
        self._wait = HistogramWindow("admission_wait_seconds", reg)
        self._admitted = CounterWindow("admission_admitted_total", reg)
        self._hbm_rejects = CounterWindow(
            "admission_rejected_total", reg, reason="hbm_budget"
        )
        self._dispatch_after_ns = time.time_ns()
        # Baselines: the operator-configured flag values at attach time;
        # decay pulls back toward these, and the hbm shrink floor is the
        # configured budget.
        self._base_concurrent = max(int(flags.admission_max_concurrent), 1)
        self._base_hbm_mb = int(flags.hbm_budget_mb)
        self._idle_windows = 0
        self._low_hbm_windows = 0
        # Post-brake hold-down (r17 satellite): windows remaining in
        # which concurrency RAISES are suppressed after an HBM-pressure
        # halving, so the MIMD law observes the brake's effect instead
        # of immediately re-climbing into the same pressure (the
        # 8->128->floor->16 thrash from the 1k-client trail).
        self._holddown = 0
        self.trail: "collections.deque[dict]" = collections.deque(
            maxlen=256
        )
        self._runner = None

    # -- cron riding (the SLOManager pattern) -------------------------------
    def attach(self, broker, datastore=None) -> "AdmissionControlLoop":
        """Persist the controller as a CronScript and start its ticker
        (restart survival rides the datastore like SLO rules)."""
        from pixie_tpu.vizier.cron import (
            CronScript, CronScriptStore, ScriptRunner,
        )
        from pixie_tpu.vizier.datastore import Datastore

        store = CronScriptStore(datastore or Datastore())
        self._runner = ScriptRunner(
            broker, store, executor=lambda _script: self.step()
        )
        self._runner.upsert_script(
            CronScript(
                self._SCRIPT_ID,
                "",
                float(flags.admission_controller_interval_s),
                configs={"admission_controller": True},
            )
        )
        return self

    def stop(self) -> None:
        if self._runner is not None:
            self._runner.stop()
            self._runner = None

    # -- signals -------------------------------------------------------------
    def _device_busy_s(self) -> float:
        """Device wall-seconds dispatched since the last tick, from the
        r15 device_dispatches attribution ring (peeked, not drained —
        the self-telemetry flush stays the single consumer). Rows the
        flush drained before we looked just under-report; the control
        law only uses this as a brake, so under-reporting is safe."""
        from pixie_tpu.parallel import profiler as resattr

        after = self._dispatch_after_ns
        self._dispatch_after_ns = time.time_ns()
        try:
            rows = resattr.dispatches_snapshot()
        except Exception:
            return 0.0
        return sum(
            r["duration_ns"] for r in rows if r["time_ns"] >= after
        ) / 1e9

    def _signals(self) -> dict:
        delta = self._wait.tick()
        admitted = self._admitted.tick()
        snap = {}
        if self._residency_fn is not None:
            try:
                snap = self._residency_fn() or {}
            except Exception:
                snap = {}
        depth = 0
        if self._queue_depth_fn is not None:
            try:
                depth = int(self._queue_depth_fn())
            except Exception:
                depth = 0
        # r22 predictive term: the cost model's expected time-in-queue
        # for the CURRENT backlog at the CURRENT concurrency (learned
        # per-fold median x depth / slots). 0.0 when the model is cold,
        # shadowing, or off — the law below degrades to pure MIMD.
        pred_wait = (
            _cost_model.controller_predicted_wait_ms(
                depth, max(int(flags.admission_max_concurrent), 1)
            )
            if _cost_model.ACTIVE
            else None
        )
        return {
            "admitted": admitted,
            "wait_p50_ms": (
                self._wait.quantile(0.5, delta) * 1e3 if delta else 0.0
            ),
            "wait_p99_ms": (
                self._wait.quantile(0.99, delta) * 1e3 if delta else 0.0
            ),
            "queue_depth": depth,
            "hbm_rejects": self._hbm_rejects.tick(),
            "used_bytes": int(snap.get("used_bytes") or 0),
            "pinned_bytes": int(snap.get("pinned_bytes") or 0),
            "budget_bytes": int(snap.get("budget_bytes") or 0),
            "device_busy_s": self._device_busy_s(),
            "predicted_wait_ms": float(pred_wait or 0.0),
        }

    # -- actuation -----------------------------------------------------------
    def _actuate(self, knob: str, new, reason: str, sig: dict) -> None:
        old = getattr(flags, knob)
        if new == old:
            return
        flags.set(knob, new)
        _ACTUATIONS.inc(
            knob=knob, direction="up" if new > old else "down"
        )
        _KNOB.set(float(new), knob=knob)
        self.trail.append(
            {
                "time_ns": time.time_ns(),
                "knob": knob,
                "from": old,
                "to": new,
                "reason": reason,
                "signals": {
                    k: round(v, 3) if isinstance(v, float) else v
                    for k, v in sig.items()
                },
            }
        )

    def step(self) -> Optional[dict]:
        """One control-law evaluation over the window since the last
        tick. Returns the observed signals (None = flag off). Safe to
        call from tests without any cron machinery."""
        if not flags.admission_controller:
            return None
        with self._lock:
            _TICKS.inc()
            sig = self._signals()
            if sig["admitted"] <= 0 and sig["hbm_rejects"] <= 0 and (
                sig["queue_depth"] == 0
            ):
                # Empty window: no evidence — hold every knob.
                return sig
            self._step_concurrency(sig)
            self._step_window(sig)
            self._step_hbm(sig)
            return sig

    def _hbm_pressure(self, sig: dict) -> bool:
        budget = sig["budget_bytes"]
        return budget > 0 and sig["pinned_bytes"] > 0.9 * budget

    def _hbm_headroom(self, sig: dict) -> bool:
        budget = sig["budget_bytes"]
        return budget <= 0 or sig["used_bytes"] < 0.8 * budget

    def _step_concurrency(self, sig: dict) -> None:
        cur = max(int(flags.admission_max_concurrent), 1)
        floor = max(int(flags.admission_controller_min_concurrent), 1)
        ceil = max(int(flags.admission_controller_max_concurrent), floor)
        target_ms = float(flags.admission_controller_wait_target_ms)
        if self._hbm_pressure(sig):
            # Brake first: admitting more folds into a pool whose
            # pinned bytes crowd the budget converts latency into OOM
            # rejections. Arm the hold-down: no raises until the
            # brake's effect has been observed.
            self._actuate(
                "admission_max_concurrent",
                max(cur // 2, floor),
                "hbm_pressure",
                sig,
            )
            self._idle_windows = 0
            self._holddown = max(
                int(flags.admission_controller_holddown_windows), 0
            )
            return
        reactive = sig["admitted"] > 0 and sig["wait_p50_ms"] > target_ms
        # r22: actuate against PREDICTED fold cost — the model's
        # expected queue-drain time for the live backlog — before the
        # reactive windowed quantile has observed the slow folds. Same
        # rails, same holddown, same brake; with the model cold/off
        # predicted_wait_ms is 0 and this clause never fires.
        predictive = (
            sig.get("predicted_wait_ms", 0.0) > target_ms
            and sig["queue_depth"] > 0
        )
        if (reactive or predictive) and self._hbm_headroom(sig):
            self._idle_windows = 0
            if self._holddown > 0:
                # Post-brake hold-down (r17): the wait signal still
                # reflects the pre-brake queue — re-climbing now is the
                # oscillation. Hold, burn one window, record why.
                self._holddown -= 1
                self.trail.append(
                    {
                        "time_ns": time.time_ns(),
                        "knob": "admission_max_concurrent",
                        "from": cur,
                        "to": cur,
                        "reason": "holddown_after_brake",
                        "signals": {
                            k: round(v, 3) if isinstance(v, float) else v
                            for k, v in sig.items()
                        },
                    }
                )
                return
            self._actuate(
                "admission_max_concurrent",
                min(cur * 2, ceil),
                "wait_p50_over_target" if reactive
                else "predicted_wait_over_target",
                sig,
            )
            return
        if self._holddown > 0:
            # Quiet window: the hold-down still decays — evidence of a
            # calmer system counts toward releasing the brake.
            self._holddown -= 1
        if sig["admitted"] > 0 and sig["queue_depth"] == 0 and (
            sig["wait_p50_ms"] < target_ms / 10.0
        ):
            # Sustained idle: decay one halving step toward the
            # configured baseline (never below it, never below floor).
            self._idle_windows += 1
            if self._idle_windows >= 3 and cur > self._base_concurrent:
                self._actuate(
                    "admission_max_concurrent",
                    max(cur // 2, self._base_concurrent, floor),
                    "idle_decay",
                    sig,
                )
                self._idle_windows = 0
        else:
            self._idle_windows = 0

    def _step_window(self, sig: dict) -> None:
        cur = float(flags.shared_scan_window_ms)
        ceil = max(float(flags.admission_controller_max_window_ms), 0.0)
        step = max(ceil / 10.0, 1.0)
        if sig["queue_depth"] > 0 and cur < ceil:
            self._actuate(
                "shared_scan_window_ms",
                min(round(cur + step, 3), ceil),
                "queue_depth",
                sig,
            )
        elif sig["queue_depth"] == 0 and cur > 0:
            self._actuate(
                "shared_scan_window_ms",
                max(round(cur - step, 3), 0.0),
                "queue_drained",
                sig,
            )

    def _step_hbm(self, sig: dict) -> None:
        cur = int(flags.hbm_budget_mb)
        ceil = int(flags.admission_controller_max_hbm_mb)
        if cur <= 0 or ceil <= 0:
            return  # no budget / no rail: HBM is not ours to move
        if sig["hbm_rejects"] > 0 and cur < ceil:
            self._low_hbm_windows = 0
            self._actuate(
                "hbm_budget_mb",
                min(max(cur + cur // 4, cur + 1), ceil),
                "hbm_budget_rejections",
                sig,
            )
            return
        floor = max(self._base_hbm_mb, 1)
        if sig["budget_bytes"] > 0 and (
            sig["used_bytes"] < 0.3 * sig["budget_bytes"]
        ):
            self._low_hbm_windows += 1
            if self._low_hbm_windows >= 5 and cur > floor:
                self._actuate(
                    "hbm_budget_mb",
                    max(cur - cur // 4, floor),
                    "hbm_underused",
                    sig,
                )
                self._low_hbm_windows = 0
        else:
            self._low_hbm_windows = 0

    # -- status --------------------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            return {
                "enabled": bool(flags.admission_controller),
                "knobs": {
                    "admission_max_concurrent": int(
                        flags.admission_max_concurrent
                    ),
                    "shared_scan_window_ms": float(
                        flags.shared_scan_window_ms
                    ),
                    "hbm_budget_mb": int(flags.hbm_budget_mb),
                },
                "rails": {
                    "min_concurrent": int(
                        flags.admission_controller_min_concurrent
                    ),
                    "max_concurrent": int(
                        flags.admission_controller_max_concurrent
                    ),
                    "max_window_ms": float(
                        flags.admission_controller_max_window_ms
                    ),
                    "max_hbm_mb": int(
                        flags.admission_controller_max_hbm_mb
                    ),
                },
                "baselines": {
                    "admission_max_concurrent": self._base_concurrent,
                    "hbm_budget_mb": self._base_hbm_mb,
                },
                # r17: windows left in the post-brake hold-down (raises
                # suppressed while > 0).
                "holddown_windows_left": self._holddown,
                "actuations": list(self.trail)[-32:],
            }
