"""Residency-aware fleet placement (r18).

Proactive inversion of the r17 failover ranking: instead of choosing a
replacement agent only AFTER a fragment is lost, the broker scores every
live agent for a query's table span AT ADMISSION and routes the scan to
the agent whose HBM already holds the data. Placement and failover share
one scorer (``coverage`` / ``failover_rank`` below), so "which agent can
serve this span, and how warm is it there" has exactly one definition.

The coverage ladder, classified purely from heartbeat-advertised state
(the broker never touches a device):

``ring_hit``
    every needed table is device-resident on the agent — a staged-cache
    entry in its ResidencyPool snapshot or an owned resident-ingest
    ring. Wire bytes for the scan are ~0.
``replica_hit``
    every needed table is covered by an adopted replica ring with at
    least one window: the replicated payload is already decoded in the
    follower's HBM.
``latency_fallback``
    no advertised residency; the agent is ranked by the r11
    per-program-key fold-latency view (lowest mean p50) and load.
``cold``
    no residency and no latency history — weighted-load round robin.

Within a rung, ties break by span affinity (the agent this exact table
span was last placed on, so placement stays stable across the heartbeat
lag between a placement and the residency it creates), then WFQ-weighted
load (per-tenant admission weights scale each placed query's cost, so a
heavy tenant's queries spread across more of the fleet), then mean fold
p50, then agent id.

``RingRebalancer`` makes r17's static leader-rank follower attachment
adaptive: per-table placement heat (the admission-side view of the
``device_dispatches`` telemetry) decides WHICH tables deserve replicas,
heartbeat ResidencyPool snapshots rail WHERE they may land (followers
above ``ring_rebalance_high_pct`` of their HBM budget are skipped), and
every move rides the existing codec'd ring_replica topic as a
``ring_replica_assign`` message plus an actuation-trail entry shaped
like the r16 admission controller's. An empty heat window holds every
assignment — no signal, no actuation.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, FrozenSet, Iterable, List, Optional, Tuple

from pixie_tpu.serving import cost_model as _cost_model
from pixie_tpu.utils import flags, metrics_registry

_M = metrics_registry()
_DECISIONS = _M.counter(
    "broker_placement_decisions_total",
    "Placement decisions by outcome (ring_hit|replica_hit|latency_fallback|cold).",
)
_HIT_RATE = _M.gauge(
    "broker_placement_hit_rate",
    "Fraction of placement decisions that landed on resident or replica HBM.",
)
_REBALANCE_MOVES = _M.counter(
    "broker_ring_rebalance_moves_total",
    "Replica-ring follower reassignments published by the rebalancer.",
)

# Outcome ladder, most preferred first. view_hit (r20) sits ABOVE
# ring_hit: a query answered from a materialized view's merged state
# never reaches admission, so no agent ranking happens at all — the
# broker records it via record_view_hit(), and decide() never returns
# it. latency_fallback and cold share one RANK rung (they are both
# "no residency" — ranked by load then latency then name, so a fresh
# agent isn't starved just because a warmer-history one exists); the
# labels stay distinct for metrics.
OUTCOMES = (
    "view_hit",
    "ring_hit",
    "replica_hit",
    "latency_fallback",
    "cold",
    "mesh_fold",
)
# mesh_fold (r21) is not an agent rung: decide() returns it INSTEAD of a
# pick when the span's estimated staging bytes exceed every eligible
# agent's advertised HBM budget — forcing a single-agent placement would
# only thrash that agent's residency ring, so the broker plans the fold
# across the full fleet (spanning placement). Order 3 is for the
# metrics/ladder listing only; it never competes in the rank tuple.
_OUTCOME_ORDER = {
    "view_hit": -1,
    "ring_hit": 0,
    "replica_hit": 1,
    "latency_fallback": 2,
    "cold": 2,
    "mesh_fold": 3,
}

View = List[Dict[str, Any]]  # AgentTracker.failover_view() entries


def eligible(agent: Dict[str, Any], needed: FrozenSet[str]) -> bool:
    """An agent can serve ``needed`` if it owns or replicates every table."""
    return needed <= (agent["tables"] | agent["replica_tables"])


def coverage(agent: Dict[str, Any], needed: FrozenSet[str]) -> Dict[str, Any]:
    """Score one failover_view entry's coverage of a table span.

    All signals come from the heartbeat-carried health snapshot:
    ``residency.tables`` (staged-cache entries), ``resident_ingest``
    (owned rings), and ``replicas`` (adopted replica rings with
    windows/lag watermarks).
    """
    health = agent.get("health") or {}
    staged = set((health.get("residency") or {}).get("tables") or ())
    rings = set(health.get("resident_ingest") or ())
    reps = health.get("replicas") or {}
    hot = 0
    lag = 0
    replica_all = bool(needed)
    for t in needed:
        r = reps.get(t) or {}
        w = int(r.get("windows", 0) or 0)
        hot += w
        lag += int(r.get("lag", 0) or 0)
        if w <= 0:
            replica_all = False
    return {
        "owned": needed <= agent["tables"],
        "resident": bool(needed) and needed <= (staged | rings),
        "replica": replica_all,
        "hot": hot,
        "lag": lag,
    }


def failover_rank(
    agent: Dict[str, Any], needed: FrozenSet[str], prefer_kelvin: bool
) -> Tuple:
    """The r17 failover rank tuple, verbatim: role match, then ownership,
    then replica warmth (more windows better), then lag, then name."""
    cov = coverage(agent, needed)
    return (
        0 if bool(agent["is_kelvin"]) == prefer_kelvin else 1,
        0 if cov["owned"] else 1,
        -cov["hot"],
        cov["lag"],
        agent["agent_id"],
    )


def best_failover_candidate(
    view: View,
    needed: FrozenSet[str],
    skip: Iterable[str],
    prefer_kelvin: bool,
) -> Optional[str]:
    """r17 failover candidate selection on the shared scorer."""
    skip = set(skip)
    best: Optional[Tuple[Tuple, str]] = None
    for a in view:
        if a["agent_id"] in skip or not eligible(a, needed):
            continue
        rank = failover_rank(a, needed, prefer_kelvin)
        if best is None or rank < best[0]:
            best = (rank, a["agent_id"])
    return best[1] if best else None


def classify(cov: Dict[str, Any]) -> Optional[str]:
    """Coverage dict -> outcome rung, or None when residency says nothing
    (the caller decides latency_fallback vs cold from the latency view)."""
    if cov["resident"]:
        return "ring_hit"
    if cov["replica"]:
        return "replica_hit"
    return None


def agent_latency(fold_latency_view: Optional[Dict[str, Dict]]) -> Dict[str, float]:
    """Collapse the r11 per-program-key view to agent -> mean p50 ms."""
    sums: Dict[str, List[float]] = {}
    for per_agent in (fold_latency_view or {}).values():
        for aid, stats in per_agent.items():
            p50 = stats.get("p50_ms")
            if not p50:
                continue
            acc = sums.setdefault(aid, [0.0, 0.0])
            acc[0] += float(p50)
            acc[1] += 1.0
    return {aid: acc[0] / acc[1] for aid, acc in sums.items() if acc[1]}


class PlacementPlane:
    """Admission-time placement state: decision counters, span affinity,
    WFQ-weighted load, inflight occupancy, and per-table query heat.

    ``decide`` is pure — it ranks but records nothing — so a placed plan
    that fails (ValueError from the planner) can fall back to the normal
    path without polluting metrics. The broker calls ``commit`` once the
    placed plan succeeds and ``release`` in its finally block.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._outcomes: collections.Counter = collections.Counter()
        self._placed: collections.Counter = collections.Counter()
        self._load: Dict[str, float] = collections.defaultdict(float)
        self._inflight: collections.Counter = collections.Counter()
        self._affinity: Dict[FrozenSet[str], str] = {}
        self._heat: collections.Counter = collections.Counter()
        self._heat_total: collections.Counter = collections.Counter()

    # -- routing ----------------------------------------------------------

    def decide(
        self,
        view: View,
        needed: FrozenSet[str],
        fold_latency: Optional[Dict[str, Dict]] = None,
        estimated_bytes: int = 0,
    ) -> Tuple[Optional[str], Optional[str]]:
        """Rank eligible data-plane agents for ``needed``.

        Returns (agent_id, outcome), (None, "mesh_fold") when the span
        is too big for any single agent's HBM (see _OUTCOME_ORDER), or
        (None, None) when no live non-kelvin agent covers the span.
        """
        if not needed:
            return None, None
        # r21 mesh_fold rung: with a staging estimate in hand, refuse a
        # single-agent pick when the span exceeds EVERY eligible
        # agent's advertised HBM budget (heartbeat residency snapshot).
        # An agent without an advertised budget is unknown — assume it
        # fits, keeping the rung conservative.
        if estimated_bytes > 0 and flags.mesh_fold_placement:
            any_eligible = False
            fits_somewhere = False
            for a in view:
                if a["is_kelvin"] or not eligible(a, needed):
                    continue
                any_eligible = True
                res = (a.get("health") or {}).get("residency") or {}
                budget = int(res.get("budget_bytes") or 0)
                if budget <= 0 or estimated_bytes <= budget:
                    fits_somewhere = True
                    break
            if any_eligible and not fits_somewhere:
                return None, "mesh_fold"
        lat = agent_latency(fold_latency)
        # r22: agents the latency view has never measured used to rank
        # ``cold`` (below latency_fallback). With a warmed cost model
        # the predicted per-fold latency stands in, so a known-cost
        # workload ranks unmeasured agents on the latency rung — same
        # answers (placement only routes), just better-ordered agents.
        # Cold, shadow, or disabled: pred_ms is None and the ladder is
        # exactly r18's.
        pred_ms = None
        if _cost_model.ACTIVE and not _cost_model.SHADOW:
            pred_ms = _cost_model.placement_latency_ms()
        best: Optional[Tuple[Tuple, str, str]] = None
        with self._lock:
            aff = self._affinity.get(needed)
            inflight = dict(self._inflight)
            load = dict(self._load)
        for a in view:
            if a["is_kelvin"] or not eligible(a, needed):
                continue
            aid = a["agent_id"]
            outcome = classify(coverage(a, needed))
            if outcome is None:
                known = aid in lat or pred_ms is not None
                outcome = "latency_fallback" if known else "cold"
            rank = (
                _OUTCOME_ORDER[outcome],
                0 if aid == aff else 1,
                inflight.get(aid, 0) + load.get(aid, 0.0),
                lat.get(aid, pred_ms if pred_ms is not None else 0.0),
                aid,
            )
            if best is None or rank < best[0]:
                best = (rank, aid, outcome)
        if best is None:
            return None, None
        return best[1], best[2]

    def commit(
        self,
        agent_id: str,
        outcome: str,
        needed: FrozenSet[str],
        weight: float = 1.0,
    ) -> None:
        """Record a routed decision: counters, hit gauge, span affinity,
        WFQ-weighted load, per-table heat, and inflight occupancy."""
        _DECISIONS.inc(outcome=outcome)
        with self._lock:
            self._outcomes[outcome] += 1
            self._placed[agent_id] += 1
            self._load[agent_id] += 1.0 / max(float(weight), 1e-6)
            self._inflight[agent_id] += 1
            self._affinity[needed] = agent_id
            if len(self._affinity) > 4096:
                self._affinity.pop(next(iter(self._affinity)))
            for t in needed:
                self._heat[t] += 1
                self._heat_total[t] += 1
            total = sum(self._outcomes.values())
            hits = (
                self._outcomes["view_hit"]
                + self._outcomes["ring_hit"]
                + self._outcomes["replica_hit"]
            )
        _HIT_RATE.set(hits / total if total else 0.0)

    def record_view_hit(self) -> None:
        """r20: a query served from a materialized view before admission.
        Top rung of the ladder — counts as a hit (the whole point is
        that NO agent had to fold), no agent load/affinity to record."""
        _DECISIONS.inc(outcome="view_hit")
        with self._lock:
            self._outcomes["view_hit"] += 1
            total = sum(self._outcomes.values())
            hits = (
                self._outcomes["view_hit"]
                + self._outcomes["ring_hit"]
                + self._outcomes["replica_hit"]
            )
        _HIT_RATE.set(hits / total if total else 0.0)

    def route_view_tail(
        self,
        agent_id: str,
        needed: FrozenSet[str],
        weight: float = 1.0,
    ) -> None:
        """r21: a view hit's unflushed-tail delta fold, routed to the
        view's maintain agent (the tracker pick recorded at
        registration). Attribution only — not an admission decision,
        so the outcome/hit-rate counters are untouched; the agent's
        WFQ load, inflight occupancy, and table heat do move so the
        rebalancer and the ladder see the tail work where it runs.
        Pair with ``release(agent_id)`` when the fold completes."""
        with self._lock:
            self._placed[agent_id] += 1
            self._load[agent_id] += 1.0 / max(float(weight), 1e-6)
            self._inflight[agent_id] += 1
            for t in needed:
                self._heat[t] += 1
                self._heat_total[t] += 1

    def release(self, agent_id: str) -> None:
        with self._lock:
            if self._inflight[agent_id] > 0:
                self._inflight[agent_id] -= 1

    # -- rebalancer feed --------------------------------------------------

    def drain_heat(self) -> Dict[str, int]:
        """Per-table placement counts since the last drain — the
        rebalancer's query-heat window."""
        with self._lock:
            heat = {t: int(c) for t, c in self._heat.items() if c}
            self._heat.clear()
        return heat

    # -- observability ----------------------------------------------------

    def status(self) -> Dict[str, Any]:
        with self._lock:
            outcomes = dict(self._outcomes)
            placed = dict(self._placed)
            per_agent = {
                aid: {
                    "placed": int(placed.get(aid, 0)),
                    "load": round(self._load.get(aid, 0.0), 3),
                    "inflight": int(self._inflight.get(aid, 0)),
                }
                for aid in sorted(set(placed) | set(self._load) | set(self._inflight))
            }
            heat = dict(self._heat_total)
            affinity_spans = len(self._affinity)
        total = sum(outcomes.values())
        hits = (
            outcomes.get("view_hit", 0)
            + outcomes.get("ring_hit", 0)
            + outcomes.get("replica_hit", 0)
        )
        shares = [c for c in placed.values() if c > 0]
        return {
            "decisions": {o: int(outcomes.get(o, 0)) for o in OUTCOMES},
            "total": int(total),
            "hit_rate": round(hits / total, 4) if total else None,
            "per_agent": per_agent,
            "balance_max_min": (
                round(max(shares) / min(shares), 3) if shares else None
            ),
            "affinity_spans": affinity_spans,
            "table_heat": heat,
        }


class RingRebalancer:
    """Adaptive replica-ring follower assignment (r18).

    Each ``tick`` drains the placement plane's per-table heat window and,
    for every hot table, picks up to ``ring_replication_factor - 1``
    followers among live non-kelvin agents that advertise the table as
    replica-capable WITHOUT owning it, skipping any follower whose
    heartbeat ResidencyPool reports usage above ``ring_rebalance_high_pct``
    of its HBM budget. Changed assignments are published on the codec'd
    ring_replica topic (``ring_replica_assign``) and appended to a
    bounded actuation trail; unchanged assignments publish nothing. An
    empty heat window is a hold: no actuation at all.
    """

    def __init__(
        self,
        publish: Callable[[Dict[str, Any]], None],
        view_fn: Callable[[], View],
        heat_fn: Callable[[], Dict[str, int]],
    ) -> None:
        self._publish = publish
        self._view_fn = view_fn
        self._heat_fn = heat_fn
        self._lock = threading.Lock()
        self._assignments: Dict[str, Tuple[str, ...]] = {}
        self._seq = 0
        self.trail: collections.deque = collections.deque(maxlen=256)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- policy -----------------------------------------------------------

    @staticmethod
    def _headroom_ok(agent: Dict[str, Any], high_pct: float) -> bool:
        res = (agent.get("health") or {}).get("residency") or {}
        budget = int(res.get("budget_bytes") or 0)
        if budget <= 0:
            return True  # unlimited pool: no rail to exceed
        return int(res.get("used_bytes") or 0) < high_pct * budget

    def tick(self) -> List[Dict[str, Any]]:
        """One rebalance pass. Returns the actuations applied (empty
        list = hold). Callable directly from tests; the background
        thread just calls this on an interval."""
        cap = max(int(flags.ring_replication_factor) - 1, 0)
        if cap <= 0:
            return []
        heat = {t: int(c) for t, c in (self._heat_fn() or {}).items() if c > 0}
        if not heat:
            return []  # empty window: hold every assignment
        view = self._view_fn()
        high_pct = float(flags.ring_rebalance_high_pct)
        moves: List[Dict[str, Any]] = []
        assigned_this_tick: collections.Counter = collections.Counter()
        with self._lock:
            # Hottest tables claim follower headroom first.
            for table in sorted(heat, key=lambda t: (-heat[t], t)):
                cands = []
                for a in view:
                    if a["is_kelvin"] or table in a["tables"]:
                        continue  # leaders replicate out, not in
                    if table not in a["replica_tables"]:
                        continue
                    if not self._headroom_ok(a, high_pct):
                        continue
                    res = (a.get("health") or {}).get("residency") or {}
                    cands.append(
                        (
                            assigned_this_tick[a["agent_id"]],
                            int(res.get("used_bytes") or 0),
                            a["agent_id"],
                        )
                    )
                cands.sort()
                followers = tuple(aid for _, _, aid in cands[:cap])
                old = self._assignments.get(table)
                if followers == old or (not followers and old is None):
                    for aid in followers:
                        assigned_this_tick[aid] += 1
                    continue
                self._seq += 1
                self._assignments[table] = followers
                for aid in followers:
                    assigned_this_tick[aid] += 1
                try:
                    self._publish(
                        {
                            "type": "ring_replica_assign",
                            "table": table,
                            "followers": list(followers),
                            "seq": self._seq,
                        }
                    )
                except Exception:
                    pass  # bus teardown race; assignment re-publishes next change
                entry = {
                    "time_ns": time.time_ns(),
                    "knob": f"replica_assign:{table}",
                    "from": list(old) if old is not None else None,
                    "to": list(followers),
                    "reason": "hbm_pressure" if old and not followers else "query_heat",
                    "signals": {"heat": heat[table], "candidates": len(cands)},
                }
                self.trail.append(entry)
                _REBALANCE_MOVES.inc()
                moves.append(entry)
        return moves

    # -- lifecycle --------------------------------------------------------

    def start(self, interval_s: Optional[float] = None) -> None:
        if self._thread is not None:
            return
        period = float(
            interval_s if interval_s is not None else flags.ring_rebalance_interval_s
        )

        def loop() -> None:
            while not self._stop.wait(period):
                try:
                    self.tick()
                except Exception:
                    pass  # a bad snapshot shouldn't kill the loop

        self._thread = threading.Thread(
            target=loop, name="ring-rebalancer", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "assignments": {
                    t: list(f) for t, f in sorted(self._assignments.items())
                },
                "rails": {
                    "replication_factor": int(flags.ring_replication_factor),
                    "high_pct": float(flags.ring_rebalance_high_pct),
                },
                "actuations": list(self.trail)[-32:],
            }
