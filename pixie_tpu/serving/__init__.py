"""Multi-query serving engine (r12, widened r16).

The single-chip hot path (r5-r8) and control plane (r9-r11) assume one
query owns the chip; the reference's query-broker + script-runner model
(SURVEY.md §vizier) assumes hundreds of concurrent PxL scripts hitting
the SAME hot tables. This package is the layer between them:

- ``residency``: the HBM staged-table pool — per-entry byte accounting,
  query-scoped pinning, LRU eviction with high/low watermarks against
  ``hbm_budget_mb``. Replaces the entry-count OrderedDict the
  MeshExecutor carried since r4.
- ``shared_scan``: concurrent compatible queries coalesce into ONE
  device fold dispatch on a two-rung ladder (shared-scan engines:
  Crescando/SharedDB): identical fold signatures share the leader's
  merged states (r12); predicate-COMPATIBLE queries (r16) batch into a
  single scan whose per-query predicate mask lanes stack partial-agg
  states on a slot axis — finalize fans out per query either way,
  bit-identical to serial.
- ``admission``: broker-side admission control — concurrency limit,
  per-tenant weighted fair queueing, HBM byte-budget check, structured
  ``AdmissionRejected`` on overload (never a hang).
- ``controller``: the r16 closed-loop half — an SLO-window adapter on
  the cron runner that reads admission-wait quantiles, queue depth,
  device-dispatch wall time, and HBM residency, and actuates
  ``admission_max_concurrent`` / ``shared_scan_window_ms`` /
  ``hbm_budget_mb`` within guard rails.
- ``signatures``: datastore-backed persistence of observed fold shapes
  so ``prewarm_compile`` replays real query shapes across restarts
  instead of guessing the canonical count+sum(f64) shape.
"""

from pixie_tpu.serving.admission import AdmissionController, AdmissionRejected
from pixie_tpu.serving.controller import AdmissionControlLoop
from pixie_tpu.serving.residency import ResidencyPool, staged_nbytes
from pixie_tpu.serving.shared_scan import SharedScanCoordinator
from pixie_tpu.serving.signatures import FoldSignatureStore

__all__ = [
    "AdmissionController",
    "AdmissionControlLoop",
    "AdmissionRejected",
    "FoldSignatureStore",
    "ResidencyPool",
    "SharedScanCoordinator",
    "staged_nbytes",
]
