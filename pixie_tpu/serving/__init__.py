"""Multi-query serving engine (r12).

The single-chip hot path (r5-r8) and control plane (r9-r11) assume one
query owns the chip; the reference's query-broker + script-runner model
(SURVEY.md §vizier) assumes hundreds of concurrent PxL scripts hitting
the SAME hot tables. This package is the layer between them:

- ``residency``: the HBM staged-table pool — per-entry byte accounting,
  query-scoped pinning, LRU eviction with high/low watermarks against
  ``hbm_budget_mb``. Replaces the entry-count OrderedDict the
  MeshExecutor carried since r4.
- ``shared_scan``: concurrent queries whose fold signatures match (the
  r7 decomposed init/fold/merge/finalize units make compatibility a
  string compare) coalesce into ONE device fold dispatch; finalize fans
  out per query (shared-scan engines: Crescando/SharedDB).
- ``admission``: broker-side admission control — concurrency limit,
  per-tenant weighted fair queueing, HBM byte-budget check, structured
  ``AdmissionRejected`` on overload (never a hang).
- ``signatures``: datastore-backed persistence of observed fold shapes
  so ``prewarm_compile`` replays real query shapes across restarts
  instead of guessing the canonical count+sum(f64) shape.
"""

from pixie_tpu.serving.admission import AdmissionController, AdmissionRejected
from pixie_tpu.serving.residency import ResidencyPool, staged_nbytes
from pixie_tpu.serving.shared_scan import SharedScanCoordinator
from pixie_tpu.serving.signatures import FoldSignatureStore

__all__ = [
    "AdmissionController",
    "AdmissionRejected",
    "FoldSignatureStore",
    "ResidencyPool",
    "SharedScanCoordinator",
    "staged_nbytes",
]
