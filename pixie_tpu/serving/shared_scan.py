"""Shared scans: concurrent compatible queries share one fold dispatch.

Ref posture: shared-scan engines (Crescando, SharedDB) batch concurrent
queries over the same hot table into one scan whose per-query predicates
evaluate inline. The unit of sharing here is the r7 program
decomposition: every device aggregation splits into init/fold/merge/
finalize units, with the FOLD signature excluding output names and
finalize modes — so queries that differ only there already share one
compiled fold EXECUTABLE. This module makes them share fold EXECUTIONS,
on a two-rung compatibility ladder:

1. **Identical signature** (r12): the first arrival (the leader)
   dispatches; queries whose EXACT key matches — staged-entry identity +
   fold signature (incl. predicates) + agg stage + aux-value digest —
   attach while the dispatch is in flight (plus the optional
   pre-dispatch window, ``shared_scan_window_ms``) and reuse the
   leader's merged UDA states. Finalize fans out per query.
2. **Predicate-compatible** (r16, flag
   ``shared_scan_predicate_batching``): queries that match on
   everything EXCEPT their predicates — and whose predicates normalize
   to data-driven comparison terms (pipeline._normalize_predicates) —
   assemble into one BATCHED dispatch: the leader's
   ``compute_batch(slot_terms)`` runs a single scan of the staged
   blocks with one masked partial-agg state lane per distinct
   predicate set, and every participant receives its own slot's merged
   states. Effective concurrency scales with batch width instead of
   the admission concurrency limit.

Both rungs are bit-identical to serial execution — followers consume
exactly the arrays a serial run of their query would have produced.

The batching window is demand-gated (r16 satellite): a leader only
sleeps ``shared_scan_window_ms`` when the admission queue has depth
(``set_queue_depth_fn``, wired by the broker) — a solo query on an idle
engine no longer pays the window tax, and the closed-loop admission
controller (serving/controller.py) drives the window length from
telemetry otherwise.

Observability: each participating query records a ``serving.shared_scan``
trace span carrying ``shared_scan_batch_size`` and its role, and the
shared /metrics registry counts dispatches vs saved dispatches plus the
per-dispatch BATCH WIDTH histogram (distinct predicate slots served by
one scan — the r16 headline serving metric).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from pixie_tpu.utils import flags, metrics_registry, trace

_M = metrics_registry()
_DISPATCHES = _M.counter(
    "serving_shared_scan_dispatches_total",
    "Device fold dispatches issued through the shared-scan coordinator.",
)
_SAVED = _M.counter(
    "serving_shared_scan_saved_dispatches_total",
    "Device fold dispatches avoided by joining another query's in-flight "
    "(or batching-window) shared scan.",
)
_BATCH_SIZE = _M.histogram(
    "serving_shared_scan_batch_size",
    "Queries served per shared-scan dispatch.",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 64),
)
_BATCH_WIDTH = _M.histogram(
    "serving_shared_scan_batch_width",
    "Distinct predicate slots served per shared-scan dispatch (r16: >1 "
    "means predicate-compatible queries shared one batched scan).",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 64),
)
_PRED_BATCHED = _M.counter(
    "serving_shared_scan_predicate_batched_queries_total",
    "Queries served from a predicate-batched (width > 1) dispatch.",
)
_WINDOW_SKIPS = _M.counter(
    "serving_shared_scan_window_skips_total",
    "Batching windows skipped because the admission queue was empty "
    "(the r16 solo-query window-tax fix).",
)
_DETACHED = _M.counter(
    "serving_shared_scan_follower_detach_total",
    "Followers that detached from a batch whose leader died mid-"
    "dispatch and completed SOLO (r17): the leader's failure is not "
    "contagious — each follower re-runs its own compute, bit-identical "
    "to never having joined.",
)

# Admission-queue depth gate for the batching window. None = unknown
# (no broker/admission wired): keep the pre-r16 always-sleep behavior
# so standalone engines batch deterministically under a window.
_QUEUE_DEPTH_FN: Optional[Callable[[], int]] = None


def set_queue_depth_fn(fn: Optional[Callable[[], int]]) -> None:
    global _QUEUE_DEPTH_FN
    _QUEUE_DEPTH_FN = fn


def clear_queue_depth_fn(fn: Optional[Callable[[], int]] = None) -> None:
    """Unset the gate — only if ``fn`` still owns it (a stopped broker
    must not yank a newer broker's wiring)."""
    global _QUEUE_DEPTH_FN
    if fn is None or _QUEUE_DEPTH_FN is fn:
        _QUEUE_DEPTH_FN = None


def _queue_depth() -> int:
    """Live admission queue depth, or -1 when unknown."""
    fn = _QUEUE_DEPTH_FN
    if fn is None:
        return -1
    try:
        return int(fn())
    except Exception:
        return -1


def aux_digest(aux_vals) -> str:
    """Content digest of the replicated aux arguments (key LUTs,
    int-dict LUTs, constants): aux SHAPES are in the fold signature but
    two queries with equal shapes and different values must not share a
    dispatch."""
    h = hashlib.sha1()
    for v in aux_vals:
        a = np.asarray(v)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


class _Batch:
    """One in-flight dispatch: a list of slots (distinct exact keys,
    each with its normalized predicate terms) plus everyone waiting on
    the published per-slot results."""

    __slots__ = (
        "event", "results", "error", "slots", "terms", "joiners",
        "closed", "published", "batch_key",
    )

    def __init__(self, batch_key=None):
        self.event = threading.Event()
        self.results: "list | None" = None
        self.error: "BaseException | None" = None
        self.slots: dict[Any, int] = {}  # exact key -> slot index
        self.terms: list = []  # per-slot predicate terms (None = opaque)
        self.joiners = 0
        self.closed = False  # slot set frozen: the leader is dispatching
        self.published = False  # results visible; late arrivals start fresh
        self.batch_key = batch_key


class SharedScanCoordinator:
    """Coalesces compatible compute() calls into shared executions.

    ``run(key, compute)`` — the first caller for a key becomes the
    leader: it (optionally) waits the batching window, executes,
    publishes, and wakes the batch. Callers arriving before publication
    join and return the leader's result without dispatching. With the
    r16 ladder (``batch_key``/``terms``/``compute_batch``), callers
    whose exact keys differ but whose batch keys match join the same
    dispatch as separate SLOTS — the leader then runs ONE
    ``compute_batch(slot_terms)`` returning a result per slot. A leader
    error makes every follower DETACH and complete solo (r17: a killed
    leader must not take its batch down with it); a failure the
    follower would hit too simply re-raises from its solo run and
    rides the r9 breaker."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_exact: dict[Any, _Batch] = {}
        self._by_batch: dict[Any, _Batch] = {}

    def run(
        self,
        key,
        compute: Callable[[], Any],
        batch_key=None,
        terms=None,
        compute_batch: Optional[Callable[[list], list]] = None,
    ):
        batchable = (
            batch_key is not None
            and terms is not None
            and compute_batch is not None
        )
        max_width = max(int(flags.shared_scan_max_batch), 1)
        with self._lock:
            b = self._by_exact.get(key)
            if b is not None and not b.published:
                # Rung 1: identical signature — share the slot (works
                # even after close: the slot's result is determined).
                b.joiners += 1
                slot = b.slots[key]
                leader = False
            else:
                g = self._by_batch.get(batch_key) if batchable else None
                if (
                    g is not None
                    and not g.closed
                    and not g.published
                    and len(g.terms) < max_width
                ):
                    # Rung 2: predicate-compatible — a new slot in an
                    # open batch.
                    slot = len(g.terms)
                    g.slots[key] = slot
                    g.terms.append(terms)
                    g.joiners += 1
                    self._by_exact[key] = g
                    b = g
                    leader = False
                else:
                    b = _Batch(batch_key if batchable else None)
                    b.joiners = 1
                    b.slots[key] = 0
                    b.terms.append(terms)
                    slot = 0
                    self._by_exact[key] = b
                    if batchable:
                        self._by_batch[batch_key] = b
                    leader = True
        if not leader:
            b.event.wait()
            _SAVED.inc()
            with self._lock:
                size = b.joiners
                width = len(b.terms)
            if width > 1:
                _PRED_BATCHED.inc()
            self._span(size, width, role="follower")
            if b.error is not None:
                # Leader died mid-batch (r17): detach and complete SOLO
                # — the follower re-runs ITS OWN compute, bit-identical
                # to never having joined the batch. A failure that
                # would hit the follower too (a sick device) re-raises
                # from the solo run and rides the r9 breaker as usual.
                _DETACHED.inc()
                self._span(1, 1, role="detached")
                return compute()
            return b.results[slot]
        # Leader: batching window (demand-gated, r16), then dispatch.
        window_s = float(flags.shared_scan_window_ms) / 1e3
        if window_s > 0:
            if _queue_depth() == 0:
                _WINDOW_SKIPS.inc()
            else:
                time.sleep(window_s)
        with self._lock:
            b.closed = True
            slot_terms = list(b.terms)
        try:
            if len(slot_terms) == 1:
                result_list = [compute()]
            else:
                result_list = compute_batch(slot_terms)
            err = None
        except BaseException as e:  # propagate to every joiner
            result_list, err = None, e
        with self._lock:
            b.results = result_list
            b.error = err
            b.published = True
            for k2 in b.slots:
                if self._by_exact.get(k2) is b:
                    del self._by_exact[k2]
            if b.batch_key is not None and (
                self._by_batch.get(b.batch_key) is b
            ):
                del self._by_batch[b.batch_key]
            size = b.joiners
            width = len(slot_terms)
        b.event.set()
        _DISPATCHES.inc()
        _BATCH_SIZE.observe(size)
        _BATCH_WIDTH.observe(width)
        if width > 1:
            _PRED_BATCHED.inc()
        self._span(size, width, role="leader")
        if err is not None:
            raise err
        return b.results[0]

    @staticmethod
    def _span(batch_size: int, width: int, role: str) -> None:
        if trace.ACTIVE:
            trace.record(
                "serving.shared_scan",
                0,
                attrs={
                    "shared_scan_batch_size": batch_size,
                    "shared_scan_batch_width": width,
                    "role": role,
                },
            )
