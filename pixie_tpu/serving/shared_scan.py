"""Shared scans: concurrent compatible queries share one fold dispatch.

Ref posture: shared-scan engines (Crescando, SharedDB) batch concurrent
queries over the same hot table into one scan whose per-query predicates
evaluate inline. Here the unit of sharing is even cleaner: the r7
program decomposition split every device aggregation into
init/fold/merge/finalize units, with the FOLD signature excluding output
names and finalize modes — so two queries that differ only in what they
call their outputs, or how they finalize (FULL vs PARTIAL, a different
quantile over the same sketch lane), already share one compiled fold
EXECUTABLE. This module makes them share one fold EXECUTION: the first
arrival (the leader) dispatches; compatible queries arriving while the
dispatch is in flight (plus an optional pre-dispatch batching window,
``shared_scan_window_ms``) attach to it and reuse the leader's merged
UDA states. Finalize fans out per query, so results are bit-identical
to serial execution — followers consume the exact arrays the leader's
dispatch produced.

Compatibility is a KEY equality, not a heuristic: the key is the staged
cache identity (table, version, column set, window, key plan, geometry)
+ the fold signature (predicates, UDA lanes, key mode, aux shapes) + a
digest of the replicated aux VALUES (two LUTs with equal shapes but
different contents must not share). Anything that could change the
merged states is in the key.

Observability: each participating query records a ``serving.shared_scan``
trace span carrying ``shared_scan_batch_size`` and its role, and the
shared /metrics registry counts dispatches vs saved dispatches so the
≥2x dispatch-reduction acceptance bar is measurable.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Callable

import numpy as np

from pixie_tpu.utils import flags, metrics_registry, trace

_M = metrics_registry()
_DISPATCHES = _M.counter(
    "serving_shared_scan_dispatches_total",
    "Device fold dispatches issued through the shared-scan coordinator.",
)
_SAVED = _M.counter(
    "serving_shared_scan_saved_dispatches_total",
    "Device fold dispatches avoided by joining another query's in-flight "
    "(or batching-window) shared scan.",
)
_BATCH_SIZE = _M.histogram(
    "serving_shared_scan_batch_size",
    "Queries served per shared-scan dispatch.",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 64),
)


def aux_digest(aux_vals) -> str:
    """Content digest of the replicated aux arguments (key LUTs,
    int-dict LUTs, constants): aux SHAPES are in the fold signature but
    two queries with equal shapes and different values must not share a
    dispatch."""
    h = hashlib.sha1()
    for v in aux_vals:
        a = np.asarray(v)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


class _Batch:
    __slots__ = ("event", "result", "error", "joiners", "closed")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error: "BaseException | None" = None
        self.joiners = 1  # the leader
        self.closed = False  # result published; late arrivals start fresh


class SharedScanCoordinator:
    """Coalesces identical-key compute() calls into one execution.

    ``run(key, compute)`` — the first caller for a key becomes the
    leader: it (optionally) waits the batching window, executes
    ``compute()``, publishes the result, and wakes the batch. Callers
    arriving before publication join the batch and return the leader's
    result without dispatching. A leader error propagates to every
    joiner (each would have hit the same error; retrying it N times
    against a failing device would just churn the breaker)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: dict[Any, _Batch] = {}

    def run(self, key, compute: Callable[[], Any]):
        with self._lock:
            batch = self._inflight.get(key)
            if batch is not None and not batch.closed:
                batch.joiners += 1
                leader = False
            else:
                batch = self._inflight[key] = _Batch()
                leader = True
        if leader:
            window_s = float(flags.shared_scan_window_ms) / 1e3
            if window_s > 0:
                time.sleep(window_s)
            try:
                result = compute()
                err = None
            except BaseException as e:  # propagate to every joiner
                result, err = None, e
            with self._lock:
                batch.result = result
                batch.error = err
                batch.closed = True
                if self._inflight.get(key) is batch:
                    del self._inflight[key]
                size = batch.joiners
            batch.event.set()
            _DISPATCHES.inc()
            _BATCH_SIZE.observe(size)
            self._span(size, role="leader")
            if err is not None:
                raise err
            return result
        batch.event.wait()
        _SAVED.inc()
        with self._lock:
            size = batch.joiners
        self._span(size, role="follower")
        if batch.error is not None:
            raise batch.error
        return batch.result

    @staticmethod
    def _span(batch_size: int, role: str) -> None:
        if trace.ACTIVE:
            trace.record(
                "serving.shared_scan",
                0,
                attrs={"shared_scan_batch_size": batch_size, "role": role},
            )
