"""Device-resident incremental ingest: HBM ring tables fed by appends.

The r13 production posture: telemetry is continuous and queries are
repeated, so a hot table should NEVER cold-stage its recent span — the
ingest loop pays the wire incrementally (compressed, off any query's
critical path) and a query finds the last N windows already in HBM,
staging only the cold tail. Crescando/SharedDB's continuously-resident
operational data, on a TPU.

Mechanics (reusing the r6 windowed layout end to end):

- A ``ResidentRing`` attaches to a Table's append listener. Appends
  buffer host-side until a full **ring window** (``resident_window_rows``
  rows, geometry from ``staging.block_geometry`` — exactly the stream
  plan's) is available, which is then packed in RAW column dtypes,
  codec-encoded (``staging_codec``), transferred, and device-decoded
  into [D, nblk, B] blocks that stay resident.
- Queries over the table stream at the ring's window size, so plan
  window w covers the same absolute rows as ring window
  ``(min_row + w·W) / W``. On a hit the pipeline skips pack+transfer
  entirely and runs a jitted raw→plan CONVERT (ops/codec.py:
  narrow/f32/int-dict computed on device) — bit-identical to the host
  pack, zero wire bytes. Misses (partial tail, pre-ring history,
  post-expiry misalignment) take the normal compressed staging path.
- Ring windows are registered with the ResidencyPool as permanently
  pinned bytes (``register_resident``), so /statusz, the byte
  watermark, and admission headroom all see them; the ring's own depth
  bound (``resident_max_windows``) rolls the oldest window out and
  frees its accounting — the device-side analogue of the table store's
  ring-buffer expiry.

Correctness stance: the ring only ever serves FULL windows whose rows it
observed gap-free in row-id order (a skipped row id — e.g. a listener
attached mid-write race — permanently invalidates the ring, never the
query). Everything else falls back to staging from the host columns.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from pixie_tpu.types import DataType
from pixie_tpu.utils import flags, metrics_registry

_M = metrics_registry()
_WINDOWS = _M.counter(
    "resident_ingest_windows_total",
    "Ring windows staged to HBM by the resident-ingest path.",
)
_WIRE = _M.counter(
    "resident_ingest_wire_bytes_total",
    "Bytes the resident-ingest path actually transferred (encoded).",
)
_HITS = _M.counter(
    "resident_window_hits_total",
    "Query stream windows served from HBM-resident ring windows "
    "(pack+transfer skipped).",
)
_INVALID = _M.counter(
    "resident_ring_invalidated_total",
    "Rings permanently invalidated (row-id gap or column mismatch).",
)

# Raw host dtypes the ring can hold, per column DataType (strings ride
# as their table-dictionary int32 codes, matching read_columns).
_RAW_DTYPES = {
    DataType.BOOLEAN: np.dtype(np.bool_),
    DataType.INT64: np.dtype(np.int64),
    DataType.FLOAT64: np.dtype(np.float64),
    DataType.STRING: np.dtype(np.int32),
    DataType.TIME64NS: np.dtype(np.int64),
}


class ResidentWindow:
    __slots__ = ("index", "start_row", "rows", "blocks", "nbytes")

    def __init__(self, index, start_row, rows, blocks, nbytes):
        self.index = index
        self.start_row = start_row
        self.rows = rows
        self.blocks = blocks  # col -> [D, nblk, B] raw-dtype device array
        self.nbytes = nbytes


class ResidentRing:
    """Per-table HBM ring of full append windows in raw column dtypes."""

    def __init__(self, mesh, table, block_rows: int, pool=None):
        from pixie_tpu.parallel.staging import block_geometry

        self.mesh = mesh
        self.table_name = table.name
        self.window_rows = int(flags.resident_window_rows)
        self.d = mesh.devices.size
        self.b, self.nblk = block_geometry(
            self.window_rows, self.d, block_rows
        )
        self._pool = pool
        self._lock = threading.Lock()
        self.columns: dict[str, np.dtype] = {}
        for c in table.relation:
            dt = _RAW_DTYPES.get(c.data_type)
            if dt is not None:
                self.columns[c.name] = dt
        self.windows: dict[int, ResidentWindow] = {}
        self._valid = bool(self.columns)
        # Buffered host rows cover [_buf_start, _next_row).
        self._next_row = table.end_row_id()
        self._buf_start = self._next_row
        self._buf: dict[str, list] = {n: [] for n in self.columns}

    # -- write side (table append listener) ----------------------------------
    def on_append(self, first_row_id: int, batch) -> None:
        from pixie_tpu.table.column import DictColumn

        with self._lock:
            if not self._valid:
                return
            if first_row_id != self._next_row:
                self._invalidate_locked()
                return
            if batch.num_rows == 0:
                return
            for name, dt in self.columns.items():
                c = batch.col(name)
                arr = c.codes if isinstance(c, DictColumn) else np.asarray(c)
                if arr.dtype != dt:
                    # A batch whose host dtype diverges from what
                    # read_columns would return must never be served.
                    self._invalidate_locked()
                    return
                self._buf[name].append(arr)
            self._next_row += batch.num_rows
            self._stage_complete_windows_locked()

    def _invalidate_locked(self) -> None:
        self._valid = False
        _INVALID.inc()
        for w in list(self.windows):
            self._release_locked(w)
        self._buf = {n: [] for n in self.columns}

    def _stage_complete_windows_locked(self) -> None:
        W = self.window_rows
        while True:
            k = -(-self._buf_start // W)  # first window at/after buffer
            if (k + 1) * W > self._next_row:
                return
            # Compact the buffer to single chunks once per staging.
            for name in self.columns:
                if len(self._buf[name]) > 1:
                    self._buf[name] = [np.concatenate(self._buf[name])]
            lo = k * W - self._buf_start
            win_cols = {
                name: self._buf[name][0][lo : lo + W]
                for name in self.columns
            }
            self._stage_window_locked(k, win_cols)
            # Drop everything through the staged window.
            keep_from = (k + 1) * W - self._buf_start
            for name in self.columns:
                self._buf[name] = [self._buf[name][0][keep_from:]]
            self._buf_start = (k + 1) * W

    def _stage_window_locked(self, k: int, win_cols: dict) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from pixie_tpu.ops import codec as _codec

        (axis_name,) = self.mesh.axis_names
        sharding = NamedSharding(self.mesh, P(axis_name))
        total = self.d * self.nblk * self.b
        W = self.window_rows
        use_codec = flags.staging_codec
        min_ratio = float(flags.staging_codec_min_ratio)
        blocks = {}
        nbytes = 0
        wire = 0
        for name, a in win_cols.items():
            flat = np.zeros(total, dtype=a.dtype)
            flat[:W] = a
            payload = None
            if use_codec:
                cp = _codec.plan_codec_local(
                    flat, self.d, self.nblk, self.b, W, min_ratio
                )
                if cp is not None:
                    try:
                        payload = _codec.encode_window(flat, cp, W)
                    except _codec.CodecOverflow:
                        payload = None
            if payload is not None:
                args = _codec.put_payload(self.mesh, payload)
                blocks[name] = _codec.decoder(
                    self.mesh, cp, self.nblk, self.b
                )(*args)
                wire += payload.nbytes
            else:
                blocks[name] = jax.device_put(
                    flat.reshape(self.d, self.nblk, self.b), sharding
                )
                wire += flat.nbytes
            nbytes += flat.nbytes
        win = ResidentWindow(k, k * W, W, blocks, nbytes)
        self.windows[k] = win
        _WINDOWS.inc()
        _WIRE.inc(wire)
        if self._pool is not None:
            self._pool.register_resident(
                ("resident", self.table_name, k), nbytes
            )
        # Ring depth bound: roll the oldest window out.
        cap = max(int(flags.resident_max_windows), 1)
        while len(self.windows) > cap:
            self._release_locked(min(self.windows))

    def _release_locked(self, k: int) -> None:
        self.windows.pop(k, None)
        if self._pool is not None:
            self._pool.release_resident(("resident", self.table_name, k))

    # -- read side (query staging) -------------------------------------------
    def lookup(
        self, start_row: int, rows: int, needed_cols
    ) -> Optional[ResidentWindow]:
        """The resident window covering EXACTLY rows
        [start_row, start_row + rows) with every needed column, or None.
        Only full, aligned windows ever match — misalignment after
        ring-buffer expiry silently degrades to the staging path."""
        W = self.window_rows
        if rows != W or start_row % W != 0:
            return None
        with self._lock:
            if not self._valid:
                return None
            win = self.windows.get(start_row // W)
        if win is None:
            return None
        for name in needed_cols:
            if name not in win.blocks:
                return None
        _HITS.inc()
        return win

    def release_all(self) -> None:
        with self._lock:
            for k in list(self.windows):
                self._release_locked(k)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "table": self.table_name,
                "window_rows": self.window_rows,
                "windows": len(self.windows),
                "resident_rows": len(self.windows) * self.window_rows,
                "bytes": sum(w.nbytes for w in self.windows.values()),
                "valid": self._valid,
                "buffered_rows": self._next_row - self._buf_start,
            }


class ResidentIngestManager:
    """The MeshExecutor's registry of per-table rings."""

    def __init__(self, mesh, block_rows: int, pool=None):
        self.mesh = mesh
        self.block_rows = block_rows
        self.pool = pool
        self._lock = threading.Lock()
        self._rings: dict[str, ResidentRing] = {}

    def enable(self, table) -> Optional[ResidentRing]:
        """Attach a ring to ``table`` (idempotent per table name).
        Returns the ring, or None when the table has no ring-able
        columns."""
        with self._lock:
            ring = self._rings.get(table.name)
            if ring is not None:
                return ring
            ring = ResidentRing(self.mesh, table, self.block_rows, self.pool)
            if not ring.columns:
                return None
            self._rings[table.name] = ring
        table.add_append_listener(ring.on_append)
        return ring

    def ring_for(self, table_name: str) -> Optional[ResidentRing]:
        with self._lock:
            return self._rings.get(table_name)

    def snapshot(self) -> dict:
        with self._lock:
            rings = list(self._rings.values())
        return {r.table_name: r.snapshot() for r in rings}
