"""Device-resident incremental ingest: HBM ring tables fed by appends.

The r13 production posture: telemetry is continuous and queries are
repeated, so a hot table should NEVER cold-stage its recent span — the
ingest loop pays the wire incrementally (compressed, off any query's
critical path) and a query finds the last N windows already in HBM,
staging only the cold tail. Crescando/SharedDB's continuously-resident
operational data, on a TPU.

Mechanics (reusing the r6 windowed layout end to end):

- A ``ResidentRing`` attaches to a Table's append listener. Appends
  buffer host-side until a full **ring window** (``resident_window_rows``
  rows, geometry from ``staging.block_geometry`` — exactly the stream
  plan's) is available, which is then packed in RAW column dtypes,
  codec-encoded (``staging_codec``), transferred, and device-decoded
  into [D, nblk, B] blocks that stay resident.
- Queries over the table stream at the ring's window size, so plan
  window w covers the same absolute rows as ring window
  ``(min_row + w·W) / W``. On a hit the pipeline skips pack+transfer
  entirely and runs a jitted raw→plan CONVERT (ops/codec.py:
  narrow/f32/int-dict computed on device) — bit-identical to the host
  pack, zero wire bytes. Misses (partial tail, pre-ring history,
  post-expiry misalignment) take the normal compressed staging path.
- Ring windows are registered with the ResidencyPool as permanently
  pinned bytes (``register_resident``), so /statusz, the byte
  watermark, and admission headroom all see them; the ring's own depth
  bound (``resident_max_windows``) rolls the oldest window out and
  frees its accounting — the device-side analogue of the table store's
  ring-buffer expiry.

Correctness stance: the ring only ever serves FULL windows whose rows it
observed gap-free in row-id order (a skipped row id — e.g. a listener
attached mid-write race — permanently invalidates the ring, never the
query). Everything else falls back to staging from the host columns.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from pixie_tpu.types import DataType
from pixie_tpu.utils import flags, metrics_registry


def _log_serving():
    import logging

    return logging.getLogger("pixie_tpu.serving")

_M = metrics_registry()
_WINDOWS = _M.counter(
    "resident_ingest_windows_total",
    "Ring windows staged to HBM by the resident-ingest path.",
)
_WIRE = _M.counter(
    "resident_ingest_wire_bytes_total",
    "Bytes the resident-ingest path actually transferred (encoded).",
)
_HITS = _M.counter(
    "resident_window_hits_total",
    "Query stream windows served from HBM-resident ring windows "
    "(pack+transfer skipped).",
)
_INVALID = _M.counter(
    "resident_ring_invalidated_total",
    "Rings permanently invalidated (row-id gap or column mismatch).",
)
_REPLICATED = _M.counter(
    "ring_replicated_windows_total",
    "Ring windows shipped to follower agents over the codec'd wire "
    "(r17, flag ring_replication_factor > 1), by table.",
)
_REPLICA_ADOPTED = _M.counter(
    "ring_replica_adopted_windows_total",
    "Replica windows decoded into a follower's HBM, by table.",
)
_REPLICA_HITS = _M.counter(
    "replica_window_hits_total",
    "Query stream windows served from a REPLICA ring after failover "
    "(pack+transfer skipped on an agent that never owned the table).",
)
_REPLICA_LAGGED = _M.counter(
    "ring_replica_lagged_windows_total",
    "Replica windows NOT adopted (decode failure, geometry mismatch, "
    "or the resident.replica_lag fault site) — the replica falls "
    "behind the leader's watermark and failover queries re-stage those "
    "rows from the table store instead.",
)
_RESTAGED = _M.counter(
    "ring_restaged_windows_total",
    "Ring windows re-staged into HBM from the durable spill after a "
    "restart (r14, flag durable_resident) — recovered without replaying "
    "table appends.",
)
_SPILL_BYTES = _M.gauge(
    "resident_spill_bytes",
    "On-disk bytes of resident-ring spill logs, by table.",
)

# Raw host dtypes the ring can hold, per column DataType (strings ride
# as their table-dictionary int32 codes, matching read_columns).
_RAW_DTYPES = {
    DataType.BOOLEAN: np.dtype(np.bool_),
    DataType.INT64: np.dtype(np.int64),
    DataType.FLOAT64: np.dtype(np.float64),
    DataType.STRING: np.dtype(np.int32),
    DataType.TIME64NS: np.dtype(np.int64),
}


class ResidentWindow:
    __slots__ = ("index", "start_row", "rows", "blocks", "nbytes")

    def __init__(self, index, start_row, rows, blocks, nbytes):
        self.index = index
        self.start_row = start_row
        self.rows = rows
        self.blocks = blocks  # col -> [D, nblk, B] raw-dtype device array
        self.nbytes = nbytes


class ResidentRing:
    """Per-table HBM ring of full append windows in raw column dtypes."""

    def __init__(self, mesh, table, block_rows: int, pool=None):
        from pixie_tpu.parallel.staging import block_geometry

        self.mesh = mesh
        self.table_name = table.name
        # Replication hook (r17, flag ring_replication_factor > 1): set
        # by the owning agent's replicator; called as hook(table_name,
        # k, start_row, rows, wire_cols, latest_k) with the EXACT
        # encoded payloads the leader's own decode consumed — the wire
        # representation is shared, not recomputed. Called under the
        # ring lock: the hook must only enqueue, never block.
        self.replication_hook = None
        self.window_rows = int(flags.resident_window_rows)
        self.d = mesh.devices.size
        self.b, self.nblk = block_geometry(
            self.window_rows, self.d, block_rows
        )
        self._pool = pool
        self._lock = threading.Lock()
        self.columns: dict[str, np.dtype] = {}
        for c in table.relation:
            dt = _RAW_DTYPES.get(c.data_type)
            if dt is not None:
                self.columns[c.name] = dt
        self.windows: dict[int, ResidentWindow] = {}
        self._valid = bool(self.columns)
        # Buffered host rows cover [_buf_start, _next_row).
        self._next_row = table.end_row_id()
        self._buf_start = self._next_row
        self._buf: dict[str, list] = {n: [] for n in self.columns}
        # Durable spill (r14, flags durable_resident + wal_dir): full
        # windows + the partial buffer mirror to a per-table segment
        # log, and a fresh ring over a recovered table re-stages its
        # windows into HBM from disk (no append replay).
        self._spill = None
        self.recovered_windows = 0
        self.spill_corrupt_records = 0
        if self._valid and flags.durable_resident and flags.wal_dir:
            from pixie_tpu.vizier.durability import RingSpill, ring_spill_path

            try:
                self._spill = RingSpill(
                    ring_spill_path(flags.wal_dir, self.table_name)
                )
                with self._lock:
                    self._recover_from_spill_locked(table)
            except Exception:
                import logging

                logging.getLogger("pixie_tpu.serving").exception(
                    "ring spill unavailable for %r (running without "
                    "durability)", self.table_name,
                )
                self._spill = None

    # -- write side (table append listener) ----------------------------------
    def on_append(self, first_row_id: int, batch) -> None:
        from pixie_tpu.table.column import DictColumn

        with self._lock:
            if not self._valid:
                return
            if first_row_id != self._next_row:
                self._invalidate_locked()
                return
            if batch.num_rows == 0:
                return
            chunk = {}
            for name, dt in self.columns.items():
                c = batch.col(name)
                arr = c.codes if isinstance(c, DictColumn) else np.asarray(c)
                if arr.dtype != dt:
                    # A batch whose host dtype diverges from what
                    # read_columns would return must never be served.
                    self._invalidate_locked()
                    return
                chunk[name] = arr
            for name, arr in chunk.items():
                self._buf[name].append(arr)
            self._next_row += batch.num_rows
            if self._spill is not None:
                # Mirror the partial buffer incrementally: a restart
                # recovers buffered-but-unstaged rows too, not only
                # full windows.
                self._spill.record_append(first_row_id, chunk)
            self._stage_complete_windows_locked()

    def _invalidate_locked(self) -> None:
        self._valid = False
        _INVALID.inc()
        for w in list(self.windows):
            self._release_locked(w)
        self._buf = {n: [] for n in self.columns}
        if self._spill is not None:
            self._spill.record_reset()

    def _stage_complete_windows_locked(self) -> None:
        W = self.window_rows
        while True:
            k = -(-self._buf_start // W)  # first window at/after buffer
            if (k + 1) * W > self._next_row:
                return
            # Compact the buffer to single chunks once per staging.
            for name in self.columns:
                if len(self._buf[name]) > 1:
                    self._buf[name] = [np.concatenate(self._buf[name])]
            lo = k * W - self._buf_start
            win_cols = {
                name: self._buf[name][0][lo : lo + W]
                for name in self.columns
            }
            self._stage_window_locked(k, win_cols)
            # Drop everything through the staged window.
            keep_from = (k + 1) * W - self._buf_start
            for name in self.columns:
                self._buf[name] = [self._buf[name][0][keep_from:]]
            self._buf_start = (k + 1) * W
            if self._spill is not None:
                self._spill.record_trim(self._buf_start)
                self._spill.maybe_compact(
                    set(self.windows), self._buf_start
                )
                _SPILL_BYTES.labels(table=self.table_name).set(
                    self._spill.nbytes()
                )

    def _stage_window_locked(
        self, k: int, win_cols: dict, record: bool = True
    ) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from pixie_tpu.ops import codec as _codec

        if record and self._spill is not None:
            # WAL posture: the window's raw host columns hit disk before
            # the HBM transfer, so a crash at any later point recovers it.
            self._spill.record_window(
                k, k * self.window_rows, self.window_rows, win_cols
            )

        axis_name = tuple(self.mesh.axis_names)  # dim0 over every mesh axis
        sharding = NamedSharding(self.mesh, P(axis_name))
        total = self.d * self.nblk * self.b
        W = self.window_rows
        use_codec = flags.staging_codec
        # r22: the ring's encode bar rides the same learned codec-vs-raw
        # rate the cold staging path uses (flag exactly when cold/off).
        from pixie_tpu.parallel.staging import codec_min_ratio

        min_ratio = codec_min_ratio()
        blocks = {}
        nbytes = 0
        wire = 0
        wire_cols = {} if self.replication_hook is not None else None
        for name, a in win_cols.items():
            flat = np.zeros(total, dtype=a.dtype)
            flat[:W] = a
            payload = None
            if use_codec:
                cp = _codec.plan_codec_local(
                    flat, self.d, self.nblk, self.b, W, min_ratio
                )
                if cp is not None:
                    try:
                        payload = _codec.encode_window(flat, cp, W)
                    except _codec.CodecOverflow:
                        payload = None
            if payload is not None:
                args = _codec.put_payload(self.mesh, payload)
                blocks[name] = _codec.decoder(
                    self.mesh, cp, self.nblk, self.b
                )(*args)
                wire += payload.nbytes
                if wire_cols is not None:
                    wire_cols[name] = ("codec", payload)
            else:
                blocks[name] = jax.device_put(
                    flat.reshape(self.d, self.nblk, self.b), sharding
                )
                wire += flat.nbytes
                if wire_cols is not None:
                    wire_cols[name] = ("raw", flat)
            nbytes += flat.nbytes
        win = ResidentWindow(k, k * W, W, blocks, nbytes)
        self.windows[k] = win
        _WINDOWS.inc()
        _WIRE.inc(wire)
        if wire_cols is not None and record:
            # Ship the SAME encoded payloads to followers (r17): the
            # replica pays the compressed wire, never a re-encode.
            try:
                self.replication_hook(
                    self.table_name, k, k * W, W, wire_cols, k
                )
                _REPLICATED.inc(table=self.table_name)
            except Exception:
                _log_serving().exception(
                    "ring replication hook failed (ignored)"
                )
        if self._pool is not None:
            self._pool.register_resident(
                ("resident", self.table_name, k), nbytes
            )
        # Ring depth bound: roll the oldest window out.
        cap = max(int(flags.resident_max_windows), 1)
        while len(self.windows) > cap:
            self._release_locked(min(self.windows))

    def _release_locked(self, k: int) -> None:
        self.windows.pop(k, None)
        if self._pool is not None:
            self._pool.release_resident(("resident", self.table_name, k))
        if self._spill is not None:
            self._spill.record_release(k)

    def _recover_from_spill_locked(self, table) -> None:
        """Restart recovery: re-stage full windows into HBM from the
        spill and restore the partial buffer — without replaying table
        appends. Everything is validated against the recovered table
        (row ranges, column set, dtypes); anything questionable is
        dropped, never served (queries fall back to the staging path,
        bit-identical either way)."""
        state = self._spill.recover()
        self.spill_corrupt_records = state["corrupt"]
        table_end = table.end_row_id()
        W = self.window_rows
        restaged = 0
        for k in sorted(state["windows"]):
            start_row, rows, cols = state["windows"][k]
            if rows != W or start_row != k * W or start_row + rows > table_end:
                continue  # geometry drift, or rows the table lost
            if set(cols) != set(self.columns) or any(
                np.asarray(cols[n]).dtype != dt or len(cols[n]) != W
                for n, dt in self.columns.items()
            ):
                continue
            self._stage_window_locked(
                k,
                {n: np.asarray(cols[n]) for n in self.columns},
                record=False,  # already on disk
            )
            restaged += 1
        self.recovered_windows = restaged
        if restaged:
            _RESTAGED.inc(restaged)
        # Partial buffer: usable only when the recorded chunks are
        # gap-free and reach EXACTLY the table's end (the ring's
        # observed-every-row contract, re-established across restart).
        chunks = state["buf"]
        bs = state["buf_start"]
        cov_start = chunks[0][0] if chunks else None
        cov_end = cov_start
        ok = bool(chunks)
        for first_row, cols in chunks:
            rows = len(next(iter(cols.values()))) if cols else 0
            if first_row != cov_end or set(cols) != set(self.columns) or any(
                np.asarray(cols[n]).dtype != dt
                for n, dt in self.columns.items()
            ):
                ok = False
                break
            cov_end = first_row + rows
        if ok and cov_end == table_end:
            if bs is None:
                bs = cov_start
            # A crash between a window record and its trim record leaves
            # a stale buf_start: never re-buffer rows a restaged window
            # already covers.
            if restaged:
                bs = max(bs, (max(self.windows) + 1) * W)
            bs = max(bs, cov_start)
            self._buf = {
                name: [
                    np.concatenate(
                        [np.asarray(c[name]) for _, c in chunks]
                    )[bs - cov_start :]
                ]
                for name in self.columns
            }
            self._buf_start = bs
            self._next_row = table_end
        elif chunks:
            _log_serving().warning(
                "ring %r: discarding unrecoverable spill buffer "
                "(coverage [%s, %s) vs table end %d)",
                self.table_name, cov_start, cov_end, table_end,
            )
        if self._spill is not None:
            # Persist exactly the adopted state: anything recovery
            # rejected (stale geometry, rows this table doesn't have,
            # corrupt payloads) is compacted off disk NOW, so it can
            # never resurrect on a later restart against a table whose
            # rows it no longer matches.
            self._spill.maybe_compact(
                set(self.windows), self._buf_start, force=True
            )
            _SPILL_BYTES.labels(table=self.table_name).set(
                self._spill.nbytes()
            )

    # -- read side (query staging) -------------------------------------------
    def lookup(
        self, start_row: int, rows: int, needed_cols
    ) -> Optional[ResidentWindow]:
        """The resident window covering EXACTLY rows
        [start_row, start_row + rows) with every needed column, or None.
        Only full, aligned windows ever match — misalignment after
        ring-buffer expiry silently degrades to the staging path."""
        W = self.window_rows
        if rows != W or start_row % W != 0:
            return None
        with self._lock:
            if not self._valid:
                return None
            win = self.windows.get(start_row // W)
        if win is None:
            return None
        for name in needed_cols:
            if name not in win.blocks:
                return None
        _HITS.inc()
        return win

    def release_all(self) -> None:
        with self._lock:
            for k in list(self.windows):
                self._release_locked(k)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "table": self.table_name,
                "window_rows": self.window_rows,
                "windows": len(self.windows),
                "resident_rows": len(self.windows) * self.window_rows,
                "bytes": sum(w.nbytes for w in self.windows.values()),
                "valid": self._valid,
                "buffered_rows": self._next_row - self._buf_start,
                "recovered_windows": self.recovered_windows,
                "spill_bytes": (
                    self._spill.nbytes() if self._spill is not None else 0
                ),
            }


class ReplicaRing:
    """A follower agent's HBM mirror of another agent's ResidentRing
    (r17, flag ``ring_replication_factor`` > 1).

    Windows arrive as the leader's EXACT wire representation (codec
    payload or raw flat column) and decode device-side into the same
    [D, nblk, B] raw-dtype blocks a local ring would hold — so a
    failover query on this agent finds the hot span already resident
    (wire ~ 0) and ``lookup`` serves it bit-identically to the leader.
    The replica never observes table appends; its freshness is bounded
    by the leader's advertised watermark (``leader_latest``), and any
    window it lacks — decode failure, geometry mismatch, the
    ``resident.replica_lag`` fault site, or plain lag — silently falls
    back to staging from the table store (the ring-miss path queries
    already take)."""

    def __init__(self, mesh, table_name: str, window_rows: int,
                 block_rows: int, pool=None):
        from pixie_tpu.parallel.staging import block_geometry

        self.mesh = mesh
        self.table_name = table_name
        self.window_rows = int(window_rows)
        self.d = mesh.devices.size
        self.b, self.nblk = block_geometry(
            self.window_rows, self.d, block_rows
        )
        self._pool = pool
        self._lock = threading.Lock()
        self.windows: dict[int, ResidentWindow] = {}
        self.leader_latest = -1  # highest window index the leader staged

    def adopt_window(
        self, k: int, start_row: int, rows: int, wire_cols: dict,
        latest_k: int,
    ) -> bool:
        """Decode one replicated window into HBM. Returns False (and
        counts the lag) when the window cannot be adopted — the replica
        stays behind and correctness rides the staging fallback."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from pixie_tpu.ops import codec as _codec
        from pixie_tpu.utils import faults

        with self._lock:
            self.leader_latest = max(self.leader_latest, int(latest_k))
            W = self.window_rows
            if rows != W or start_row != k * W:
                _REPLICA_LAGGED.inc(table=self.table_name)
                return False
            if faults.ACTIVE and faults.fires("resident.replica_lag"):
                # A dropped/late replication frame: the replica is now
                # behind the leader's watermark for this window.
                _REPLICA_LAGGED.inc(table=self.table_name)
                return False
            axis_name = tuple(self.mesh.axis_names)  # dim0 over every mesh axis
            sharding = NamedSharding(self.mesh, P(axis_name))
            shard_len = self.nblk * self.b
            blocks = {}
            nbytes = 0
            try:
                for name, (kind, data) in wire_cols.items():
                    if kind == "codec":
                        cp = data.plan
                        if cp.d != self.d or cp.shard_len != shard_len:
                            raise ValueError("replica geometry mismatch")
                        args = _codec.put_payload(self.mesh, data)
                        blocks[name] = _codec.decoder(
                            self.mesh, cp, self.nblk, self.b
                        )(*args)
                        nbytes += cp.block_nbytes()
                    else:
                        flat = np.asarray(data)
                        if flat.size != self.d * shard_len:
                            raise ValueError("replica geometry mismatch")
                        blocks[name] = jax.device_put(
                            flat.reshape(self.d, self.nblk, self.b),
                            sharding,
                        )
                        nbytes += flat.nbytes
            except Exception:
                _log_serving().exception(
                    "replica window %d of %r not adopted",
                    k, self.table_name,
                )
                _REPLICA_LAGGED.inc(table=self.table_name)
                return False
            self.windows[k] = ResidentWindow(k, start_row, rows, blocks,
                                             nbytes)
            _REPLICA_ADOPTED.inc(table=self.table_name)
            if self._pool is not None:
                self._pool.register_resident(
                    ("replica", self.table_name, k), nbytes
                )
            cap = max(int(flags.resident_max_windows), 1)
            while len(self.windows) > cap:
                self._release_locked(min(self.windows))
            return True

    def _release_locked(self, k: int) -> None:
        self.windows.pop(k, None)
        if self._pool is not None:
            self._pool.release_resident(("replica", self.table_name, k))

    def release_all(self) -> None:
        with self._lock:
            for k in list(self.windows):
                self._release_locked(k)

    # -- read side: same contract as ResidentRing.lookup ---------------------
    def lookup(
        self, start_row: int, rows: int, needed_cols
    ) -> Optional[ResidentWindow]:
        W = self.window_rows
        if rows != W or start_row % W != 0:
            return None
        with self._lock:
            win = self.windows.get(start_row // W)
        if win is None:
            return None
        for name in needed_cols:
            if name not in win.blocks:
                return None
        _REPLICA_HITS.inc()
        return win

    def snapshot(self) -> dict:
        with self._lock:
            latest = max(self.windows) if self.windows else -1
            # Lag counts every window inside the leader's retention
            # span this replica lacks — holes from dropped replication
            # frames included, not just a short tail.
            cap = max(int(flags.resident_max_windows), 1)
            span_start = max(self.leader_latest - cap + 1, 0)
            lag = sum(
                1
                for k in range(span_start, self.leader_latest + 1)
                if k not in self.windows
            )
            return {
                "table": self.table_name,
                "window_rows": self.window_rows,
                "windows": len(self.windows),
                "latest": latest,
                "leader_latest": self.leader_latest,
                "lag": lag,
                "bytes": sum(w.nbytes for w in self.windows.values()),
            }


class ResidentIngestManager:
    """The MeshExecutor's registry of per-table rings — owned
    (append-fed) rings plus adopted replica rings (r17)."""

    def __init__(self, mesh, block_rows: int, pool=None):
        self.mesh = mesh
        self.block_rows = block_rows
        self.pool = pool
        self._lock = threading.Lock()
        self._rings: dict[str, ResidentRing] = {}
        self._replicas: dict[str, ReplicaRing] = {}
        # Replication hook applied to rings created later (r17).
        self._replication_hook = None

    def enable(self, table) -> Optional[ResidentRing]:
        """Attach a ring to ``table`` (idempotent per table name).
        Returns the ring, or None when the table has no ring-able
        columns."""
        with self._lock:
            ring = self._rings.get(table.name)
            if ring is not None:
                return ring
            ring = ResidentRing(self.mesh, table, self.block_rows, self.pool)
            if not ring.columns:
                return None
            ring.replication_hook = self._replication_hook
            self._rings[table.name] = ring
        table.add_append_listener(ring.on_append)
        return ring

    def set_replication_hook(self, hook) -> None:
        """Install the leader-side replication hook on every owned ring
        (current and future)."""
        with self._lock:
            self._replication_hook = hook
            for ring in self._rings.values():
                ring.replication_hook = hook

    def adopt_replica_window(
        self, table_name: str, window_rows: int, k: int, start_row: int,
        rows: int, wire_cols: dict, latest_k: int,
    ) -> bool:
        """Follower side: decode a replicated window into this agent's
        HBM (creating the table's ReplicaRing on first sight)."""
        with self._lock:
            rep = self._replicas.get(table_name)
            if rep is None or rep.window_rows != int(window_rows):
                if rep is not None:
                    rep.release_all()
                rep = ReplicaRing(
                    self.mesh, table_name, window_rows, self.block_rows,
                    self.pool,
                )
                self._replicas[table_name] = rep
        return rep.adopt_window(k, start_row, rows, wire_cols, latest_k)

    def ring_for(self, table_name: str):
        """The table's serving ring: the owned (append-fed) ring when
        one exists, else an adopted replica ring (r17 failover — the
        agent never owned the table but its HBM already holds the hot
        windows)."""
        with self._lock:
            return self._rings.get(table_name) or self._replicas.get(
                table_name
            )

    def replica_for(self, table_name: str) -> Optional[ReplicaRing]:
        with self._lock:
            return self._replicas.get(table_name)

    def replica_snapshot(self) -> dict:
        with self._lock:
            reps = list(self._replicas.values())
        return {r.table_name: r.snapshot() for r in reps}

    def snapshot(self) -> dict:
        with self._lock:
            rings = list(self._rings.values())
        return {r.table_name: r.snapshot() for r in rings}
